"""Range planning for Parquet reads: exact ranges, coalescing, readahead.

A Parquet footer names the exact byte extent of everything a projected +
filtered read will touch — column-chunk page runs, page-index structures,
bloom filters. Production readers (pyarrow's dataset scanner, parquet-mr's
Hadoop input streams) exploit that: plan the ranges up front, merge
near-neighbors into one transport request, fetch batches ahead of decode.
This module is that layer:

  plan_ranges()    FileMetaData + (row groups, column paths) -> the exact
                   (offset, length) list the read needs; nothing else is
                   ever fetched (projection efficiency is measurable:
                   io_bytes_read_total vs the file size)
  coalesce()       sorted ranges whose gap is under a threshold merge into
                   one run (default 64 KiB — around the point where a
                   second ~ms-latency range GET costs more than re-reading
                   the gap bytes); runs are capped so one merge never
                   becomes an unbounded single read
  fetch_ranges()   the one choke point reads go through: block-cache
                   lookup, coalesce, batched source.read_ranges under the
                   io.read trace stage, member slicing, cache fill
  Readahead        a bounded scheduler on the dedicated pqt-io pool:
                   fetches planned ranges into a BlockCache ahead of
                   decode, with a budget on in-flight bytes; over-budget
                   schedules are DROPPED, not queued (readahead is
                   advisory — decode stays correct reading through the
                   cache-missing path). The pool is distinct from the
                   prepare ("pqt-host") and dataset ("pqt-data") pools so
                   no layer can deadlock waiting on its own executor.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..utils import metrics as _metrics
from ..obs.pool import instrumented_submit
from ..utils.trace import stage
from .autotune import io_tuner, profile_key

__all__ = [
    "DEFAULT_COALESCE_GAP",
    "DEFAULT_MAX_RUN",
    "plan_ranges",
    "coalesce",
    "fetch_ranges",
    "Readahead",
    "io_pool",
]

# Merge ranges separated by less than this many bytes (64 KiB: past it, on
# a ~1 GB/s local disk the wasted gap read costs about what a fresh syscall
# does; on a ~ms-latency object store the break-even gap is far LARGER —
# tune up via coalesce_gap/PQT_IO_GAP for remote sources).
DEFAULT_COALESCE_GAP = 64 << 10

# Never merge into a single read larger than this: one run must not hold
# the whole transport (or the readahead budget) hostage.
DEFAULT_MAX_RUN = 16 << 20


def plan_ranges(
    meta,
    *,
    row_groups=None,
    columns=None,
    page_index: bool = False,
    blooms: bool = False,
) -> list[tuple[int, int]]:
    """The exact (offset, length) byte ranges a read of `meta` needs.

    `row_groups` is an iterable of group indices (None = all); `columns` a
    set/collection of leaf path TUPLES (None = all). `page_index` adds each
    selected chunk's ColumnIndex/OffsetIndex extents, `blooms` its bloom
    filter (when the footer records a length — headers-only blooms have no
    planned extent and fall back to the reader's peek path). Chunks with
    unusable metadata are skipped here; the decode path reports the precise
    typed error."""
    from ..core.chunk import ChunkError, chunk_byte_range

    groups = meta.row_groups or []
    indices = range(len(groups)) if row_groups is None else row_groups
    selected = None if columns is None else {tuple(p) for p in columns}
    out: list[tuple[int, int]] = []
    for gi in indices:
        if not 0 <= gi < len(groups):
            continue
        for cc in groups[gi].columns or []:
            md = cc.meta_data
            if md is None:
                continue
            path = tuple(md.path_in_schema or [])
            if selected is not None and path not in selected:
                continue
            try:
                off, total = chunk_byte_range(cc)
            except ChunkError:
                continue
            out.append((off, total))
            if page_index:
                if cc.column_index_offset and cc.column_index_length:
                    out.append((cc.column_index_offset, cc.column_index_length))
                if cc.offset_index_offset and cc.offset_index_length:
                    out.append((cc.offset_index_offset, cc.offset_index_length))
            if blooms and md.bloom_filter_offset and md.bloom_filter_length:
                out.append((md.bloom_filter_offset, md.bloom_filter_length))
    return out


def coalesce(
    ranges,
    gap: int = DEFAULT_COALESCE_GAP,
    max_run: int = DEFAULT_MAX_RUN,
) -> list[tuple[int, int, list[tuple[int, int]]]]:
    """Merge (offset, length) ranges into batched read runs.

    Returns [(run_offset, run_length, [member ranges...])], sorted; members
    keep their original identity so fetch_ranges can slice each requested
    range back out of its run. Ranges merge when the gap between them is
    <= `gap` bytes AND the merged run stays <= `max_run` (overlapping or
    duplicate ranges always merge — reading the same bytes twice in one
    batch is pure waste)."""
    if not ranges:
        return []
    ordered = sorted(set((int(o), int(n)) for o, n in ranges if n > 0))
    if not ordered:
        return []
    runs: list[tuple[int, int, list]] = []
    run_off, run_len = ordered[0]
    members = [ordered[0]]
    for off, n in ordered[1:]:
        end = run_off + run_len
        new_end = max(end, off + n)
        # overlapping ranges ALWAYS merge (fetching shared bytes twice in
        # one batch is pure waste, whatever the run cap says)
        if off < end or (off - end <= gap and new_end - run_off <= max_run):
            run_len = new_end - run_off
            members.append((off, n))
        else:
            runs.append((run_off, run_len, members))
            run_off, run_len, members = off, n, [(off, n)]
    runs.append((run_off, run_len, members))
    _metrics.inc("io_coalesce_ranges_total", len(ordered))
    _metrics.inc("io_coalesce_runs_total", len(runs))
    return runs


def fetch_ranges(
    source,
    ranges,
    *,
    cache=None,
    gap: int = DEFAULT_COALESCE_GAP,
    max_run: int = DEFAULT_MAX_RUN,
) -> dict:
    """Fetch every (offset, length) range; returns {(offset, length): buf}.

    The read choke point: cache-satisfied ranges never touch the source;
    the rest coalesce (io.coalesce stage) into batched read_ranges calls
    (io.read stage, byte volume billed) and fill the cache. Buffers for
    members of one run are zero-copy memoryview slices of the run buffer;
    cached entries are bytes.

    `gap="auto"` resolves through the process IOTuner's profile for this
    source's transport (io/autotune.py) — 64 KiB until the transport has
    demonstrably remote latency, MiB-scale after. Every batched read here
    also FEEDS that tuner (latency + achieved bandwidth), whichever gap
    was used, so opting into "auto" anywhere benefits from observations
    made everywhere."""
    if gap == "auto":
        gap = io_tuner().gap_for(source.source_id)
    out: dict = {}
    missing = []
    sid = source.source_id if cache is not None else None
    for off, n in ranges:
        key = (int(off), int(n))
        if key in out:
            continue
        if cache is not None:
            hit = cache.get(sid, key[0], key[1])
            if hit is not None:
                out[key] = hit
                continue
        missing.append(key)
    if not missing:
        return out
    with stage("io.coalesce"):
        runs = coalesce(missing, gap=gap, max_run=max_run)
    run_spans = [(off, n) for off, n, _m in runs]
    total = sum(n for _o, n in run_spans)
    t0 = time.perf_counter()
    with stage("io.read", total):
        bufs = source.read_ranges(run_spans)
    # wall/runs is only an honest per-request latency when the runs were
    # SEQUENTIAL — remote sources fan read_ranges out concurrently and
    # feed the tuner per request themselves (HttpSource._observe), so
    # only local-profiled transports are observed from here
    if profile_key(source.source_id) == "local":
        io_tuner().observe(
            source.source_id, total, time.perf_counter() - t0, len(run_spans)
        )
    for (run_off, _run_len, members), buf in zip(runs, bufs):
        mv = memoryview(buf)
        for off, n in members:
            piece = mv[off - run_off : off - run_off + n]
            out[(off, n)] = piece
            if cache is not None:
                cache.put(sid, off, n, piece)
    return out


# -- the dedicated IO pool ----------------------------------------------------

_pool: ThreadPoolExecutor | None = None
_pool_lock = threading.Lock()


def io_pool() -> ThreadPoolExecutor:
    """The process-wide readahead executor ("pqt-io", PQT_IO_THREADS or
    min(cpu, 8) workers). Deliberately its OWN pool: readahead tasks block
    on source latency, and parking them in the prepare or dataset pools
    would let slow IO starve decode (or deadlock a pool waiting on work it
    must itself run)."""
    global _pool
    with _pool_lock:
        if _pool is None:
            env = os.environ.get("PQT_IO_THREADS")
            workers = int(env) if env else min(os.cpu_count() or 1, 8)
            _pool = ThreadPoolExecutor(
                max_workers=max(1, workers), thread_name_prefix="pqt-io"
            )
        return _pool


class Readahead:
    """Bounded readahead: fetch planned ranges into a BlockCache ahead of
    decode on the pqt-io pool, holding at most `budget_bytes` in flight.

    schedule() is fire-and-forget and advisory: when the budget is full the
    request is dropped (counted io_readahead_dropped_total) rather than
    queued — decode reads through fetch_ranges either way, so a dropped
    readahead costs latency, never correctness. Fetch failures are likewise
    swallowed (counted io_readahead_errors_total): the decode path will hit
    the same fault with its full typed-error context."""

    def __init__(self, cache, *, budget_bytes: int = 64 << 20,
                 gap: int = DEFAULT_COALESCE_GAP, autotune: bool = False):
        if cache is None:
            raise ValueError("Readahead needs a BlockCache to fetch into")
        self.cache = cache
        self.budget_bytes = int(budget_bytes)
        self.gap = gap
        # autotune=True consults the IOTuner per schedule(): the in-flight
        # budget GROWS to the transport's recommended readahead (deep for
        # high-latency stores, the configured budget otherwise), and
        # fetches coalesce at the tuned gap. The configured budget_bytes
        # stays the floor — autotune only ever deepens readahead.
        self.autotune = bool(autotune)
        if autotune and gap == DEFAULT_COALESCE_GAP:
            self.gap = "auto"
        self._lock = threading.Lock()
        self._inflight = 0
        self._futures: list = []
        self._closed = False

    def _budget_for(self, source_or_path) -> int:
        if not self.autotune:
            return self.budget_bytes
        key = (
            source_or_path
            if isinstance(source_or_path, (str, os.PathLike))
            else source_or_path.source_id
        )
        return max(
            self.budget_bytes, io_tuner().readahead_for(os.fspath(key))
        )

    def schedule(self, source_or_path, ranges) -> bool:
        """Queue a background fetch of `ranges` from a ByteSource or a local
        path (opened and closed inside the task). True when accepted."""
        total = sum(int(n) for _o, n in ranges)
        if total <= 0:
            return False
        budget = self._budget_for(source_or_path)
        with self._lock:
            if self._closed:
                return False
            if self._inflight + total > budget:
                _metrics.inc("io_readahead_dropped_total")
                return False
            self._inflight += total
            self._futures = [f for f in self._futures if not f.done()]
            self._futures.append(
                instrumented_submit(io_pool(), self._fetch, source_or_path,
                                    list(ranges), total, pool="pqt-io")
            )
        return True

    def _fetch(self, source_or_path, ranges, total) -> None:
        from .source import open_source

        try:
            # paths open through open_source so readahead reads inherit the
            # same resilience policy (breaker/retry/hedge) decode does — a
            # blacked-out source must not keep burning pqt-io on fetches
            # decode would fast-fail
            if isinstance(source_or_path, (str, os.PathLike)):
                src, owned = open_source(os.fspath(source_or_path))
            else:
                src, owned = source_or_path, False
            try:
                fetch_ranges(src, ranges, cache=self.cache, gap=self.gap)
                _metrics.inc("io_readahead_fetched_total")
            finally:
                if owned:
                    src.close()
        except Exception:  # noqa: BLE001 — advisory path, decode re-raises
            _metrics.inc("io_readahead_errors_total")
        finally:
            with self._lock:
                self._inflight -= total

    def drain(self) -> None:
        """Block until every accepted fetch has finished (tests/benches)."""
        with self._lock:
            futs = list(self._futures)
        for f in futs:
            if not f.cancelled():
                f.exception()  # wait; errors were already counted in-task

    def close(self, wait: bool = False) -> None:
        """Stop accepting schedules and cancel not-yet-started fetches.
        Running fetches finish on their own (they hold no dataset state);
        wait=True blocks for them too."""
        with self._lock:
            self._closed = True
            futs = list(self._futures)
        for f in futs:
            f.cancel()
        if wait:
            self.drain()

"""Pluggable byte sources: where a Parquet file's bytes actually come from.

The decode stack above this layer (reader/planner/cache) never touches a
file handle directly — it speaks the small ByteSource contract:

    size()                    total byte length
    read_at(offset, n)        exactly n bytes at offset (or raise)
    read_ranges([(o, n)...])  batched positional reads, one result per range
    source_id                 stable identity for cache keys
    close()

That is the seam production readers interpose on: the reference reader (and
the original FileReader here) assumed one cheap seekable local handle guarded
by a position lock, which serializes a 16-thread prepare pool and models an
object store not at all. Concrete sources:

  LocalFileSource    lock-free os.pread on a local fd — no shared cursor,
                     so concurrent chunk preparers never contend
  MemorySource       an in-memory buffer (zero-copy slicing)
  FileObjectSource   adapter over an arbitrary seekable file-like (BytesIO,
                     sockets wrapped in a buffer, ...) — the compatibility
                     lane for FileReader(file_obj)
  RetryingSource     wraps any source with a deadline + capped exponential
                     backoff + jitter retry ladder for transient faults
                     (the remote-object-store shape); exhausting the budget
                     raises the typed SourceError

Every CONCRETE source feeds the always-on io_bytes_read_total /
io_read_calls_total counters (wrappers don't double-count); RetryingSource
adds io_retries_total{reason=...} per failed attempt. The seeded fault
injector lives in parquet_tpu.testing.flaky (FlakySource).
"""

from __future__ import annotations

import errno as _errno
import io as _io
import os
import random
import threading
import time
from pathlib import Path

from ..obs.log import log_event as _log_event
from ..utils import metrics as _metrics
from ..utils import trace as _trace

__all__ = [
    "ByteSource",
    "SourceError",
    "LocalFileSource",
    "MemorySource",
    "FileObjectSource",
    "RetryingSource",
    "SourceFile",
    "open_source",
]


class SourceError(OSError):
    """Terminal IO failure of a byte source: the read is not satisfiable
    (range past EOF, retry budget exhausted, source closed, circuit
    breaker open). An OSError subclass so callers treating IO failures
    generically (the dataset layer's skip policy) need no new clause — but
    typed, so tests can pin that the retry ladder converted a transient
    fault storm into exactly this, never a raw errno leak. `code` is an
    optional stable discriminator ("breaker_open") layers above branch on
    — the serve executor turns breaker fast-fails into 503s instead of
    422s with it."""

    def __init__(self, *args, code: str | None = None):
        super().__init__(*args)
        self.code = code


def _count_read(nbytes: int) -> None:
    # concrete sources only — wrappers delegate and must not double-count
    _metrics.inc("io_bytes_read_total", nbytes)
    _metrics.inc("io_read_calls_total")


class ByteSource:
    """Base contract for byte sources (see module docstring).

    Subclasses implement size() and read_at(); read_ranges() has a
    loop-of-read_at default that batching sources (HTTP multi-range,
    io_uring) override. Sources are context managers; close() is
    idempotent and a no-op by default."""

    def size(self) -> int:
        raise NotImplementedError

    def read_at(self, offset: int, n: int) -> bytes:
        """Exactly `n` bytes at `offset`. A source that cannot deliver them
        (EOF inside the range, transport failure) raises — short returns
        are a contract violation RetryingSource guards against."""
        raise NotImplementedError

    def read_ranges(self, ranges) -> list:
        """One buffer per (offset, n) range, in order."""
        return [self.read_at(off, n) for off, n in ranges]

    @property
    def source_id(self) -> str:
        """Stable identity for (source_id, offset, len) cache keys. Two
        sources over the SAME underlying bytes should agree (LocalFileSource
        keys on inode+size+mtime so reopened paths share cache entries and
        rewritten files never hit stale ones)."""
        return f"{type(self).__name__}:{id(self):#x}"

    def generation(self):
        """A hashable content-generation signature, or None when the
        source has no cheaper validity check than its bytes. Remote
        sources return (size, ETag) — what lets the FooterCache validate
        a URL-keyed footer the way it stats a local path. Wrapper sources
        delegate to their inner source."""
        return None

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# POSIX-only; non-POSIX platforms fall back to a lock-guarded lseek+read
_PREAD = getattr(os, "pread", None)


class LocalFileSource(ByteSource):
    """A local file read with positionless os.pread — no shared cursor, no
    lock, so any number of threads read concurrently (the seek/read+position
    -restore dance of the original reader is gone, not just guarded).
    Platforms without os.pread serialize on a per-source lock instead."""

    def __init__(self, path):
        self._path = os.fspath(path)
        self._fd = os.open(self._path, os.O_RDONLY)
        self._lock = None if _PREAD is not None else threading.Lock()
        st = os.fstat(self._fd)
        self._size = st.st_size
        # identity pins the CONTENT, not just the name: a rewritten file
        # (new mtime/size/inode) can never serve another generation's blocks
        self._id = (
            f"file:{os.path.realpath(self._path)}"
            f":{st.st_ino}:{st.st_size}:{st.st_mtime_ns}"
        )
        self._closed = False

    @property
    def path(self) -> str:
        return self._path

    @property
    def source_id(self) -> str:
        return self._id

    def size(self) -> int:
        return self._size

    def read_at(self, offset: int, n: int) -> bytes:
        if offset < 0 or n < 0:
            raise ValueError(f"read_at({offset}, {n}): negative offset/length")
        if n == 0:
            return b""
        if self._closed:
            raise SourceError(f"source closed: {self._path}")
        if offset + n > self._size:
            raise SourceError(
                f"read past end of {self._path}: "
                f"[{offset}, {offset + n}) > {self._size}"
            )
        parts = []
        pos, want = offset, n
        while want:
            if _PREAD is not None:
                buf = _PREAD(self._fd, want, pos)
            else:
                with self._lock:
                    os.lseek(self._fd, pos, os.SEEK_SET)
                    buf = os.read(self._fd, want)
            if not buf:
                raise SourceError(
                    f"short read from {self._path}: wanted {n} at {offset}, "
                    f"got {n - want}"
                )
            parts.append(buf)
            pos += len(buf)
            want -= len(buf)
        _count_read(n)
        return parts[0] if len(parts) == 1 else b"".join(parts)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            os.close(self._fd)


class MemorySource(ByteSource):
    """An in-memory byte buffer as a source (tests, pre-staged footers,
    tiny sidecar files)."""

    def __init__(self, data, source_id: str | None = None):
        self._mv = memoryview(data)
        self._id = source_id or f"mem:{id(self):#x}:{len(self._mv)}"

    @property
    def source_id(self) -> str:
        return self._id

    def size(self) -> int:
        return len(self._mv)

    def read_at(self, offset: int, n: int) -> bytes:
        if offset < 0 or n < 0:
            raise ValueError(f"read_at({offset}, {n}): negative offset/length")
        if offset + n > len(self._mv):
            raise SourceError(
                f"read past end of memory source: [{offset}, {offset + n}) "
                f"> {len(self._mv)}"
            )
        _count_read(n)
        return bytes(self._mv[offset : offset + n])


class FileObjectSource(ByteSource):
    """Adapter over an arbitrary seekable binary file-like object.

    Prefers positionless os.pread when the object exposes a real fd;
    otherwise falls back to lock-guarded seek+read. No position restore:
    nothing above this layer shares the object's cursor anymore (every
    consumer reads through read_at), so saving and re-seeking the old
    position — the original reader's lock dance — has nothing left to
    protect."""

    def __init__(self, f):
        self._f = f
        self._lock = threading.Lock()
        try:
            self._fd = f.fileno()
        except (AttributeError, OSError, _io.UnsupportedOperation):
            self._fd = None
        with self._lock:
            pos = f.tell()
            self._size = f.seek(0, _io.SEEK_END)
            f.seek(pos)

    def size(self) -> int:
        return self._size

    def read_at(self, offset: int, n: int) -> bytes:
        if offset < 0 or n < 0:
            raise ValueError(f"read_at({offset}, {n}): negative offset/length")
        if n == 0:
            return b""
        if offset + n > self._size:
            raise SourceError(
                f"read past end of file object: [{offset}, {offset + n}) "
                f"> {self._size}"
            )
        if self._fd is not None and _PREAD is not None:
            try:
                buf = _PREAD(self._fd, n, offset)
                if len(buf) == n:
                    _count_read(n)
                    return buf
            except OSError:
                pass  # e.g. a pipe-backed fd: fall through to seek+read
        with self._lock:
            self._f.seek(offset)
            buf = self._f.read(n)
        if len(buf) != n:
            raise SourceError(
                f"short read from file object: wanted {n} at {offset}, "
                f"got {len(buf)}"
            )
        _count_read(n)
        return buf


_TRANSIENT_DEFAULT = (OSError, TimeoutError)


class RetryingSource(ByteSource):
    """Retry ladder for transient source faults (the remote-read shape).

    Each read gets up to `attempts` tries under a wall-clock `deadline_s`;
    failed attempts back off exponentially from `base_delay_s`, capped at
    `max_delay_s`, with multiplicative jitter (`jitter`, 0..1) so a fleet
    of readers retrying the same stalled store doesn't synchronize into
    waves. A short return from the inner source (a contract violation real
    transports do commit) retries like an error. Every failed attempt
    counts io_retries_total{reason=<errno name | short_read | exception
    type>}; exhausting the budget raises SourceError chained to the last
    underlying failure.

    `sleep` is injectable so tests sweep the full ladder in microseconds;
    `seed` pins the jitter stream for reproducible schedules."""

    def __init__(
        self,
        inner: ByteSource,
        *,
        attempts: int = 4,
        deadline_s: float = 30.0,
        base_delay_s: float = 0.01,
        max_delay_s: float = 2.0,
        jitter: float = 0.25,
        retry_on: tuple = _TRANSIENT_DEFAULT,
        sleep=time.sleep,
        seed: int | None = None,
    ):
        if attempts < 1:
            raise ValueError("attempts must be >= 1")
        self.inner = inner
        self.attempts = attempts
        self.deadline_s = deadline_s
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.jitter = jitter
        self.retry_on = retry_on
        self._sleep = sleep
        self._rng = random.Random(seed)

    @property
    def source_id(self) -> str:
        return self.inner.source_id

    def generation(self):
        gen = getattr(self.inner, "generation", None)
        return gen() if gen is not None else None

    def size(self) -> int:
        return self.inner.size()

    def _reason(self, exc) -> str:
        if isinstance(exc, OSError) and exc.errno:
            return _errno.errorcode.get(exc.errno, f"errno_{exc.errno}")
        return type(exc).__name__

    def read_at(self, offset: int, n: int) -> bytes:
        t0 = time.monotonic()
        last: Exception | None = None
        reason = "unknown"
        for attempt in range(self.attempts):
            try:
                buf = self.inner.read_at(offset, n)
            except ValueError:
                raise  # caller bug (negative range), not a transport fault
            except self.retry_on as e:
                # a SourceError from the inner source is TERMINAL (past-EOF,
                # source closed, a nested ladder's exhausted budget): backing
                # off cannot change it, so propagate immediately — unless the
                # caller explicitly opted SourceError into retry_on
                if isinstance(e, SourceError) and not any(
                    rt is SourceError for rt in self.retry_on
                ):
                    raise
                last, reason = e, self._reason(e)
            else:
                if len(buf) == n:
                    return buf
                last = SourceError(
                    f"inner source returned {len(buf)}/{n} bytes at {offset}"
                )
                reason = "short_read"
            _metrics.inc("io_retries_total", reason=reason)
            # per-request attribution: the retry shows in this request's
            # trace (and merged multi-process view), not just the process
            # counter — a remote.get followed by io.retry reads as one story
            _trace.count("io.retry")
            # structured mirror of the counter: rate-limited per event key,
            # so a retry storm costs counters (exact) not disk (sampled)
            _log_event(
                "source_retry", level="warning", reason=reason,
                attempt=attempt + 1, offset=offset, nbytes=n,
                source=self.inner.source_id,
            )
            if attempt + 1 >= self.attempts:
                break
            delay = min(self.max_delay_s, self.base_delay_s * (2**attempt))
            delay *= 1.0 + self.jitter * self._rng.random()
            if time.monotonic() - t0 + delay > self.deadline_s:
                reason = f"{reason} (deadline)"
                break
            self._sleep(delay)
        raise SourceError(
            f"read of {n} bytes at {offset} failed after "
            f"{min(attempt + 1, self.attempts)} attempt(s) "
            f"[last: {reason}]",
            code="retry_exhausted",
        ) from last

    def read_ranges(self, ranges) -> list:
        ranges = list(ranges)
        if len(ranges) > 1:
            # fast path: ONE batched attempt through the inner source, so
            # a concurrency-capable transport (HttpSource fans read_ranges
            # out on pqt-io) keeps its parallelism under the retry ladder.
            # A retryable fault drops to the per-range ladder below —
            # healthy batch-mates may re-fetch once on that path, the
            # price of never letting one flaky range burn the batch's
            # retry budget.
            try:
                bufs = self.inner.read_ranges(ranges)
            except ValueError:
                raise  # caller bug, not a transport fault
            except self.retry_on as e:
                if isinstance(e, SourceError) and not any(
                    rt is SourceError for rt in self.retry_on
                ):
                    raise  # terminal (past-EOF, breaker open, ...)
                _metrics.inc("io_retries_total", reason=self._reason(e))
            else:
                if len(bufs) == len(ranges) and all(
                    len(b) == n for b, (_o, n) in zip(bufs, ranges)
                ):
                    return bufs
                _metrics.inc("io_retries_total", reason="short_read")
        # per-range retry: each range gets its own full ladder
        return [self.read_at(off, n) for off, n in ranges]

    def close(self) -> None:
        self.inner.close()


class SourceFile:
    """File-like view (seek/tell/read) over a ByteSource, with an
    INDEPENDENT cursor per instance — the compatibility shim for the page
    walks and footer parser that still speak stream. Reads clamp at EOF
    (short return, like a real file) instead of raising, so truncated-file
    corruption surfaces as the decode ladder's typed errors, exactly as
    with a plain handle."""

    __slots__ = ("_src", "_pos")

    def __init__(self, source: ByteSource):
        self._src = source
        self._pos = 0

    @property
    def source(self) -> ByteSource:
        return self._src

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        elif whence == 2:
            self._pos = self._src.size() + offset
        else:
            raise ValueError(f"invalid whence {whence}")
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        end = self._src.size()
        if self._pos < 0 or self._pos >= end:
            return b""
        want = end - self._pos if n is None or n < 0 else min(n, end - self._pos)
        if want <= 0:
            return b""
        buf = self._src.read_at(self._pos, want)
        self._pos += len(buf)
        return buf

    def close(self) -> None:  # the READER owns the source's lifetime
        pass


def _wrap_policy(source: ByteSource) -> ByteSource:
    """Apply the installed resilience policy (io.hedge: chaos wrapper,
    circuit breaker, retry ladder, hedged reads) to a source open_source
    just CONSTRUCTED. The default policy is all-off and this is the
    identity; lazy import keeps source.py <-> hedge.py acyclic."""
    from .hedge import wrap_resilient

    return wrap_resilient(source)


def open_source(obj) -> tuple[ByteSource, bool]:
    """Coerce `obj` into a (ByteSource, owns) pair — the FileReader
    constructor's one entry point for every accepted source shape.

      str / Path            -> LocalFileSource       (owned: reader closes)
      bytes-like            -> MemorySource          (owned, close no-op)
      io.BytesIO            -> MemorySource snapshot (owned)
      ByteSource            -> passed through        (caller keeps lifetime)
      seekable file-like    -> FileObjectSource      (caller keeps lifetime)

    Sources this function CONSTRUCTS additionally pass through the
    process resilience policy (io.hedge.configure_resilience): with a
    policy installed, every reader/dataset/daemon open inherits breakers,
    retries and hedging here, with no per-callsite wiring. Pre-built
    ByteSource and file-like objects pass through untouched — an explicit
    stack is the caller's to compose.

    An http(s):// URL string opens an io.remote.HttpSource (range GETs on
    the pooled persistent connections), so URLs ride every path-shaped
    API — FileReader, ParquetDataset, readahead — and inherit the same
    policy stack remote reads were built for."""
    if isinstance(obj, ByteSource):
        return obj, False
    if isinstance(obj, str) and obj.startswith(("http://", "https://")):
        from .remote import HttpSource

        return _wrap_policy(HttpSource(obj)), True
    if isinstance(obj, (str, Path)):
        return _wrap_policy(LocalFileSource(obj)), True
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return _wrap_policy(MemorySource(obj)), True
    if isinstance(obj, _io.BytesIO):
        # snapshot: decouples decode from later caller mutation of the BytesIO
        return _wrap_policy(MemorySource(obj.getvalue())), True
    if hasattr(obj, "read_at") and hasattr(obj, "size"):
        return obj, False  # duck-typed source (custom remote implementations)
    if hasattr(obj, "read") and hasattr(obj, "seek"):
        return FileObjectSource(obj), False
    raise TypeError(
        f"cannot open {type(obj).__name__!r} as a byte source (expected a "
        "path, bytes, a ByteSource, or a seekable binary file object)"
    )

"""parquet_tpu.io — pluggable byte sources, range planning, and caching.

The IO seam under the decode stack: ByteSource implementations (lock-free
local pread, in-memory, HTTP(S) range-GET remote sources with presigned-
URL object-store variants, retrying/breaker/hedged wrappers), remote
ByteSinks (atomic multipart object-store writes) with SigV4-style request
signing applied symmetrically to reads and writes, a planner
that derives the exact byte ranges a projected read needs from the footer
and coalesces them into batched reads, a bounded pqt-io readahead
scheduler, byte-budgeted block + footer caches with a RAM -> local-disk
TieredCache for remote corpora, and a latency-aware auto-tuner that picks
coalesce/readahead knobs per transport. See each module's docstring.
"""

from .autotune import IOParams, IOTuner, io_tuner, profile_key  # noqa: F401
from .cache import BlockCache, FooterCache, shared_footer_cache  # noqa: F401
from .hedge import (  # noqa: F401
    BreakerRegistry,
    BreakerSource,
    CircuitBreaker,
    HedgedSource,
    ResilienceConfig,
    breaker_registry,
    configure_resilience,
    resilience_config,
    wrap_resilient,
)
from .planner import (  # noqa: F401
    DEFAULT_COALESCE_GAP,
    Readahead,
    coalesce,
    fetch_ranges,
    io_pool,
    plan_ranges,
)
from .remote import (  # noqa: F401
    HttpSource,
    ObjectStoreSource,
    TransientSourceError,
)
from .remote_sink import HttpSink, ObjectStoreSink  # noqa: F401
from .sign import (  # noqa: F401
    SigV4Signer,
    clear_signers,
    configure_signer,
    signer_for,
    verify_request,
)
from .source import (  # noqa: F401
    ByteSource,
    FileObjectSource,
    LocalFileSource,
    MemorySource,
    RetryingSource,
    SourceError,
    SourceFile,
    open_source,
)
from .tiercache import TieredCache  # noqa: F401

__all__ = [
    "ByteSource",
    "SourceError",
    "LocalFileSource",
    "MemorySource",
    "FileObjectSource",
    "RetryingSource",
    "SourceFile",
    "open_source",
    "BlockCache",
    "FooterCache",
    "shared_footer_cache",
    "plan_ranges",
    "coalesce",
    "fetch_ranges",
    "Readahead",
    "io_pool",
    "DEFAULT_COALESCE_GAP",
    "HedgedSource",
    "CircuitBreaker",
    "BreakerRegistry",
    "BreakerSource",
    "breaker_registry",
    "ResilienceConfig",
    "configure_resilience",
    "resilience_config",
    "wrap_resilient",
    "HttpSource",
    "ObjectStoreSource",
    "TransientSourceError",
    "HttpSink",
    "ObjectStoreSink",
    "SigV4Signer",
    "configure_signer",
    "signer_for",
    "clear_signers",
    "verify_request",
    "TieredCache",
    "IOParams",
    "IOTuner",
    "io_tuner",
    "profile_key",
]

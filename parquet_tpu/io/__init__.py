"""parquet_tpu.io — pluggable byte sources, range planning, and caching.

The IO seam under the decode stack: ByteSource implementations (lock-free
local pread, in-memory, retrying remote-shaped wrappers), a planner that
derives the exact byte ranges a projected read needs from the footer and
coalesces them into batched reads, a bounded pqt-io readahead scheduler,
and byte-budgeted block + footer caches. See each module's docstring.
"""

from .cache import BlockCache, FooterCache, shared_footer_cache  # noqa: F401
from .hedge import (  # noqa: F401
    BreakerRegistry,
    BreakerSource,
    CircuitBreaker,
    HedgedSource,
    ResilienceConfig,
    breaker_registry,
    configure_resilience,
    resilience_config,
    wrap_resilient,
)
from .planner import (  # noqa: F401
    DEFAULT_COALESCE_GAP,
    Readahead,
    coalesce,
    fetch_ranges,
    io_pool,
    plan_ranges,
)
from .source import (  # noqa: F401
    ByteSource,
    FileObjectSource,
    LocalFileSource,
    MemorySource,
    RetryingSource,
    SourceError,
    SourceFile,
    open_source,
)

__all__ = [
    "ByteSource",
    "SourceError",
    "LocalFileSource",
    "MemorySource",
    "FileObjectSource",
    "RetryingSource",
    "SourceFile",
    "open_source",
    "BlockCache",
    "FooterCache",
    "shared_footer_cache",
    "plan_ranges",
    "coalesce",
    "fetch_ranges",
    "Readahead",
    "io_pool",
    "DEFAULT_COALESCE_GAP",
    "HedgedSource",
    "CircuitBreaker",
    "BreakerRegistry",
    "BreakerSource",
    "breaker_registry",
    "ResilienceConfig",
    "configure_resilience",
    "resilience_config",
    "wrap_resilient",
]

"""Remote byte sinks: atomic multipart object-store writes over HTTP(S).

The write-side twin of parquet_tpu.io.remote — PR 13 made URLs work
everywhere a *path* does for reads; this module closes the write
direction with the same typed-failure, crash-never-tears discipline that
LocalFileSink pins locally:

  HttpSink           a ByteSink over one HTTP(S) URL. Bytes accumulate in
                     memory and seal into fixed-size PARTS; each part
                     rides a bounded-in-flight PUT on the pqt-io pool
                     (S3 multipart shape: initiate -> part PUTs ->
                     complete), with per-part CRC32 verification against
                     the store's part ETag and a per-part retry ladder
                     with capped exponential backoff. The LocalFileSink
                     atomicity contract holds exactly: close() is the
                     complete-multipart COMMIT (the object appears at the
                     destination all at once or not at all), abort() is
                     abort-upload (idempotent, safe after close, never
                     destroys committed output) — a crash or fault at ANY
                     point never leaves a torn or partially-visible
                     object. An output that never overflows one part
                     skips multipart entirely: one single-shot PUT, atomic
                     by nature.
  ObjectStoreSink    the header-auth variant: HttpSink that REQUIRES a
                     request signer (explicit or resolved from the
                     io.sign registry) — writes to a real store fail at
                     construction, not with N unsigned 403s mid-upload.

Failure taxonomy (mirrors the read side; FileWriter converts sink
OSErrors to typed WriterError + auto-abort):

  transient  -> TransientSourceError absorbed by the per-part retry
               ladder: http_5xx/408/429, connection reset/timeout
               ("transport"), part_etag_mismatch (the store's CRC
               disagrees with ours — re-send the part).
  terminal   -> SinkError(code=...): other 4xx (http_403 and friends),
               retry exhaustion ("put_retry_exhausted"), breaker
               fast-fail ("breaker_open"), use-after-close. Terminal
               failures latch the sink: close() refuses to commit and
               aborts instead.

URL coercion flows through sink.open_sink, so FileWriter(sink="https://
...") / merge_files(-o URL) inherit this path with zero wiring; the
process resilience policy (io.hedge) contributes its breaker — the same
breaker->retry stack reads get — keyed per PUT origin.

Multipart wire protocol (what testing/httpstub.py's writable mode and a
thin S3 adapter both speak):

  POST   {url}?uploads                          -> {"upload_id": id}
  PUT    {url}?partNumber=N&uploadId=id  body   -> ETag: "crc32-<8hex>"
  POST   {url}?uploadId=id   {"parts": [...]}   -> {"etag": ...}  COMMIT
  DELETE {url}?uploadId=id                      -> 204            ABORT
  PUT    {url}                           body   -> single-shot (one part)

Metrics: io_put_requests_total{status=}, io_put_bytes_total,
io_put_retries_total{reason=}, sink_multipart_{initiated,parts,completed,
aborted}_total (documented in utils/metrics.py).
"""

from __future__ import annotations

import json
import threading
import time
import zlib
from urllib.parse import urlsplit

from ..obs import propagate as _propagate
from ..obs.log import log_event as _log_event
from ..sink.sink import ByteSink, SinkError, _count_write
from ..utils import metrics as _metrics
from ..utils import trace as _trace
from .remote import (
    TransientSourceError,
    _default_port,
    host_pool,
    pooled_roundtrip,
)
from .source import SourceError

__all__ = ["HttpSink", "ObjectStoreSink"]

DEFAULT_PART_BYTES = 8 << 20
_MIN_PART_BYTES = 1 << 10  # floor: a 0-byte "part" loops forever


def _put_status_error(status: int, reason: str, context: str):
    """Status -> taxonomy for the write path: transient shapes become
    TransientSourceError (the per-part ladder absorbs them), terminal
    ones SinkError — the sink-side twin of remote._status_error."""
    msg = f"{context}: HTTP {status} {reason}"
    if status >= 500 or status in (408, 429):
        return TransientSourceError(msg, code=f"http_{status}")
    return SinkError(msg, code=f"http_{status}")


class HttpSink(ByteSink):
    """See module docstring. Single-writer like every ByteSink (the
    encode stack serializes writes); the part PUTs it launches fan out on
    the pqt-io pool and are joined at close()/abort().

    Parameters
    ----------
    url            the destination object URL (http/https)
    part_bytes     sealed part size (default 8 MiB; the bench sweeps it)
    max_in_flight  concurrent part PUTs in the air before write() blocks
                   on the oldest (memory bound = part_bytes * in-flight)
    attempts       per-part/commit retry budget (transient faults only)
    backoff_s /    capped exponential backoff between attempts
    backoff_cap_s  (sleep injectable for tests)
    signer         io.sign-style header signer; None consults the
                   configure_signer registry (no match -> unsigned)
    headers        extra headers on every request (auth tokens etc.)
    """

    def __init__(
        self,
        url: str,
        *,
        part_bytes: int = DEFAULT_PART_BYTES,
        max_in_flight: int = 4,
        timeout_s: float = 20.0,
        headers: dict | None = None,
        signer=None,
        attempts: int = 4,
        backoff_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        sleep=time.sleep,
    ):
        split = urlsplit(url)
        if split.scheme not in ("http", "https"):
            raise ValueError(
                f"HttpSink: unsupported scheme {split.scheme!r} in {url!r}"
            )
        if not split.hostname:
            raise ValueError(f"HttpSink: no host in {url!r}")
        if part_bytes < _MIN_PART_BYTES:
            raise ValueError(
                f"HttpSink: part_bytes {part_bytes} < {_MIN_PART_BYTES}"
            )
        if max_in_flight < 1:
            raise ValueError("HttpSink: max_in_flight must be >= 1")
        if attempts < 1:
            raise ValueError("HttpSink: attempts must be >= 1")
        self.url = url
        self.part_bytes = int(part_bytes)
        self.max_in_flight = int(max_in_flight)
        self.timeout_s = float(timeout_s)
        self.headers = dict(headers or {})
        self.attempts = int(attempts)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._sleep = sleep
        self._scheme = split.scheme
        self._host = split.hostname
        self._port = split.port or _default_port(split.scheme)
        self._path = split.path or "/"
        if split.query:
            raise ValueError(
                f"HttpSink: query strings are reserved for the multipart "
                f"protocol: {url!r}"
            )
        self._pool = host_pool(self._scheme, self._host, self._port)
        if signer is None:
            from .sign import signer_for

            signer = signer_for(url)
        self._signer = signer
        # the breaker the process resilience policy grants reads, keyed
        # per PUT origin: a store answering nothing but 503s fast-fails
        # the remaining parts instead of burning a full ladder on each
        from .hedge import breaker_registry, resilience_config

        policy = resilience_config()
        self._breaker = (
            (policy.registry or breaker_registry()).breaker_for(
                f"put:{self._scheme}://{self._host}:{self._port}"
            )
            if policy.breaker
            else None
        )
        netloc = (
            self._host
            if self._port == _default_port(self._scheme)
            else f"{self._host}:{self._port}"
        )
        self._id = f"http:{self._scheme}://{netloc}{self._path}"
        self._buf = bytearray()
        self._pos = 0
        self._upload_id: str | None = None
        self._next_part = 1
        self._parts: list[dict] = []  # completed part manifest entries
        self._pending: list = []  # in-flight part futures, launch order
        self._failed: BaseException | None = None
        self._committed = False
        self._aborted = False

    @property
    def sink_id(self) -> str:
        return self._id

    # -- one signed round trip with the per-part ladder ------------------------

    def _send(
        self,
        method: str,
        target: str,
        body: bytes | None,
        context: str,
        *,
        retry: bool = True,
    ):
        """One request, signed, retried through the capped-backoff ladder
        (transient shapes only — a 403 is wrong on attempt 1 and wrong on
        attempt 4). Returns (status, headers, body) for 2xx; raises the
        typed error otherwise. The breaker (when the policy grants one)
        gates every attempt and learns from every outcome."""
        # netloc must agree with the Host header http.client will send
        # (default ports omitted), or the signature never verifies
        netloc = (
            self._host
            if self._port == _default_port(self._scheme)
            else f"{self._host}:{self._port}"
        )
        url = f"{self._scheme}://{netloc}{target}"
        last: BaseException | None = None
        for attempt in range(self.attempts if retry else 1):
            if attempt:
                reason = getattr(last, "code", None) or "transport"
                _metrics.inc("io_put_retries_total", reason=str(reason))
                self._sleep(
                    min(self.backoff_cap_s, self.backoff_s * (2 ** (attempt - 1)))
                )
            if self._breaker is not None:
                try:
                    self._breaker.before_read()
                except SourceError as e:
                    raise SinkError(
                        f"{context}: {e}", code="breaker_open"
                    ) from e
            hdrs = dict(self.headers)
            if self._signer is not None:
                hdrs.update(self._signer.headers(method, url, body or b""))
            tp = _propagate.outbound_traceparent("put")
            if tp is not None:
                # fresh child span-id per ATTEMPT: a retried part is two
                # distinct spans in the store's access log, one trace-id
                hdrs["traceparent"] = tp
            try:
                span_args = {"attempt": attempt + 1, "nbytes": len(body or b"")}
                with _trace.span("remote.put", args=span_args):
                    status, reason_s, resp_headers, resp_body = pooled_roundtrip(
                        self._pool,
                        method,
                        target,
                        hdrs,
                        body=body,
                        timeout_s=self.timeout_s,
                        counter="io_put_requests_total",
                    )
                    span_args["status"] = status
                if status >= 300:
                    raise _put_status_error(status, reason_s, context)
            except TransientSourceError as e:
                if self._breaker is not None:
                    self._breaker.record_failure()
                if not retry:
                    raise  # the caller owns the ladder (_put_part)
                last = e
                continue
            except SinkError:
                if self._breaker is not None:
                    self._breaker.record_failure()
                raise
            if self._breaker is not None:
                self._breaker.record_success()
            return status, resp_headers, resp_body
        raise SinkError(
            f"{context}: gave up after {self.attempts} attempts: {last}",
            code="put_retry_exhausted",
        ) from last

    # -- multipart plumbing ----------------------------------------------------

    def _ensure_upload(self) -> str:
        if self._upload_id is None:
            _, _, body = self._send(
                "POST", f"{self._path}?uploads", b"",
                f"initiate multipart {self.url}",
            )
            try:
                self._upload_id = str(json.loads(body or b"{}")["upload_id"])
            except (ValueError, KeyError) as e:
                raise SinkError(
                    f"initiate multipart {self.url}: malformed response "
                    f"{body[:128]!r}",
                    code="bad_initiate_response",
                ) from e
            _metrics.inc("sink_multipart_initiated_total")
            _log_event(
                "multipart_initiated", sink=self._id, upload_id=self._upload_id
            )
        return self._upload_id

    def _put_part(self, part_number: int, data: bytes) -> dict:
        """Upload ONE sealed part (runs on pqt-io or inline). The store's
        part ETag carries a CRC32 of what it RECEIVED; a mismatch with
        what we SENT is a torn transfer shaped like success — re-sent
        like any transient fault rather than trusted."""
        crc = zlib.crc32(data) & 0xFFFFFFFF
        expect = f'"crc32-{crc:08x}"'
        target = (
            f"{self._path}?partNumber={part_number}&uploadId={self._upload_id}"
        )
        context = f"part {part_number} of {self.url}"
        last: BaseException | None = None
        for attempt in range(self.attempts):
            if attempt:
                reason = getattr(last, "code", None) or "transport"
                _metrics.inc("io_put_retries_total", reason=str(reason))
                self._sleep(
                    min(self.backoff_cap_s, self.backoff_s * (2 ** (attempt - 1)))
                )
            try:
                _, resp_headers, _ = self._send(
                    "PUT", target, data, context, retry=False
                )
            except TransientSourceError as e:
                last = e
                continue
            etag = resp_headers.get("ETag")
            if etag is not None and etag != expect:
                last = TransientSourceError(
                    f"{context}: part ETag {etag} != {expect} "
                    f"(torn transfer acknowledged as success)",
                    code="part_etag_mismatch",
                )
                continue
            _metrics.inc("io_put_bytes_total", len(data))
            _metrics.inc("sink_multipart_parts_total")
            return {
                "part_number": part_number,
                "etag": etag or expect,
                "size": len(data),
            }
        raise SinkError(
            f"{context}: gave up after {self.attempts} attempts: {last}",
            code="put_retry_exhausted",
        ) from last

    def _launch(self, data: bytes) -> None:
        """Seal `data` as the next part and put it in flight (bounded)."""
        self._ensure_upload()
        part_number = self._next_part
        self._next_part += 1
        while len(self._pending) >= self.max_in_flight:
            self._reap(self._pending.pop(0))
        if threading.current_thread().name.startswith("pqt-io"):
            # never submit-to-self: a bounded pool waiting on itself is a
            # deadlock (same degrade as HttpSource.read_ranges)
            try:
                self._parts.append(self._put_part(part_number, data))
            except BaseException as e:  # noqa: BLE001 — latched, re-raised
                if self._failed is None:
                    self._failed = e
            return
        from ..obs.pool import instrumented_submit
        from .planner import io_pool

        self._pending.append(
            instrumented_submit(
                io_pool(), self._put_part, part_number, data, pool="pqt-io"
            )
        )

    def _reap(self, fut) -> None:
        try:
            self._parts.append(fut.result())
        except BaseException as e:  # noqa: BLE001 — latched for close/abort
            if self._failed is None:
                self._failed = e

    def _drain(self) -> None:
        while self._pending:
            self._reap(self._pending.pop(0))

    def _raise_failed(self, context: str):
        e = self._failed
        if isinstance(e, SinkError):
            raise SinkError(f"{context}: {e}", code=e.code) from e
        raise SinkError(f"{context}: {e}", code="put_failed") from e

    # -- the ByteSink contract -------------------------------------------------

    def write(self, data) -> int:
        if self._committed or self._aborted:
            raise SinkError(f"sink closed: {self.url}", code="sink_closed")
        if self._failed is not None:
            # fail the WRITE, not just the eventual close: the writer's
            # auto-abort fires now instead of encoding gigabytes into a
            # sink that can no longer commit
            self._raise_failed(f"write to {self.url}")
        n = len(data)
        self._buf += data
        self._pos += n
        _count_write(n)
        while len(self._buf) >= self.part_bytes:
            part = bytes(self._buf[: self.part_bytes])
            del self._buf[: self.part_bytes]
            self._launch(part)
            if self._failed is not None:
                self._raise_failed(f"write to {self.url}")
        return n

    def tell(self) -> int:
        return self._pos

    def flush(self) -> None:
        """A no-op by design: remote bytes are durable only at COMMIT
        (parts of an uncompleted upload are invisible), so there is no
        intermediate durability for flush to buy — and force-sealing a
        short part here would fragment the part-size the bench tunes."""

    def close(self) -> None:
        if self._committed or self._aborted:
            return
        try:
            if self._upload_id is None and not self._pending:
                # everything fits one part: single-shot PUT, atomic by
                # nature — 1 request instead of 3
                data = bytes(self._buf)
                self._buf = bytearray()
                _, resp_headers, _ = self._send(
                    "PUT", self._path, data, f"put {self.url}"
                )
                etag = resp_headers.get("ETag")
                crc = zlib.crc32(data) & 0xFFFFFFFF
                if etag is not None and etag != f'"crc32-{crc:08x}"':
                    raise SinkError(
                        f"put {self.url}: object ETag {etag} does not match "
                        f"sent bytes (torn transfer acknowledged as success)",
                        code="put_etag_mismatch",
                    )
                _metrics.inc("io_put_bytes_total", len(data))
            else:
                if self._buf:
                    self._launch(bytes(self._buf))
                    self._buf = bytearray()
                self._drain()
                if self._failed is not None:
                    self._raise_failed(f"commit of {self.url}")
                manifest = json.dumps(
                    {
                        "parts": sorted(
                            self._parts, key=lambda p: p["part_number"]
                        )
                    }
                ).encode("utf-8")
                self._send(
                    "POST",
                    f"{self._path}?uploadId={self._upload_id}",
                    manifest,
                    f"complete multipart {self.url}",
                )
                _metrics.inc("sink_multipart_completed_total")
                _log_event(
                    "multipart_completed",
                    sink=self._id,
                    upload_id=self._upload_id,
                    parts=len(self._parts),
                    bytes=self._pos,
                )
        except BaseException:
            # commit did NOT happen; leave nothing behind (abort-upload
            # is best-effort — an unreachable store keeps the close()
            # error, not a second one from the cleanup)
            self.abort()
            raise
        self._committed = True

    def abort(self) -> None:
        if self._committed or self._aborted:
            return  # never destroy committed output (or double-abort)
        self._aborted = True
        self._buf = bytearray()
        # absorb in-flight parts first: an abort racing its own part PUTs
        # could otherwise delete the upload out from under them
        while self._pending:
            fut = self._pending.pop(0)
            try:
                fut.result()
            except BaseException:  # noqa: BLE001 — aborting anyway
                pass
        if self._upload_id is not None:
            try:
                self._send(
                    "DELETE",
                    f"{self._path}?uploadId={self._upload_id}",
                    None,
                    f"abort multipart {self.url}",
                    retry=False,
                )
            except BaseException:  # noqa: BLE001 — best-effort by contract
                pass
            _metrics.inc("sink_multipart_aborted_total")
            _log_event(
                "multipart_aborted", sink=self._id, upload_id=self._upload_id
            )


class ObjectStoreSink(HttpSink):
    """HttpSink that REQUIRES header-auth signing (S3/GCS shape): pass a
    signer or register one via io.sign.configure_signer — a store write
    without credentials should fail at construction, not as a stream of
    unsigned 403s mid-upload."""

    def __init__(self, url: str, *, signer=None, **kw):
        if signer is None:
            from .sign import signer_for

            signer = signer_for(url)
        if signer is None:
            raise ValueError(
                f"ObjectStoreSink: no signer for {url!r} (pass signer= or "
                "configure_signer(...))"
            )
        super().__init__(url, signer=signer, **kw)

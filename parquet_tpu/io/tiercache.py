"""TieredCache: BlockCache grown into a RAM-LRU -> local-disk spill cache.

The "millions of users hammering the same hot shards" story needs more
cache than RAM: a 64 MiB BlockCache in front of a 200 GB remote corpus
thrashes, but a local NVMe holds tens of GBs of the compressed hot set at
~100x less latency than the store. TieredCache keeps the BlockCache
contract (get/put/invalidate/stats keyed (source_id, offset, len) — every
fetch_ranges call site works unchanged) and adds a disk tier underneath:

  RAM tier     the same byte-budgeted LRU as BlockCache. Eviction does
               not discard — it SPILLS the block to the disk tier.
  disk tier    append-only segment files under cache_dir + an in-memory
               offset index. Spills append to the active segment (rolled
               at segment_bytes); sealed segments are mmap'd for readback;
               a disk hit copies the block out and PROMOTES it back to
               RAM. The tier is byte-budgeted too: over budget, the
               OLDEST WHOLE SEGMENT is dropped (one unlink reclaims real
               bytes — per-block hole-punching in an append-only file
               reclaims nothing).

Crash safety: every record carries magic + lengths + a CRC over key and
payload. A restart against an existing cache_dir replays the segments and
re-serves every intact record; the first torn/corrupt record ABANDONS the
rest of its segment (counted cache_tier_torn_segments_total) — a torn
block is discarded, never served. The key rides in the record (source_id
embeds content generation — size/mtime/inode for files, ETag for HTTP),
so a rewritten source can never hit a stale restart-loaded block.

Sharing: one TieredCache instance is safe under concurrent readers and
writers (single lock; disk reads copy out under it), so the serve daemon
and co-resident dataset workers can pool one spill directory. Metric
families are tier-labelled (cache_tier_* — see utils/metrics.py); the
io_cache_* block-cache families keep counting too, so every existing
hit-rate surface (parquet-tool scan, the tenant ledger) reads the same.
"""

from __future__ import annotations

import mmap
import os
import shutil
import struct
import tempfile
import threading
import zlib
from collections import OrderedDict

from ..utils import metrics as _metrics
from ..utils.trace import count as _trace_count

__all__ = ["TieredCache"]

_MAGIC = b"PQTC"
# record: magic(4) key_len(u16) data_len(u32) crc32(u32) key data
_HEADER = struct.Struct("<4sHII")


def _record_key(source_id: str, offset: int, length: int) -> bytes:
    return f"{source_id}\x00{offset}\x00{length}".encode()


def _parse_key(raw: bytes):
    sid, off, length = raw.decode().rsplit("\x00", 2)
    return (sid, int(off), int(length))


class _Segment:
    """One append-only spill file. Active: appended via fd, read via
    pread. Sealed: read-only through one shared mmap."""

    __slots__ = ("seg_id", "path", "fd", "mm", "size", "keys", "live_bytes")

    def __init__(self, seg_id: int, path: str, *, size: int = 0):
        self.seg_id = seg_id
        self.path = path
        self.fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        self.mm: mmap.mmap | None = None
        self.size = size  # valid (replayed or written) bytes
        self.keys: list[tuple] = []  # index keys living in this segment
        self.live_bytes = 0  # payload bytes still indexed (diagnostics)

    def append(self, blob: bytes) -> int:
        """Append one full record; returns its start offset."""
        off = self.size
        os.pwrite(self.fd, blob, off)
        self.size += len(blob)
        return off

    def seal(self) -> None:
        if self.mm is None and self.size > 0:
            # map exactly the VALID prefix: a torn tail replayed past it
            # is unreachable by construction
            self.mm = mmap.mmap(
                self.fd, self.size, prot=mmap.PROT_READ
            )

    def read(self, offset: int, length: int) -> bytes:
        if self.mm is not None:
            return bytes(self.mm[offset : offset + length])
        return os.pread(self.fd, length, offset)

    def close(self, *, unlink: bool) -> None:
        if self.mm is not None:
            self.mm.close()
            self.mm = None
        os.close(self.fd)
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class TieredCache:
    """RAM-LRU -> disk-spill block cache (see module docstring).

    ram_bytes      RAM tier budget (> 0)
    disk_bytes     disk tier budget (> 0; use BlockCache for RAM-only)
    cache_dir      spill directory. None = a private temp dir removed on
                   close(); a given path is created, REUSED across
                   restarts (intact records re-serve) and left in place.
    segment_bytes  roll the active segment past this many bytes
    """

    def __init__(
        self,
        ram_bytes: int = 64 << 20,
        disk_bytes: int = 256 << 20,
        cache_dir=None,
        *,
        segment_bytes: int = 32 << 20,
    ):
        if ram_bytes <= 0:
            raise ValueError("TieredCache: ram_bytes must be positive")
        if disk_bytes <= 0:
            raise ValueError("TieredCache: disk_bytes must be positive")
        if segment_bytes <= 0:
            raise ValueError("TieredCache: segment_bytes must be positive")
        self.ram_bytes = int(ram_bytes)
        self.disk_bytes = int(disk_bytes)
        self.segment_bytes = int(segment_bytes)
        self._owns_dir = cache_dir is None
        if cache_dir is None:
            self.cache_dir = tempfile.mkdtemp(prefix="pqt-tiercache-")
        else:
            self.cache_dir = os.fspath(cache_dir)
            os.makedirs(self.cache_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._ram: OrderedDict[tuple, bytes] = OrderedDict()
        self._ram_used = 0
        # disk index: key -> (segment, payload offset, payload length)
        self._disk: dict[tuple, tuple] = {}
        self._disk_used = 0  # file bytes on disk (records, not payloads)
        self._segments: OrderedDict[int, _Segment] = OrderedDict()
        self._active: _Segment | None = None
        self._next_seg = 0
        self._closed = False
        self._load_existing()

    # -- restart replay --------------------------------------------------------

    def _load_existing(self) -> None:
        names = sorted(
            n for n in os.listdir(self.cache_dir)
            if n.startswith("seg-") and n.endswith(".dat")
        )
        for name in names:
            path = os.path.join(self.cache_dir, name)
            try:
                seg_id = int(name[4:-4])
            except ValueError:
                continue
            self._next_seg = max(self._next_seg, seg_id + 1)
            seg = _Segment(seg_id, path)
            file_size = os.fstat(seg.fd).st_size
            restored = self._replay(seg, file_size)
            if seg.size < file_size:
                # a torn tail (crash mid-append): everything past the last
                # intact record is DISCARDED, never served
                _metrics.inc("cache_tier_torn_segments_total")
            if restored == 0:
                seg.close(unlink=True)
                continue
            seg.seal()
            self._segments[seg_id] = seg
            self._disk_used += seg.size
            _metrics.inc("cache_tier_restored_blocks_total", restored)
        self._enforce_disk_budget()
        self._set_gauges()

    def _replay(self, seg: _Segment, file_size: int) -> int:
        """Walk records from offset 0; index every intact one. Stops (and
        pins seg.size) at the first corrupt/short record."""
        pos = 0
        restored = 0
        while pos + _HEADER.size <= file_size:
            hdr = os.pread(seg.fd, _HEADER.size, pos)
            if len(hdr) < _HEADER.size:
                break
            magic, key_len, data_len, crc = _HEADER.unpack(hdr)
            if magic != _MAGIC:
                break
            body_end = pos + _HEADER.size + key_len + data_len
            if body_end > file_size:
                break  # torn mid-payload
            body = os.pread(seg.fd, key_len + data_len, pos + _HEADER.size)
            if len(body) < key_len + data_len or zlib.crc32(body) != crc:
                break
            try:
                key = _parse_key(body[:key_len])
            except (ValueError, UnicodeDecodeError):
                break
            if key not in self._disk:  # first writer wins within a replay
                self._disk[key] = (seg, pos + _HEADER.size + key_len, data_len)
                seg.keys.append(key)
                seg.live_bytes += data_len
                restored += 1
            pos = body_end
        seg.size = pos
        return restored

    # -- the BlockCache contract -----------------------------------------------

    def get(self, source_id: str, offset: int, length: int):
        key = (source_id, int(offset), int(length))
        with self._lock:
            buf = self._ram.get(key)
            if buf is not None:
                self._ram.move_to_end(key)
                self._count_hit("ram")
                return buf
            loc = self._disk.get(key)
            if loc is not None:
                seg, data_off, data_len = loc
                buf = seg.read(data_off, data_len)
                self._count_hit("disk")
                _metrics.inc("cache_tier_promotions_total")
                # promote: the block is hot again — next hit is a RAM hit.
                # It stays indexed on disk too, so re-evicting it later
                # never re-spills the same bytes.
                self._ram_put(key, buf, spill_on_evict=True)
                return buf
        _metrics.inc("cache_tier_misses_total")
        _metrics.inc("io_cache_misses_total")
        _trace_count("io_cache_miss")
        return None

    def put(self, source_id: str, offset: int, length: int, data) -> None:
        data = bytes(data)
        key = (source_id, int(offset), int(length))
        with self._lock:
            if self._closed:
                return
            if len(data) > self.ram_bytes:
                # too big for the whole RAM tier: straight to disk (a
                # block past the DISK budget too is simply not cacheable)
                if len(data) <= self.disk_bytes and key not in self._disk:
                    self._spill(key, data)
                    self._enforce_disk_budget()
                    self._set_gauges()
                return
            self._ram_put(key, data, spill_on_evict=True)

    def _count_hit(self, tier: str) -> None:
        _metrics.inc("cache_tier_hits_total", tier=tier)
        _metrics.inc("io_cache_hits_total")
        _trace_count("io_cache_hit")

    def _ram_put(self, key, data: bytes, *, spill_on_evict: bool) -> None:
        # lock held
        old = self._ram.pop(key, None)
        if old is not None:
            self._ram_used -= len(old)
        self._ram[key] = data
        self._ram_used += len(data)
        while self._ram_used > self.ram_bytes:
            k, evicted = self._ram.popitem(last=False)
            self._ram_used -= len(evicted)
            _metrics.inc("cache_tier_evictions_total", tier="ram")
            if spill_on_evict and k not in self._disk:
                self._spill(k, evicted)
        self._enforce_disk_budget()
        self._set_gauges()

    # -- disk tier -------------------------------------------------------------

    def _spill(self, key, data: bytes) -> None:
        # lock held
        key_raw = _record_key(*key)
        blob = (
            _HEADER.pack(
                _MAGIC, len(key_raw), len(data), zlib.crc32(key_raw + data)
            )
            + key_raw
            + data
        )
        if len(blob) > self.disk_bytes:
            return
        seg = self._active
        if seg is not None and seg.size + len(blob) > self.segment_bytes:
            seg.seal()
            self._active = seg = None
        if seg is None:
            seg_id = self._next_seg
            self._next_seg += 1
            seg = _Segment(
                seg_id, os.path.join(self.cache_dir, f"seg-{seg_id:08d}.dat")
            )
            self._segments[seg_id] = seg
            self._active = seg
        off = seg.append(blob)
        self._disk_used += len(blob)
        self._disk[key] = (seg, off + _HEADER.size + len(key_raw), len(data))
        seg.keys.append(key)
        seg.live_bytes += len(data)
        _metrics.inc("cache_tier_spills_total")
        _metrics.inc("cache_tier_spill_bytes_total", len(data))

    def _enforce_disk_budget(self) -> None:
        # lock held; oldest-first whole-segment eviction
        while self._disk_used > self.disk_bytes and self._segments:
            seg_id, seg = next(iter(self._segments.items()))
            if seg is self._active:
                self._active = None
            del self._segments[seg_id]
            self._disk_used -= seg.size
            dropped = 0
            for key in seg.keys:
                loc = self._disk.get(key)
                if loc is not None and loc[0] is seg:
                    del self._disk[key]
                    dropped += 1
            if dropped:
                _metrics.inc(
                    "cache_tier_evictions_total", dropped, tier="disk"
                )
            seg.close(unlink=True)

    # -- management ------------------------------------------------------------

    def invalidate(self, source_id: str) -> None:
        """Drop every block of one source from BOTH tiers (the disk bytes
        stay dead in their segments until segment eviction reclaims them)."""
        with self._lock:
            for key in [k for k in self._ram if k[0] == source_id]:
                self._ram_used -= len(self._ram.pop(key))
            for key in [k for k in self._disk if k[0] == source_id]:
                seg, _off, data_len = self._disk.pop(key)
                seg.live_bytes -= data_len
            self._set_gauges()

    def clear(self) -> None:
        with self._lock:
            self._ram.clear()
            self._ram_used = 0
            self._disk.clear()
            self._drop_segments()
            self._set_gauges()

    def _drop_segments(self) -> None:
        # lock held
        for seg in self._segments.values():
            seg.close(unlink=True)
        self._segments.clear()
        self._active = None
        self._disk_used = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                # BlockCache-shaped top level (existing surfaces read these)
                "blocks": len(self._ram) + len(self._disk),
                "bytes": self._ram_used + self._disk_used,
                "capacity_bytes": self.ram_bytes + self.disk_bytes,
                "ram": {
                    "blocks": len(self._ram),
                    "bytes": self._ram_used,
                    "capacity_bytes": self.ram_bytes,
                },
                "disk": {
                    "blocks": len(self._disk),
                    "bytes": self._disk_used,
                    "capacity_bytes": self.disk_bytes,
                    "segments": len(self._segments),
                    "dir": self.cache_dir,
                },
            }

    def _set_gauges(self) -> None:
        _metrics.set_gauge("cache_tier_bytes", self._ram_used, tier="ram")
        _metrics.set_gauge("cache_tier_bytes", self._disk_used, tier="disk")
        _metrics.set_gauge("io_cache_bytes", self._ram_used + self._disk_used)

    def close(self) -> None:
        """Release fds/mmaps. A PRIVATE temp dir is deleted; a caller-
        provided cache_dir keeps its segments for the next process (the
        restart-replay path re-serves them)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for seg in self._segments.values():
                seg.close(unlink=self._owns_dir)
            self._segments.clear()
            self._active = None
            self._ram.clear()
            self._ram_used = 0
            self._disk.clear()
            self._disk_used = 0
        if self._owns_dir:
            shutil.rmtree(self.cache_dir, ignore_errors=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

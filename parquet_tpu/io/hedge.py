"""Hedged reads and circuit breakers: the failover layer of the IO stack.

RetryingSource (source.py) answers a TRANSIENT fault after the fact: wait,
try again. This module answers the two failure shapes retry alone handles
badly:

  tail latency   one read in twenty stalls 50x longer than the median (a
                 hot shard, a GC pause, a slow replica). Retrying only
                 starts AFTER the stall. `HedgedSource` instead launches a
                 duplicate of a read that has outlived the observed latency
                 quantile and takes whichever copy answers first — the
                 classic tail-at-scale move. The loser is cancelled when
                 still queued, or absorbed (result dropped, latency still
                 recorded) when already running.

  blackout       a source that fails EVERY read. The retry ladder burns
                 its full attempts x backoff budget on each of potentially
                 thousands of reads. A `CircuitBreaker` per source_id trips
                 after `failure_threshold` consecutive failures and
                 fast-fails every subsequent read with the typed
                 SourceError(code="breaker_open") until `open_s` has
                 passed; then ONE half-open probe read is let through — it
                 closes the breaker on success and re-arms the open timer
                 on failure.

Composition is explicit and order matters:

    RetryingSource(BreakerSource(src))   breaker counts RAW failures; the
                                         fast-fail is a SourceError, which
                                         the retry ladder treats as
                                         terminal (no pointless backoff)
    BreakerSource(RetryingSource(src))   breaker counts post-retry
                                         EXHAUSTION (trips only when the
                                         ladder itself gives up)

`ResilienceConfig` + `configure_resilience()` wire the layer through
`open_source`, the choke point every FileReader construction passes: when a
policy is installed, every concrete source opened anywhere (reader, dataset
units, serve executor, readahead) comes back wrapped per the policy — the
chaos harness (testing/chaos.py) also injects its FlakySource through the
same hook. The default policy is all-off: zero wrappers, zero cost.

Metrics: io_hedges_total{outcome=launched|win_primary|win_hedge|failed}
and the io_breaker_state{source=} gauge (0 closed, 1 open, 2 half-open;
the label set is bounded by BreakerRegistry's max_sources).
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutTimeout
from dataclasses import dataclass, field

from ..obs.log import log_event as _log_event
from ..utils import metrics as _metrics
from ..utils import trace as _trace
from .source import ByteSource, RetryingSource, SourceError

__all__ = [
    "HedgedSource",
    "CircuitBreaker",
    "BreakerRegistry",
    "BreakerSource",
    "breaker_registry",
    "ResilienceConfig",
    "configure_resilience",
    "resilience_config",
    "wrap_resilient",
    "hedge_pool",
]


# -- the hedge pool ------------------------------------------------------------

_pool: ThreadPoolExecutor | None = None
_pool_lock = threading.Lock()


def hedge_pool() -> ThreadPoolExecutor:
    """The process-wide hedged-read executor ("pqt-hedge", PQT_HEDGE_THREADS
    or 8 workers). Its OWN pool, never pqt-io: hedged reads are issued FROM
    pqt-io readahead tasks, and a bounded pool that submits to itself
    deadlocks the moment every worker is waiting on a future only another
    worker can run."""
    global _pool
    with _pool_lock:
        if _pool is None:
            env = os.environ.get("PQT_HEDGE_THREADS")
            workers = int(env) if env else 8
            _pool = ThreadPoolExecutor(
                max_workers=max(1, workers), thread_name_prefix="pqt-hedge"
            )
        return _pool


class _LatencyWindow:
    """A bounded ring of recent read latencies with on-demand quantiles
    (128 floats: the sort is cheaper than any streaming sketch at this
    size, and the window forgets a past latency regime in ~128 reads)."""

    __slots__ = ("_buf", "_n", "_next", "_lock")

    def __init__(self, size: int = 128):
        self._buf = [0.0] * size
        self._n = 0  # filled entries
        self._next = 0  # ring cursor
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            self._buf[self._next] = seconds
            self._next = (self._next + 1) % len(self._buf)
            if self._n < len(self._buf):
                self._n += 1

    def quantile(self, q: float) -> float | None:
        with self._lock:
            if self._n < 8:  # too few samples to call a tail
                return None
            vals = sorted(self._buf[: self._n])
        k = min(self._n - 1, max(0, int(q * self._n)))
        return vals[k]


class HedgedSource(ByteSource):
    """Duplicate a read that has outlived the observed latency quantile;
    first result wins.

    Every read runs as a task on the pqt-hedge pool. The caller waits
    `hedge delay` = clamp(quantile(`delay_quantile`) of the last ~128 read
    latencies, [`min_delay_s`, `max_delay_s`]) for the primary; past that it
    launches ONE duplicate and returns whichever finishes first with data.
    The loser is cancelled if still queued; if running, its completion is
    absorbed by a done-callback that records the latency and swallows the
    result/exception. Both copies failing raises the primary's error.

    Wrap OUTSIDE RetryingSource for independent retry ladders per copy, or
    INSIDE so the ladder retries a hedged read as one unit. Not free: each
    read pays a pool hop, so this belongs on ~ms-latency (remote-shaped)
    sources, not raw local files.
    """

    def __init__(
        self,
        inner: ByteSource,
        *,
        delay_quantile: float = 0.95,
        min_delay_s: float = 0.01,
        max_delay_s: float = 1.0,
        initial_delay_s: float = 0.05,
        window: int = 128,
        clock=time.perf_counter,
    ):
        if not 0.0 < delay_quantile < 1.0:
            raise ValueError("hedge: delay_quantile must be in (0, 1)")
        if min_delay_s < 0 or max_delay_s < min_delay_s:
            raise ValueError("hedge: need 0 <= min_delay_s <= max_delay_s")
        self.inner = inner
        self.delay_quantile = float(delay_quantile)
        self.min_delay_s = float(min_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.initial_delay_s = float(initial_delay_s)
        self._clock = clock
        self._window = _LatencyWindow(window)
        self.hedges_launched = 0
        self.hedges_won = 0

    @property
    def source_id(self) -> str:
        return self.inner.source_id

    def generation(self):
        gen = getattr(self.inner, "generation", None)
        return gen() if gen is not None else None

    def size(self) -> int:
        return self.inner.size()

    def hedge_delay(self) -> float:
        """The current stall bar: the latency-window quantile clamped to
        [min_delay_s, max_delay_s] (initial_delay_s until the window has
        enough samples to call a tail)."""
        q = self._window.quantile(self.delay_quantile)
        if q is None:
            q = self.initial_delay_s
        return min(self.max_delay_s, max(self.min_delay_s, q))

    def _timed_read(self, offset: int, n: int) -> bytes:
        t0 = self._clock()
        try:
            return self.inner.read_at(offset, n)
        finally:
            self._window.record(self._clock() - t0)

    def read_at(self, offset: int, n: int) -> bytes:
        # lazy import: obs.pool imports metrics which is fine, but keep the
        # module import graph acyclic (planner also imports obs.pool)
        from ..obs.pool import instrumented_submit

        delay = self.hedge_delay()
        primary = instrumented_submit(
            hedge_pool(), self._timed_read, offset, n, pool="pqt-hedge"
        )
        try:
            # a primary failing BEFORE the bar propagates from here: there
            # is nothing to race, retry ladders handle plain failure
            return primary.result(timeout=delay)
        except _FutTimeout:
            pass
        # the primary outlived the bar: race a duplicate
        hedge = instrumented_submit(
            hedge_pool(), self._timed_read, offset, n, pool="pqt-hedge"
        )
        self.hedges_launched += 1
        _metrics.inc("io_hedges_total", outcome="launched")
        # per-request attribution beside the process-wide counter: the
        # hedge launch is visible in this request's merged trace
        _trace.count("io.hedge")
        _log_event(
            "hedged_read", delay_ms=round(delay * 1e3, 3), offset=offset,
            nbytes=n, source=self.inner.source_id,
        )
        return self._race(primary, hedge)

    def _race(self, primary, hedge) -> bytes:
        """First copy to return data wins; the loser is cancelled or
        absorbed. Both failing re-raises the primary's error (the hedge's
        is the same fault one more time, not new information)."""
        from concurrent.futures import FIRST_COMPLETED, wait

        pending = {primary: "primary", hedge: "hedge"}
        first_error = {}
        while pending:
            done, _ = wait(list(pending), return_when=FIRST_COMPLETED)
            for fut in done:
                who = pending.pop(fut)
                err = fut.exception()
                if err is None:
                    self._absorb(pending)
                    if who == "hedge":
                        self.hedges_won += 1
                    _metrics.inc("io_hedges_total", outcome=f"win_{who}")
                    return fut.result()
                first_error[who] = err
        _metrics.inc("io_hedges_total", outcome="failed")
        raise first_error.get("primary") or first_error["hedge"]

    @staticmethod
    def _absorb(pending: dict) -> None:
        """Cancel still-queued losers; running ones get a callback that
        retrieves their outcome so a late failure never surfaces as an
        'exception was never retrieved' warning."""
        for fut in pending:
            if not fut.cancel():
                fut.add_done_callback(
                    lambda f: f.cancelled() or f.exception()
                )

    def read_ranges(self, ranges) -> list:
        return [self.read_at(off, n) for off, n in ranges]

    def close(self) -> None:
        self.inner.close()


# -- circuit breaker -----------------------------------------------------------

_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half_open"
_STATE_GAUGE = {_CLOSED: 0, _OPEN: 1, _HALF_OPEN: 2}


class CircuitBreaker:
    """closed -> open -> half-open failure gate for one source.

    Closed: reads pass; `failure_threshold` CONSECUTIVE failures trip it
    open (any success resets the streak). Open: `before_read()` fast-fails
    with SourceError(code="breaker_open") — no transport touch, no retry
    ladder spin — until `open_s` has elapsed on the injected clock. Then
    half-open: ONE probe read is admitted (concurrent readers keep
    fast-failing); its success closes the breaker, its failure re-opens it
    and re-arms the timer. Thread-safe; every transition is logged and
    mirrored on the io_breaker_state{source=} gauge."""

    def __init__(
        self,
        source_id: str,
        *,
        failure_threshold: int = 5,
        open_s: float = 5.0,
        clock=time.monotonic,
        label: str | None = None,
    ):
        if failure_threshold < 1:
            raise ValueError("breaker: failure_threshold must be >= 1")
        if open_s <= 0:
            raise ValueError("breaker: open_s must be positive")
        self.source_id = source_id
        self.failure_threshold = int(failure_threshold)
        self.open_s = float(open_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = _CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        # the gauge label: bounded/sanitized by the registry (NOT the raw
        # source_id, which embeds paths and mtimes)
        self._label = label if label is not None else source_id[:96]
        self._set_gauge()

    def _set_gauge(self) -> None:
        _metrics.set_gauge(
            "io_breaker_state", _STATE_GAUGE[self._state], source=self._label
        )

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        # lock held
        if (
            self._state == _OPEN
            and self._clock() - self._opened_at >= self.open_s
        ):
            self._state = _HALF_OPEN
            self._probing = False
            self._set_gauge()

    def _transition(self, state: str, event: str) -> None:
        # lock held
        self._state = state
        self._set_gauge()
        _log_event(
            f"breaker_{event}", level="warning", source=self._label,
            failures=self._failures,
        )

    def before_read(self) -> None:
        """The admission gate: raises the typed fast-fail while open, and
        claims the single half-open probe slot."""
        with self._lock:
            self._maybe_half_open()
            if self._state == _CLOSED:
                return
            if self._state == _HALF_OPEN and not self._probing:
                self._probing = True  # this caller IS the probe
                return
        raise SourceError(
            f"breaker open for source {self._label}: fast-failing reads "
            f"for {self.open_s:.1f}s after {self.failure_threshold} "
            "consecutive failures",
            code="breaker_open",
        )

    def abort_probe(self) -> None:
        """Release the half-open probe slot without a verdict — the read
        never reached the transport (a ValueError caller bug), so it says
        nothing about source health. Without this, a probe that dies
        pre-flight would leave _probing latched and every later read
        fast-failing forever."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != _CLOSED:
                self._transition(_CLOSED, "closed")
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == _HALF_OPEN:
                # the probe failed: back to open, timer re-armed
                self._opened_at = self._clock()
                self._probing = False
                self._transition(_OPEN, "reopened")
            elif (
                self._state == _CLOSED
                and self._failures >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                self._transition(_OPEN, "opened")


class BreakerRegistry:
    """Process-wide breakers keyed by source_id, BOUNDED like every other
    externally-keyed table in this codebase: past `max_sources` distinct
    ids, the least-recently-used CLOSED breaker is evicted (its gauge
    zeroed); when every breaker is open — a full-fleet blackout — new
    sources share the overflow breaker rather than growing the table."""

    OVERFLOW = "__overflow__"

    def __init__(self, *, max_sources: int = 256, clock=time.monotonic,
                 **breaker_kw):
        if max_sources < 1:
            raise ValueError("breaker registry: max_sources must be >= 1")
        self.max_sources = int(max_sources)
        self._clock = clock
        self._kw = breaker_kw
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    def _label_for(self, source_id: str, n: int) -> str:
        # one bounded, readable gauge label per breaker slot: the basename
        # tail of the id (paths dominate), truncated, uniquified by slot
        tail = source_id.rsplit("/", 1)[-1][:64]
        return f"{tail}#{n}"

    def breaker_for(self, source_id: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(source_id)
            if b is not None:
                return b
            if len(self._breakers) >= self.max_sources:
                victim = next(
                    (
                        k
                        for k, v in self._breakers.items()
                        if v.state == _CLOSED and k != self.OVERFLOW
                    ),
                    None,
                )
                if victim is not None:
                    ev = self._breakers.pop(victim)
                    _metrics.set_gauge(
                        "io_breaker_state", 0, source=ev._label
                    )
                else:
                    source_id = self.OVERFLOW
                    b = self._breakers.get(source_id)
                    if b is not None:
                        return b
            b = CircuitBreaker(
                source_id,
                clock=self._clock,
                label=self._label_for(source_id, len(self._breakers)),
                **self._kw,
            )
            self._breakers[source_id] = b
            return b

    def states(self) -> dict:
        """{source_id: state} right now (tests/diagnostics)."""
        with self._lock:
            items = list(self._breakers.items())
        return {k: b.state for k, b in items}

    def reset(self) -> None:
        """Drop every breaker (tests, chaos-harness teardown)."""
        with self._lock:
            breakers = list(self._breakers.values())
            self._breakers.clear()
        for b in breakers:
            _metrics.set_gauge("io_breaker_state", 0, source=b._label)


_default_registry: BreakerRegistry | None = None
_default_registry_lock = threading.Lock()


def breaker_registry() -> BreakerRegistry:
    """The process-wide breaker registry (shared by every BreakerSource
    that wasn't handed an explicit breaker — reader, dataset and daemon
    reads of one blacked-out file all trip ONE breaker)."""
    global _default_registry
    with _default_registry_lock:
        if _default_registry is None:
            _default_registry = BreakerRegistry()
        return _default_registry


class BreakerSource(ByteSource):
    """A ByteSource gated by a CircuitBreaker.

    Each read asks the breaker first (typed fast-fail while open), then
    reports the outcome. ValueError (caller bugs: negative ranges) and the
    breaker's own fast-fail never count as source failures; everything
    else — OSError, short-read SourceError, a nested retry ladder's
    exhaustion — does."""

    def __init__(self, inner: ByteSource, breaker: CircuitBreaker | None = None,
                 *, registry: BreakerRegistry | None = None):
        self.inner = inner
        if breaker is None:
            reg = registry if registry is not None else breaker_registry()
            breaker = reg.breaker_for(inner.source_id)
        self.breaker = breaker

    @property
    def source_id(self) -> str:
        return self.inner.source_id

    def generation(self):
        gen = getattr(self.inner, "generation", None)
        return gen() if gen is not None else None

    def size(self) -> int:
        return self.inner.size()

    def read_at(self, offset: int, n: int) -> bytes:
        self.breaker.before_read()
        try:
            buf = self.inner.read_at(offset, n)
        except ValueError:
            # caller bug, not source health — but a claimed half-open
            # probe slot must be released or the breaker latches
            self.breaker.abort_probe()
            raise
        except Exception:
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        return buf

    def read_ranges(self, ranges) -> list:
        # per-range accounting: one blacked-out range trips the breaker at
        # the same cadence batched and unbatched readers observe
        return [self.read_at(off, n) for off, n in ranges]

    def close(self) -> None:
        self.inner.close()


# -- the resilience policy open_source applies ---------------------------------


@dataclass
class ResilienceConfig:
    """What open_source wraps every concrete source with. All-off by
    default (the wrap is the identity). `chaos_wrapper` is the innermost
    layer — the chaos harness injects its scheduled FlakySource THERE, so
    the breaker/retry/hedge stack under test sits above the faults exactly
    as it would above a faulty transport."""

    breaker: bool = False
    breaker_kw: dict = field(default_factory=dict)
    retry: bool = False
    retry_kw: dict = field(default_factory=dict)
    hedge: bool = False
    hedge_kw: dict = field(default_factory=dict)
    chaos_wrapper: object = None  # fn(ByteSource) -> ByteSource, innermost
    registry: BreakerRegistry | None = None

    @property
    def active(self) -> bool:
        return bool(
            self.breaker or self.retry or self.hedge or self.chaos_wrapper
        )


_config = ResilienceConfig()
_config_lock = threading.Lock()


def configure_resilience(config: ResilienceConfig | None) -> ResilienceConfig:
    """Install the process-wide resilience policy (None resets to all-off).
    Returns the PREVIOUS config so scoped users (chaos harness, tests)
    can restore it."""
    global _config
    with _config_lock:
        prev = _config
        cfg = config if config is not None else ResilienceConfig()
        if cfg.breaker and cfg.registry is None and cfg.breaker_kw:
            # non-default breaker knobs need their own registry (the shared
            # one was built with defaults and its breakers are keyed, not
            # parameterized, per source)
            cfg.registry = BreakerRegistry(**cfg.breaker_kw)
        _config = cfg
        return prev


def resilience_config() -> ResilienceConfig:
    with _config_lock:
        return _config


def wrap_resilient(source: ByteSource) -> ByteSource:
    """Apply the installed policy to a freshly opened concrete source:
    chaos (innermost) -> breaker -> retry -> hedge (outermost). With the
    default all-off policy this returns `source` unchanged. The breaker
    sits UNDER retry so the ladder counts raw faults and the typed
    breaker_open fast-fail is terminal to it; the hedge sits on TOP so a
    duplicate read carries its own full retry ladder."""
    cfg = resilience_config()
    if not cfg.active:
        return source
    if cfg.chaos_wrapper is not None:
        source = cfg.chaos_wrapper(source)
    if cfg.breaker:
        source = BreakerSource(source, registry=cfg.registry)
    if cfg.retry:
        source = RetryingSource(source, **cfg.retry_kw)
    if cfg.hedge:
        source = HedgedSource(source, **cfg.hedge_kw)
    return source

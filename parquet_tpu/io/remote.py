"""Remote byte sources: HTTP(S) range GETs and signed-URL object stores.

The first transport that is not the local filesystem — the scenario the
whole IO stack above this module was shaped for. PR 5's coalescing and
budgeted readahead, and PR 10's breaker/retry/hedge stack at the
`open_source` choke point, were built for ~ms-latency range reads; this
module puts an actual remote store under them:

  HttpSource         a ByteSource over one HTTP(S) URL. Every read_at is
                     a `Range: bytes=a-b` GET on a pooled persistent
                     connection (stdlib http.client — no new deps); size
                     and ETag come from one HEAD at open (with a
                     range-GET fallback for HEAD-less servers) and pin
                     the object GENERATION: the ETag rides the source_id
                     (so caches can never mix generations) and every
                     response is validated against it — an object
                     rewritten mid-read is a typed error, not silent
                     corruption. Batched read_ranges fans the ranges out
                     as concurrent in-flight GETs on the pqt-io pool.
  ObjectStoreSource  the S3/GCS-style presigned-URL variant: a `sign`
                     hook supplies (url, expires_at); reads re-sign
                     before the expiry horizon (refresh_margin_s) and
                     once more reactively when the store answers 403 —
                     credential rotation costs one extra round trip, not
                     a failed scan. The generation carries ACROSS
                     re-signs, so a re-signed URL pointing at different
                     bytes is caught like any rewrite.

Failure taxonomy (what the resilience stack keys on):

  terminal   -> SourceError(code=...): http_404, http_403, http_416,
               other 4xx, source_changed (ETag/size drift), read past
               EOF. The retry ladder treats SourceError as terminal —
               retrying a 404 is pure backoff waste.
  transient  -> TransientSourceError(code=...), an OSError subclass the
               retry ladder retries naturally: http_5xx, http_408/429,
               truncated_body (fewer bytes than the 206 promised),
               transport faults (reset/timeout/BadStatusLine).

URLs compose like any path: `open_source("https://...")` builds an
HttpSource and applies the installed resilience policy, so FileReader,
ParquetDataset units and readahead over URLs inherit breaker -> retry ->
hedge with zero per-callsite wiring.

Metrics: io_http_requests_total{status=}, io_http_connections_total
{event=new|reused}, io_resigns_total (documented in utils/metrics.py).
"""

from __future__ import annotations

import http.client
import re
import threading
import time
from urllib.parse import urlsplit

from ..obs import propagate as _propagate
from ..obs.log import log_event as _log_event
from ..utils import metrics as _metrics
from ..utils import trace as _trace
from .source import ByteSource, SourceError, _count_read

__all__ = [
    "HttpSource",
    "ObjectStoreSource",
    "TransientSourceError",
    "host_pool",
    "pooled_roundtrip",
]

_MAX_HOST_POOLS = 64


class TransientSourceError(OSError):
    """A retryable transport fault (5xx, truncated body, reset): an
    OSError subclass so RetryingSource's default retry_on absorbs it, but
    typed — `code` names the fault ("http_503", "truncated_body") for
    tests and for the SourceError(code="retry_exhausted") chain when the
    ladder gives up."""

    def __init__(self, *args, code: str | None = None):
        super().__init__(*args)
        self.code = code


class _HostPool:
    """Persistent connections to ONE (scheme, host, port), checked out per
    request and returned after a fully-drained response. Bounded: past
    `max_idle` parked connections, a returned one is simply closed."""

    def __init__(self, scheme: str, host: str, port: int, *, max_idle: int = 8):
        self.scheme = scheme
        self.host = host
        self.port = port
        self.max_idle = int(max_idle)
        self._lock = threading.Lock()
        self._idle: list = []
        self._closed = False

    def acquire(self, timeout_s: float):
        """-> (connection, reused). `reused` matters to the caller: a
        parked keep-alive the server closed in the meantime fails the
        NEXT request through no fault of the source, and only reused
        connections earn the one fresh-connection retry."""
        with self._lock:
            if self._idle:
                conn = self._idle.pop()
                _metrics.inc("io_http_connections_total", event="reused")
                return conn, True
        cls = (
            http.client.HTTPSConnection
            if self.scheme == "https"
            else http.client.HTTPConnection
        )
        _metrics.inc("io_http_connections_total", event="new")
        return cls(self.host, self.port, timeout=timeout_s), False

    def release(self, conn) -> None:
        with self._lock:
            if not self._closed and len(self._idle) < self.max_idle:
                self._idle.append(conn)
                return
        conn.close()

    def discard(self, conn) -> None:
        conn.close()

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
            self._closed = True
        for c in idle:
            c.close()


_pools: dict[tuple, _HostPool] = {}
_pools_lock = threading.Lock()


def host_pool(scheme: str, host: str, port: int) -> _HostPool:
    """The process-wide connection pool for one origin (every HttpSource
    to one store shares it — a thousand-shard corpus does not open a
    thousand sockets). Bounded at _MAX_HOST_POOLS origins, oldest-idle
    closed past it."""
    key = (scheme, host, port)
    with _pools_lock:
        pool = _pools.get(key)
        if pool is None:
            if len(_pools) >= _MAX_HOST_POOLS:
                _, victim = next(iter(_pools.items()))
                del _pools[(victim.scheme, victim.host, victim.port)]
                victim.close()
            pool = _HostPool(scheme, host, port)
            _pools[key] = pool
        return pool


def _default_port(scheme: str) -> int:
    return 443 if scheme == "https" else 80


def _status_error(status: int, reason: str, context: str):
    """Map one HTTP status to the failure taxonomy (returns an exception
    to raise; 2xx never reaches here)."""
    msg = f"{context}: HTTP {status} {reason}"
    if status >= 500 or status in (408, 429):
        return TransientSourceError(msg, code=f"http_{status}")
    return SourceError(msg, code=f"http_{status}")


def pooled_roundtrip(
    pool: _HostPool,
    method: str,
    target: str,
    headers: dict,
    *,
    body: bytes | None = None,
    timeout_s: float = 20.0,
    counter: str = "io_http_requests_total",
):
    """One request on a pooled connection — the shared transport core of
    HttpSource reads AND remote_sink's PUT path. Returns (status, reason,
    headers, body); transport-level failures discard the connection and
    surface as TransientSourceError(code="transport").

    A transport fault on a REUSED connection gets one silent retry on a
    fresh socket first: a parked keep-alive the server idle-closed says
    nothing about source health, and every mainstream HTTP client absorbs
    that shape rather than failing the call (with the default all-off
    resilience policy there is no ladder above to catch it). The retry
    resends `body` verbatim — every caller's requests are idempotent
    (range GET, part PUT, complete-by-manifest)."""
    for attempt in (0, 1):
        conn, reused = pool.acquire(timeout_s)
        try:
            conn.request(method, target, body=body, headers=headers)
            resp = conn.getresponse()
            # the body MUST drain fully before the connection can be
            # reused; HEAD bodies are empty by contract
            resp_body = resp.read()
        except (http.client.HTTPException, OSError, EOFError) as e:
            pool.discard(conn)
            if isinstance(e, (SourceError, TransientSourceError)):
                raise
            if reused and attempt == 0:
                continue  # stale keep-alive: once more, fresh socket
            raise TransientSourceError(
                f"http transport fault on {pool.host}:{pool.port}: "
                f"{type(e).__name__}: {e}",
                code="transport",
            ) from e
        _metrics.inc(counter, status=str(resp.status))
        if resp.will_close:
            pool.discard(conn)
        else:
            pool.release(conn)
        return resp.status, resp.reason, resp.headers, resp_body


class HttpSource(ByteSource):
    """Range-GET ByteSource over one HTTP(S) URL (see module docstring).

    `size`/`etag` may be passed by a caller that already knows them (the
    ObjectStoreSource re-sign path) to skip the opening HEAD — they PIN
    the expected generation. `headers` are sent with every request
    (auth tokens etc.). Thread-safe: concurrent read_at calls each check
    a connection out of the shared per-host pool."""

    def __init__(
        self,
        url: str,
        *,
        timeout_s: float = 20.0,
        headers: dict | None = None,
        size: int | None = None,
        etag: str | None = None,
        signer=None,
    ):
        split = urlsplit(url)
        if split.scheme not in ("http", "https"):
            raise ValueError(
                f"HttpSource: unsupported scheme {split.scheme!r} in {url!r}"
            )
        if not split.hostname:
            raise ValueError(f"HttpSource: no host in {url!r}")
        self.url = url
        self.timeout_s = float(timeout_s)
        self.headers = dict(headers or {})
        self._scheme = split.scheme
        self._host = split.hostname
        self._port = split.port or _default_port(split.scheme)
        path = split.path or "/"
        self._target = f"{path}?{split.query}" if split.query else path
        self._pool = host_pool(self._scheme, self._host, self._port)
        if signer is None:
            # the registry seam: open_source("https://...") picks up header
            # signing with zero per-callsite wiring (resolved ONCE, here)
            from .sign import signer_for

            signer = signer_for(url)
        self._signer = signer
        # one-shot multi-range batches until the server proves it only
        # speaks single-range (read_ranges latches this False)
        self._multirange = True
        if size is None:
            self._size, self._etag = self._stat()
        else:
            self._size, self._etag = int(size), etag
        netloc = (
            self._host
            if self._port == _default_port(self._scheme)
            else f"{self._host}:{self._port}"
        )
        # the QUERY is deliberately excluded: a presigned URL's rotating
        # signature must not fracture the cache identity of one object
        self._id = (
            f"http:{self._scheme}://{netloc}{path}"
            f"#{self._etag or '-'}:{self._size}"
        )

    # -- identity --------------------------------------------------------------

    @property
    def source_id(self) -> str:
        return self._id

    def generation(self):
        """(size, etag): what pins this object's content generation (the
        FooterCache validates URL-keyed footers against it, the way local
        paths validate against (size, mtime))."""
        return (self._size, self._etag)

    def size(self) -> int:
        return self._size

    # -- one HTTP round trip ---------------------------------------------------

    def _request(self, method: str, extra_headers: dict | None = None):
        """One request on a pooled connection (see pooled_roundtrip, which
        holds the transport-fault semantics): merges the instance headers,
        applies the header-auth signer when one is bound."""
        hdrs = dict(self.headers)
        if extra_headers:
            hdrs.update(extra_headers)
        if self._signer is not None:
            hdrs.update(self._signer.headers(method, self.url, b""))
        tp = _propagate.outbound_traceparent("get")
        if tp is not None:
            # every call gets its own child span-id under the request's
            # trace — a store-side access log lines up per attempt
            hdrs["traceparent"] = tp
        return pooled_roundtrip(
            self._pool, method, self._target, hdrs, timeout_s=self.timeout_s
        )

    def _stat(self) -> tuple:
        """Learn (size, etag) via HEAD, falling back to a 1-byte range GET
        for servers that reject HEAD (405/501). One transient fault gets
        one short-backoff retry HERE: the stat runs at construction,
        BEFORE open_source has wrapped the source in the resilience
        policy, so without it a single 503 on open fails a scan the
        ladder would have absorbed one call later."""
        try:
            return self._stat_once()
        except TransientSourceError:
            time.sleep(0.05)
            return self._stat_once()

    def _stat_once(self) -> tuple:
        status, reason, headers, _ = self._request("HEAD")
        if status == 200:
            length = headers.get("Content-Length")
            if length is None:
                raise SourceError(
                    f"HEAD {self.url}: no Content-Length", code="no_size"
                )
            return int(length), headers.get("ETag")
        if status in (405, 501):
            status, reason, headers, body = self._request(
                "GET", {"Range": "bytes=0-0"}
            )
            if status == 206:
                total = (headers.get("Content-Range") or "").rpartition("/")[2]
                if total.isdigit():
                    return int(total), headers.get("ETag")
            if status == 200:
                return len(body), headers.get("ETag")
        raise _status_error(status, reason, f"stat of {self.url}")

    # -- reads -----------------------------------------------------------------

    def _validate_generation(self, headers, context: str) -> None:
        etag = headers.get("ETag")
        if self._etag and etag and etag != self._etag:
            raise SourceError(
                f"{context}: object changed (ETag {self._etag} -> {etag})",
                code="source_changed",
            )
        total = (headers.get("Content-Range") or "").rpartition("/")[2]
        if total.isdigit() and int(total) != self._size:
            raise SourceError(
                f"{context}: object changed (size {self._size} -> {total})",
                code="source_changed",
            )

    def read_at(self, offset: int, n: int) -> bytes:
        if offset < 0 or n < 0:
            raise ValueError(f"read_at({offset}, {n}): negative offset/length")
        if n == 0:
            return b""
        if offset + n > self._size:
            raise SourceError(
                f"read past end of {self.url}: "
                f"[{offset}, {offset + n}) > {self._size}"
            )
        context = f"GET {self.url} [{offset}, {offset + n})"
        hdrs = {"Range": f"bytes={offset}-{offset + n - 1}"}
        if self._etag:
            # mid-scan revalidation: a server seeing a stale validator
            # answers 200 + the CURRENT full body instead of a 206 slice
            # of bytes that no longer exist — the 200 path below then
            # surfaces the rewrite as a typed source_changed rather than
            # silently mis-slicing the new generation
            hdrs["If-Range"] = self._etag
        # remote.get rides the request's DecodeTrace as a child span; the
        # args dict is committed by reference, so the status lands on the
        # span once the response is in
        span_args = {"offset": offset, "nbytes": n}
        with _trace.span("remote.get", args=span_args):
            t0 = time.perf_counter()
            status, reason, headers, body = self._request("GET", hdrs)
            dt = time.perf_counter() - t0
            span_args["status"] = status
        if status == 206:
            self._validate_generation(headers, context)
            if len(body) != n:
                # the transfer closed short of the promised range — the
                # transport shape RetryingSource exists to re-read
                raise TransientSourceError(
                    f"{context}: truncated body ({len(body)}/{n} bytes)",
                    code="truncated_body",
                )
            _count_read(n)
            self._observe(n, dt)
            return body
        if status == 200:
            # a server that ignores Range — or one whose If-Range check
            # failed — ships the whole CURRENT object; honest accounting
            # bills the full transfer
            self._validate_generation(headers, context)
            declared = headers.get("Content-Length")
            if declared is not None and declared.isdigit() and (
                int(declared) != self._size
            ):
                # an ETag-less server can only betray a rewrite by length
                raise SourceError(
                    f"{context}: object changed "
                    f"(size {self._size} -> {declared})",
                    code="source_changed",
                )
            if len(body) < offset + n:
                raise TransientSourceError(
                    f"{context}: truncated body "
                    f"({len(body)}/{self._size} bytes of a full-object 200)",
                    code="truncated_body",
                )
            _count_read(len(body))
            self._observe(len(body), dt)
            return body[offset : offset + n]
        raise _status_error(status, reason, context)

    def _observe(self, nbytes: int, seconds: float) -> None:
        # the SOURCE feeds the IO tuner, per request: fetch_ranges times a
        # whole batch, but read_ranges here executes its runs CONCURRENTLY
        # on pqt-io, so batch-wall / runs would underestimate per-request
        # latency by up to the pool width — only the request site knows
        # the true number (fetch_ranges skips non-"local" profiles for
        # exactly this reason)
        from .autotune import io_tuner

        io_tuner().observe(self._id, nbytes, seconds, 1)

    def read_ranges(self, ranges) -> list:
        """N coalesced runs in ONE round trip when the server speaks
        multi-range (`Range: bytes=a-b,c-d` -> 206 multipart/byteranges),
        else concurrent per-range GETs on the pqt-io pool (one pooled
        connection each). The first response proving the server doesn't
        do multi-range (single-part 206, or a 416 on the comma form)
        latches the fallback for this source's lifetime; transport faults
        fall back for THIS call without latching. From INSIDE a pqt-io
        worker (readahead tasks run there) the fan-out degrades to
        sequential — a bounded pool that submits to itself and waits is a
        deadlock."""
        ranges = list(ranges)
        if (
            len(ranges) > 1
            and self._multirange
            and sum(n for _, n in ranges) > 0
        ):
            got = self._read_multirange(ranges)
            if got is not None:
                return got
        if (
            len(ranges) <= 1
            or threading.current_thread().name.startswith("pqt-io")
        ):
            return [self.read_at(off, n) for off, n in ranges]
        from ..obs.pool import instrumented_submit
        from .planner import io_pool

        futs = [
            instrumented_submit(io_pool(), self.read_at, off, n, pool="pqt-io")
            for off, n in ranges
        ]
        out, first_err = [], None
        for f in futs:
            try:
                out.append(f.result())
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first_err is None:
                    first_err = e
                out.append(None)
        if first_err is not None:
            raise first_err
        return out

    # -- multi-range: N runs, one round trip -----------------------------------

    def _read_multirange(self, ranges):
        """One `Range: bytes=a-b,c-d` GET for every run. Returns the
        payload list on success, None to fall back to per-range GETs —
        never raises for "the server doesn't do multi-range" (that is
        the expected legacy shape, not a fault). Terminal generation
        mismatches and transport faults DO raise, exactly like read_at
        (the retry/validation ladder above owns those)."""
        for off, n in ranges:
            if off < 0 or n < 0 or off + n > self._size:
                raise SourceError(
                    f"read past end of {self.url}: "
                    f"[{off}, {off + n}) > {self._size}"
                )
        spec = ",".join(f"{off}-{off + n - 1}" for off, n in ranges if n)
        hdrs = {"Range": f"bytes={spec}"}
        if self._etag:
            hdrs["If-Range"] = self._etag
        context = f"GET {self.url} [{len(ranges)} ranges]"
        span_args = {"ranges": len(ranges), "nbytes": sum(n for _, n in ranges)}
        with _trace.span("remote.multirange", args=span_args):
            t0 = time.perf_counter()
            try:
                status, reason, headers, body = self._request("GET", hdrs)
            except TransientSourceError:
                # a transport fault says nothing about multi-range
                # support: fall back THIS call, try again next time
                _count_multirange("transport_fallback")
                return None
            dt = time.perf_counter() - t0
            span_args["status"] = status
        ctype = (headers.get("Content-Type") or "").lower()
        if status == 206 and ctype.startswith("multipart/byteranges"):
            self._validate_generation(headers, context)
            parts = _parse_multipart_byteranges(body, ctype)
            if parts is None:
                _count_multirange("parse_fallback")
                return None
            out = []
            for off, n in ranges:
                if n == 0:
                    out.append(b"")
                    continue
                payload = parts.get((off, off + n - 1))
                if payload is None or len(payload) != n:
                    _count_multirange("parse_fallback")
                    return None
                out.append(payload)
            nbytes = sum(len(p) for p in out)
            _count_read(nbytes)
            _metrics.inc("io_multirange_parts_total", len(parts))
            _count_multirange("ok")
            self._observe(nbytes, dt)
            return out
        if status == 200:
            # a Range-blind server ships the whole CURRENT object: one
            # transfer still answers every run — slice locally (and bill
            # the full body, like read_at's 200 path)
            self._validate_generation(headers, context)
            if len(body) < self._size:
                raise TransientSourceError(
                    f"{context}: truncated body "
                    f"({len(body)}/{self._size} bytes of a full-object 200)",
                    code="truncated_body",
                )
            _count_read(len(body))
            _count_multirange("full_body")
            self._observe(len(body), dt)
            return [body[off : off + n] for off, n in ranges]
        if status in (206, 416):
            # single-part 206 (the server honored ONE range) or a 416 on
            # the comma form: a legacy server — latch per-range forever
            self._multirange = False
            _count_multirange("unsupported")
            return None
        raise _status_error(status, reason, context)

    def close(self) -> None:
        pass  # connections belong to the shared per-host pool


def _count_multirange(outcome: str) -> None:
    _metrics.inc("io_multirange_requests_total", outcome=outcome)


def _parse_multipart_byteranges(body: bytes, content_type: str):
    """multipart/byteranges -> {(first, last): payload}. None on any
    structural surprise (missing boundary, malformed part headers, a
    Content-Range that doesn't parse) — the caller falls back to
    per-range GETs rather than guessing."""
    m = re.search(r'boundary="?([^";,\s]+)"?', content_type)
    if m is None:
        return None
    delim = b"--" + m.group(1).encode("ascii", "replace")
    parts: dict = {}
    segments = body.split(delim)
    # segments[0] is the preamble; the last begins with "--" (the close)
    for seg in segments[1:]:
        if seg.startswith(b"--"):
            break
        seg = seg.lstrip(b"\r\n")
        head, sep, payload = seg.partition(b"\r\n\r\n")
        if not sep:
            return None
        content_range = None
        for line in head.split(b"\r\n"):
            name, _, value = line.partition(b":")
            if name.strip().lower() == b"content-range":
                content_range = value.strip()
        if content_range is None:
            return None
        cm = re.match(rb"bytes (\d+)-(\d+)/(\d+|\*)", content_range)
        if cm is None:
            return None
        first, last = int(cm.group(1)), int(cm.group(2))
        # each part ends with the CRLF that precedes the next delimiter
        if payload.endswith(b"\r\n"):
            payload = payload[:-2]
        if len(payload) != last - first + 1:
            return None
        parts[(first, last)] = payload
    return parts or None


class ObjectStoreSource(ByteSource):
    """Presigned-URL object read (S3/GCS shape): HttpSource + a re-signing
    hook.

    `sign()` returns the current presigned URL — either a plain string or
    (url, expires_at_epoch_s). Reads re-sign proactively within
    `refresh_margin_s` of expiry and REACTIVELY once per read when the
    store answers 401/403 (clock skew, rotated credentials); both count
    io_resigns_total. The object's (size, ETag) generation is learned
    once and pinned across re-signs — a re-signed URL resolving to
    different bytes raises the same typed source_changed as any rewrite.
    """

    def __init__(
        self,
        sign,
        *,
        refresh_margin_s: float = 30.0,
        clock=time.time,
        timeout_s: float = 20.0,
        headers: dict | None = None,
    ):
        if not callable(sign):
            raise TypeError("ObjectStoreSource: sign must be callable")
        self._sign = sign
        self.refresh_margin_s = float(refresh_margin_s)
        self._clock = clock
        self._timeout_s = timeout_s
        self._headers = headers
        self._lock = threading.Lock()
        self._inner: HttpSource | None = None
        self._expires_at: float | None = None
        self._ensure()

    def _resign(self) -> None:
        # lock held
        signed = self._sign()
        url, expires_at = (
            signed if isinstance(signed, tuple) else (signed, None)
        )
        prev = self._inner
        self._inner = HttpSource(
            url,
            timeout_s=self._timeout_s,
            headers=self._headers,
            # carry the pinned generation across re-signs (and skip the
            # re-HEAD); the first sign learns it from the store
            size=prev._size if prev is not None else None,
            etag=prev._etag if prev is not None else None,
        )
        self._expires_at = float(expires_at) if expires_at is not None else None
        if prev is not None:
            _metrics.inc("io_resigns_total")
            _log_event(
                "source_resigned", source=self._inner.source_id,
                expires_at=self._expires_at,
            )

    def _ensure(self) -> HttpSource:
        with self._lock:
            if self._inner is None or (
                self._expires_at is not None
                and self._clock() >= self._expires_at - self.refresh_margin_s
            ):
                self._resign()
            return self._inner

    def _force_resign(self, stale: HttpSource) -> HttpSource:
        with self._lock:
            if self._inner is stale:  # a racing reader may have re-signed
                self._resign()
            return self._inner

    @property
    def source_id(self) -> str:
        return self._ensure().source_id

    def generation(self):
        return self._ensure().generation()

    def size(self) -> int:
        return self._ensure().size()

    @staticmethod
    def _auth_rejected(e: SourceError) -> bool:
        return getattr(e, "code", None) in ("http_401", "http_403")

    def read_at(self, offset: int, n: int) -> bytes:
        inner = self._ensure()
        try:
            return inner.read_at(offset, n)
        except SourceError as e:
            if not self._auth_rejected(e):
                raise
            # the signature the store judged, not the clock we guessed:
            # re-sign once and retry this read before giving up
            return self._force_resign(inner).read_at(offset, n)

    def read_ranges(self, ranges) -> list:
        ranges = list(ranges)
        inner = self._ensure()
        try:
            return inner.read_ranges(ranges)
        except SourceError as e:
            if not self._auth_rejected(e):
                raise
            return self._force_resign(inner).read_ranges(ranges)

    def close(self) -> None:
        pass

"""Request signing: SigV4-style header auth for remote reads AND writes.

PR 13's ObjectStoreSource covered the *presigned URL* shape — the store
hands out a rotating `?token=...` query and judges it server-side. This
module adds the other half of real object-store auth: HEADER signing,
where the client holds long-lived credentials and signs every request
itself (the AWS SigV4 family). The scheme here, `PQT4-HMAC-SHA256`, is a
faithful structural clone of SigV4 — canonical request -> string-to-sign
-> derived-key HMAC chain — with its own prefix so nothing ever mistakes
it for a real AWS signature:

    x-pqt-date            YYYYMMDDTHHMMSSZ (the signer's injectable clock)
    x-pqt-content-sha256  hex SHA-256 of the request payload (b"" for
                          GET/HEAD) — the body is IN the signature, so a
                          tampered part PUT fails verification
    Authorization         PQT4-HMAC-SHA256 Credential=<key>/<scope>,
                          SignedHeaders=host;x-pqt-content-sha256;
                          x-pqt-date, Signature=<hex>

Symmetry is the point: `SigV4Signer.headers()` (the client) and
`verify_request()` (the server — testing/httpstub.py's signed mode) share
ONE canonicalization, so a signature the stub accepts is bit-identical to
what the client computed — signed GETs and signed PUTs are provable
hermetically in the same test.

Wiring: `configure_signer(signer, prefix=...)` registers a signer for a
URL prefix (longest prefix wins); `signer_for(url)` is consulted by
HttpSource and HttpSink at construction when no explicit signer is
passed — so `open_source("https://...")` / `open_sink` coercion pick up
signing with zero per-callsite plumbing. Every signed request counts
io_sign_requests_total{method=}.
"""

from __future__ import annotations

import calendar as _calendar
import hashlib
import hmac
import threading
import time
from urllib.parse import urlsplit

from ..utils import metrics as _metrics

__all__ = [
    "SigV4Signer",
    "sign_headers",
    "verify_request",
    "configure_signer",
    "signer_for",
    "clear_signers",
]

_SCHEME = "PQT4-HMAC-SHA256"
_TERMINATOR = "pqt4_request"
# the headers every PQT4 signature covers, in canonical (sorted) order
_SIGNED_HEADERS = "host;x-pqt-content-sha256;x-pqt-date"
_DEFAULT_SKEW_S = 300.0


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).digest()


def _canonical_query(query: str) -> str:
    """Sorted `k=v` pairs — the pair ORDER must not change the signature
    (clients build query strings in whatever order), but the pair CONTENT
    must (swapping partNumber between two uploads is an attack). Values
    are taken as transmitted: both sides canonicalize the same raw string,
    so no re-encoding pass is needed (or wanted — it would have to agree
    byte-for-byte with every client's encoder)."""
    if not query:
        return ""
    return "&".join(sorted(query.split("&")))


def _canonical_request(
    method: str, path: str, query: str, host: str, date: str, payload_hash: str
) -> str:
    canonical_headers = (
        f"host:{host.strip()}\n"
        f"x-pqt-content-sha256:{payload_hash}\n"
        f"x-pqt-date:{date}\n"
    )
    return "\n".join(
        (
            method.upper(),
            path or "/",
            _canonical_query(query),
            canonical_headers,
            _SIGNED_HEADERS,
            payload_hash,
        )
    )


def _scope(datestamp: str, region: str, service: str) -> str:
    return f"{datestamp}/{region}/{service}/{_TERMINATOR}"


def _signing_key(
    secret_key: str, datestamp: str, region: str, service: str
) -> bytes:
    """The SigV4 key-derivation chain: the long-lived secret never signs a
    request directly — a per-(day, region, service) key does, so a leaked
    derived key expires with its scope."""
    k = _hmac(("PQT4" + secret_key).encode("utf-8"), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, _TERMINATOR)


def _signature(
    secret_key: str,
    method: str,
    path: str,
    query: str,
    host: str,
    date: str,
    payload_hash: str,
    region: str,
    service: str,
) -> str:
    datestamp = date[:8]
    creq = _canonical_request(method, path, query, host, date, payload_hash)
    string_to_sign = "\n".join(
        (
            _SCHEME,
            date,
            _scope(datestamp, region, service),
            _sha256_hex(creq.encode("utf-8")),
        )
    )
    key = _signing_key(secret_key, datestamp, region, service)
    return hmac.new(
        key, string_to_sign.encode("utf-8"), hashlib.sha256
    ).hexdigest()


def sign_headers(
    method: str,
    url: str,
    payload: bytes = b"",
    *,
    access_key: str,
    secret_key: str,
    region: str = "local",
    service: str = "pqt",
    clock=time.time,
) -> dict:
    """The headers that make one request verifiable: x-pqt-date,
    x-pqt-content-sha256, Authorization. Pure function of (request,
    credentials, clock) — the functional core SigV4Signer wraps."""
    split = urlsplit(url)
    host = split.netloc
    date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(clock()))
    payload_hash = _sha256_hex(bytes(payload))
    sig = _signature(
        secret_key,
        method,
        split.path or "/",
        split.query,
        host,
        date,
        payload_hash,
        region,
        service,
    )
    credential = f"{access_key}/{_scope(date[:8], region, service)}"
    return {
        "x-pqt-date": date,
        "x-pqt-content-sha256": payload_hash,
        "Authorization": (
            f"{_SCHEME} Credential={credential}, "
            f"SignedHeaders={_SIGNED_HEADERS}, Signature={sig}"
        ),
    }


class SigV4Signer:
    """A bound (credentials, region/service scope, clock) that signs
    requests. Thread-safe (stateless past construction); the clock is
    injectable so tests pin the date and replay exact signatures."""

    def __init__(
        self,
        access_key: str,
        secret_key: str,
        *,
        region: str = "local",
        service: str = "pqt",
        clock=time.time,
    ):
        if not access_key or not secret_key:
            raise ValueError("SigV4Signer: access_key and secret_key required")
        self.access_key = str(access_key)
        self._secret_key = str(secret_key)
        self.region = str(region)
        self.service = str(service)
        self._clock = clock

    def headers(self, method: str, url: str, payload: bytes = b"") -> dict:
        """Headers to merge into one outgoing request (counted per sign)."""
        _metrics.inc("io_sign_requests_total", method=str(method).upper())
        return sign_headers(
            method,
            url,
            payload,
            access_key=self.access_key,
            secret_key=self._secret_key,
            region=self.region,
            service=self.service,
            clock=self._clock,
        )

    def __repr__(self) -> str:  # never leak the secret into logs
        return (
            f"SigV4Signer(access_key={self.access_key!r}, "
            f"region={self.region!r}, service={self.service!r})"
        )


def _parse_authorization(value: str):
    """-> (access_key, scope, signed_headers, signature) or None."""
    if not value or not value.startswith(_SCHEME + " "):
        return None
    fields = {}
    for part in value[len(_SCHEME) + 1 :].split(","):
        k, sep, v = part.strip().partition("=")
        if sep:
            fields[k] = v
    credential = fields.get("Credential", "")
    key, sep, scope = credential.partition("/")
    if not sep or not key:
        return None
    return (
        key,
        scope,
        fields.get("SignedHeaders", ""),
        fields.get("Signature", ""),
    )


def verify_request(
    method: str,
    target: str,
    headers,
    payload: bytes,
    secret_for,
    *,
    host: str | None = None,
    clock=time.time,
    max_skew_s: float = _DEFAULT_SKEW_S,
) -> str | None:
    """Server-side verification (httpstub's signed mode): returns None when
    the request verifies, else a short reason string for the 403 body.

    `headers` is any Mapping with case-insensitive .get (http.client's
    HTTPMessage qualifies); `secret_for(access_key)` returns the secret or
    None for an unknown key; `host` overrides the received Host header
    (proxies). Constant-time signature compare; the payload hash is
    checked FIRST so a tampered body fails even before key lookup."""
    auth = _parse_authorization(headers.get("Authorization") or "")
    if auth is None:
        return "missing_or_malformed_authorization"
    access_key, scope, signed_headers, signature = auth
    if signed_headers != _SIGNED_HEADERS:
        return "unexpected_signed_headers"
    date = headers.get("x-pqt-date") or ""
    if len(date) != 16 or not date.endswith("Z"):
        return "missing_or_malformed_date"
    try:
        then = _calendar.timegm(time.strptime(date, "%Y%m%dT%H%M%SZ"))
    except ValueError:
        return "missing_or_malformed_date"
    if abs(clock() - then) > max_skew_s:
        return "date_skew"
    declared_hash = headers.get("x-pqt-content-sha256") or ""
    if not hmac.compare_digest(declared_hash, _sha256_hex(bytes(payload))):
        return "payload_hash_mismatch"
    secret = secret_for(access_key)
    if secret is None:
        return "unknown_access_key"
    scope_parts = scope.split("/")
    if (
        len(scope_parts) != 4
        or scope_parts[0] != date[:8]
        or scope_parts[3] != _TERMINATOR
    ):
        return "malformed_scope"
    _, region, service, _ = scope_parts
    path, _, query = target.partition("?")
    expected = _signature(
        secret,
        method,
        path or "/",
        query,
        host if host is not None else (headers.get("Host") or ""),
        date,
        declared_hash,
        region,
        service,
    )
    if not hmac.compare_digest(expected, signature):
        return "signature_mismatch"
    return None


# -- the signer registry (what open_source/open_sink coercion consults) --------

_registry_lock = threading.Lock()
_registry: list[tuple[str, object]] = []  # (url prefix, signer)


def configure_signer(signer, *, prefix: str = "") -> None:
    """Register `signer` for URLs starting with `prefix` ("" = every URL).
    Longest matching prefix wins at lookup; passing signer=None removes
    the prefix's entry. Consulted at SOURCE/SINK CONSTRUCTION — sources
    already open keep the signer they resolved."""
    with _registry_lock:
        _registry[:] = [(p, s) for p, s in _registry if p != prefix]
        if signer is not None:
            _registry.append((prefix, signer))
            _registry.sort(key=lambda ps: len(ps[0]), reverse=True)


def signer_for(url: str):
    """The registered signer whose prefix matches `url` (longest wins), or
    None — the default header-auth resolution for HttpSource/HttpSink."""
    with _registry_lock:
        for prefix, signer in _registry:
            if url.startswith(prefix):
                return signer
    return None


def clear_signers() -> None:
    """Drop every registered signer (test teardown)."""
    with _registry_lock:
        _registry.clear()

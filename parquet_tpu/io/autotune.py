"""Latency-aware IO auto-tuning: pick coalesce/readahead knobs per source.

The planner's coalesce gap answers one question — below how many wasted
gap bytes is merging two ranges into one read cheaper than paying a second
request? The answer is the transport's bandwidth-delay product: on a local
NVMe pread (~50us, ~GB/s) the break-even sits around the 64 KiB default;
on a ~25ms-RTT object store the same math says *megabytes*, and the fixed
local default issues dozens of tiny range GETs where one fat read would
do. PR 5 left the knob manual (`coalesce_gap=` / PQT_IO_GAP); this module
closes the loop:

  IOTuner       per-transport EWMAs of observed read behavior, fed from
                fetch_ranges (the one choke point every planner-batched
                read already passes): per-RUN latency (seconds / runs in
                the batch) and achieved bandwidth (bytes / seconds).
                `params_for()` turns them into an IOParams — coalesce gap
                and readahead budget — by the bandwidth-delay product,
                clamped between the LOCAL profile (the 64 KiB default,
                modest readahead) and the REMOTE ceiling (MiB-scale gap,
                deep readahead).
  profile_key   the aggregation key: transports, not files. Every
                LocalFileSource collapses to "local", every HttpSource to
                its "http(s)://host:port" — a thousand-shard corpus on one
                store trains ONE profile, and a fresh file on a known-slow
                store starts tuned.

Consumers opt in with the string "auto" where they would pass a gap:
`FileReader(coalesce_gap="auto")`, `ParquetDataset(io_autotune=True)`,
`ServeConfig(io_autotune=True)`. Resolution happens inside fetch_ranges /
Readahead, so the first read of an unknown transport uses the LOCAL
profile (64 KiB — correct for the common case and merely suboptimal for a
remote one) and every read after it is tuned by what the transport
actually did. Below `remote_floor_s` of per-run latency the tuner returns
the LOCAL profile EXACTLY: observation noise on a fast local disk must
never perturb the default byte-for-byte behavior tests pin.

Observation is always on (one lock + three float updates per BATCHED
read, not per range); only knob RESOLUTION is opt-in. The gauges
io_autotune_gap_bytes{profile=} / io_autotune_latency_ms{profile=} mirror
each profile's current verdict for operators; `io_tuner().stats()` is the
debug-vars form.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import NamedTuple

from ..utils import metrics as _metrics

__all__ = [
    "IOParams",
    "IOTuner",
    "io_tuner",
    "profile_key",
    "LOCAL_GAP",
    "LOCAL_READAHEAD",
    "MAX_GAP",
    "MAX_READAHEAD",
]

# the LOCAL profile: the PR 5 defaults, what an untrained (or provably
# fast) transport resolves to — auto-tuning must be a no-op until the
# observed latency says otherwise
LOCAL_GAP = 64 << 10
LOCAL_READAHEAD = 8 << 20

# the REMOTE ceiling: one merged read never grows past MAX_GAP of pure
# gap waste, and the readahead budget recommendation stays bounded
MAX_GAP = 8 << 20
MAX_READAHEAD = 128 << 20

# assume this floor bandwidth until a transport demonstrates one: the
# very first high-latency observation should already coalesce harder
# instead of waiting for a bandwidth estimate to converge
_FLOOR_BANDWIDTH = 8 << 20  # 8 MiB/s


class IOParams(NamedTuple):
    """One transport's tuned knobs (what `params_for` returns)."""

    coalesce_gap: int
    readahead_bytes: int
    latency_s: float  # the EWMA per-run latency behind the verdict
    bandwidth_bps: float  # the EWMA achieved bandwidth behind the verdict
    observations: int

    @property
    def remote(self) -> bool:
        """Whether the transport tuned AWAY from the local profile."""
        return self.coalesce_gap > LOCAL_GAP


def profile_key(source_id_or_path: str) -> str:
    """Collapse a source_id (or a path/URL) to its TRANSPORT key.

    "http:https://host:9000/bucket/obj#etag:123" -> "https://host:9000"
    "http://host/file.parquet"                   -> "http://host"
    "file:/data/x.parquet:41:9:17"               -> "local"
    anything else (mem:, custom sources)         -> "local"

    Files on one store share latency physics, not names — profiling per
    transport is what lets shard #2 start with shard #1's tuning."""
    s = str(source_id_or_path)
    # an HttpSource source_id prefixes the URL with "http:" — strip the
    # tag, not a plain URL's scheme
    if s.startswith(("http:http://", "http:https://")):
        s = s[5:]
    if s.startswith(("http://", "https://")):
        scheme, _, rest = s.partition("://")
        host = rest.split("/", 1)[0].split("#", 1)[0]
        return f"{scheme}://{host}" if host else "local"
    return "local"


class _Profile:
    __slots__ = ("latency_s", "bandwidth_bps", "observations")

    def __init__(self):
        self.latency_s = 0.0
        self.bandwidth_bps = 0.0
        self.observations = 0


class IOTuner:
    """EWMA-per-transport observer + knob resolver (thread-safe).

    alpha            EWMA weight of the newest observation
    remote_floor_s   per-run latency below which a transport IS the local
                     profile (noise guard: a loaded CI box must not
                     re-tune local preads)
    min_observations observations before a profile may deviate from local
    max_profiles     bound on distinct transport keys (LRU evicted)
    """

    def __init__(
        self,
        *,
        alpha: float = 0.25,
        remote_floor_s: float = 0.002,
        min_observations: int = 3,
        readahead_depth: int = 16,
        max_profiles: int = 64,
    ):
        if not 0.0 < alpha <= 1.0:
            raise ValueError("autotune: alpha must be in (0, 1]")
        if min_observations < 1:
            raise ValueError("autotune: min_observations must be >= 1")
        self.alpha = float(alpha)
        self.remote_floor_s = float(remote_floor_s)
        self.min_observations = int(min_observations)
        self.readahead_depth = int(readahead_depth)
        self.max_profiles = int(max_profiles)
        self._lock = threading.Lock()
        self._profiles: OrderedDict[str, _Profile] = OrderedDict()

    # -- observation (fed by fetch_ranges, always on) --------------------------

    def observe(
        self, source_id: str, nbytes: int, seconds: float, runs: int = 1
    ) -> None:
        """Record one batched read: `runs` transport requests moving
        `nbytes` in `seconds` of wall. Degenerate observations (zero
        bytes, non-positive wall) are dropped, not averaged."""
        if nbytes <= 0 or seconds <= 0 or runs <= 0:
            return
        key = profile_key(source_id)
        per_run = seconds / runs
        bw = nbytes / seconds
        with self._lock:
            p = self._profiles.get(key)
            if p is None:
                p = _Profile()
                self._profiles[key] = p
                while len(self._profiles) > self.max_profiles:
                    self._profiles.popitem(last=False)
            else:
                self._profiles.move_to_end(key)
            if p.observations == 0:
                p.latency_s, p.bandwidth_bps = per_run, bw
            else:
                a = self.alpha
                p.latency_s += a * (per_run - p.latency_s)
                p.bandwidth_bps += a * (bw - p.bandwidth_bps)
            p.observations += 1
            lat_ms, params = self._params_locked(key, p)
        # gauges outside the tuner lock (the registry has its own)
        _metrics.set_gauge(
            "io_autotune_gap_bytes", params.coalesce_gap, profile=key
        )
        _metrics.set_gauge("io_autotune_latency_ms", lat_ms, profile=key)

    # -- resolution ------------------------------------------------------------

    def _params_locked(self, key: str, p: _Profile | None):
        if (
            p is None
            or p.observations < self.min_observations
            or p.latency_s < self.remote_floor_s
        ):
            lat = 0.0 if p is None else p.latency_s
            bw = 0.0 if p is None else p.bandwidth_bps
            n = 0 if p is None else p.observations
            return round(lat * 1e3, 3), IOParams(
                LOCAL_GAP, LOCAL_READAHEAD, lat, bw, n
            )
        # the bandwidth-delay product: the bytes the transport could have
        # delivered in the time one more request costs — below that, gap
        # bytes are cheaper than a second round trip
        bdp = p.latency_s * max(p.bandwidth_bps, _FLOOR_BANDWIDTH)
        gap = int(min(MAX_GAP, max(LOCAL_GAP, bdp)))
        readahead = int(
            min(
                MAX_READAHEAD,
                max(LOCAL_READAHEAD, bdp * self.readahead_depth),
            )
        )
        return round(p.latency_s * 1e3, 3), IOParams(
            gap, readahead, p.latency_s, p.bandwidth_bps, p.observations
        )

    def params_for(self, source_id_or_path: str) -> IOParams:
        """The tuned knobs for a source/path/URL (LOCAL profile when the
        transport is unknown, under-observed, or provably fast)."""
        key = profile_key(source_id_or_path)
        with self._lock:
            return self._params_locked(key, self._profiles.get(key))[1]

    def gap_for(self, source_id_or_path: str) -> int:
        return self.params_for(source_id_or_path).coalesce_gap

    def readahead_for(self, source_id_or_path: str) -> int:
        return self.params_for(source_id_or_path).readahead_bytes

    # -- introspection ---------------------------------------------------------

    def stats(self) -> dict:
        """Per-transport snapshot for /v1/debug/vars and tests."""
        with self._lock:
            keys = list(self._profiles)
        out = {}
        for key in keys:
            with self._lock:
                p = self._profiles.get(key)
                if p is None:
                    continue
                lat_ms, params = self._params_locked(key, p)
            out[key] = {
                "latency_ms": lat_ms,
                "bandwidth_mb_s": round(params.bandwidth_bps / 1e6, 3),
                "observations": params.observations,
                "coalesce_gap": params.coalesce_gap,
                "readahead_bytes": params.readahead_bytes,
                "remote": params.remote,
            }
        return out

    def reset(self) -> None:
        """Forget every profile (tests, bench runs that must start cold)."""
        with self._lock:
            self._profiles.clear()


_tuner: IOTuner | None = None
_tuner_lock = threading.Lock()


def io_tuner() -> IOTuner:
    """The process-wide tuner every fetch_ranges call feeds — reader,
    dataset workers and the serve daemon all train (and consult) ONE set
    of transport profiles."""
    global _tuner
    with _tuner_lock:
        if _tuner is None:
            _tuner = IOTuner()
        return _tuner

"""Byte-budgeted block cache + footer/metadata cache for the IO layer.

Two caches, two lifetimes:

  BlockCache    (source_id, offset, len) -> bytes, LRU under a byte budget.
                Holds COMPRESSED chunk/page-index ranges, so a re-read (a
                second epoch, a retried unit, two readers over one file)
                skips the source entirely. Keyed on the source's content
                identity (LocalFileSource folds size+mtime+inode in), so a
                rewritten file can never serve another generation's bytes.

  FooterCache   path -> parsed FileMetaData, validated against the file's
                (size, mtime_ns) on every hit. Parsing a footer is pure CPU
                (thrift walk) plus one tail read; a dataset re-planning a
                thousand-file glob every epoch — or open_many across jobs
                in one process — pays it once here.

Both report always-on metrics: io_cache_hits_total / io_cache_misses_total
and the io_cache_bytes gauge for blocks, io_footer_cache_hits_total /
io_footer_cache_misses_total for footers.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from ..utils import metrics as _metrics
from ..utils.trace import count as _trace_count

__all__ = ["BlockCache", "FooterCache", "shared_footer_cache"]


class BlockCache:
    """LRU byte-range cache under a byte budget (thread-safe)."""

    def __init__(self, capacity_bytes: int = 64 << 20):
        if capacity_bytes <= 0:
            raise ValueError("BlockCache capacity must be positive")
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        self._blocks: OrderedDict[tuple, bytes] = OrderedDict()
        self._bytes = 0

    def get(self, source_id: str, offset: int, length: int):
        """The cached bytes for one exact range, or None (counted)."""
        key = (source_id, offset, length)
        with self._lock:
            buf = self._blocks.get(key)
            if buf is not None:
                self._blocks.move_to_end(key)
                _metrics.inc("io_cache_hits_total")
                # trace-only count (the registry line above already owns
                # the always-on counter): a request-scoped trace carries
                # its own hit/miss split — how the serve cost ledger
                # attributes cache outcomes per tenant. Costs one
                # contextvar read when no trace is active.
                _trace_count("io_cache_hit")
                return buf
        _metrics.inc("io_cache_misses_total")
        _trace_count("io_cache_miss")
        return None

    def put(self, source_id: str, offset: int, length: int, data) -> None:
        data = bytes(data)
        if len(data) > self.capacity_bytes:
            return  # a block bigger than the whole budget would just thrash
        key = (source_id, offset, length)
        with self._lock:
            old = self._blocks.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._blocks[key] = data
            self._bytes += len(data)
            while self._bytes > self.capacity_bytes:
                _k, evicted = self._blocks.popitem(last=False)
                self._bytes -= len(evicted)
                _metrics.inc("io_cache_evictions_total")
            _metrics.set_gauge("io_cache_bytes", self._bytes)

    def invalidate(self, source_id: str) -> None:
        """Drop every block of one source (a file known to be rewritten)."""
        with self._lock:
            for key in [k for k in self._blocks if k[0] == source_id]:
                self._bytes -= len(self._blocks.pop(key))
            _metrics.set_gauge("io_cache_bytes", self._bytes)

    def clear(self) -> None:
        with self._lock:
            self._blocks.clear()
            self._bytes = 0
            _metrics.set_gauge("io_cache_bytes", 0)

    def stats(self) -> dict:
        with self._lock:
            return {
                "blocks": len(self._blocks),
                "bytes": self._bytes,
                "capacity_bytes": self.capacity_bytes,
            }


class FooterCache:
    """Parsed-footer cache validated by (size, mtime_ns) per hit.

    A hit returns the SAME FileMetaData object; footers are treated as
    immutable by every consumer (the reader only walks them). max_entries
    bounds the footprint LRU-style — footers are small (KBs) but a service
    scanning rolling datasets should not grow without bound."""

    def __init__(self, max_entries: int = 4096):
        if max_entries <= 0:
            raise ValueError("FooterCache max_entries must be positive")
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        # path -> ((st_size, st_mtime_ns), FileMetaData)
        self._entries: OrderedDict[str, tuple] = OrderedDict()

    @staticmethod
    def _sig(path: str):
        st = os.stat(path)
        return (st.st_size, st.st_mtime_ns)

    def get(self, path, sig=None):
        """The cached FileMetaData for `path` when the file on disk still
        matches the cached generation; None (counted as a miss) otherwise.
        A stat failure — vanished file — is a miss too: the caller's open
        will raise the real error with its real context.

        `sig` overrides the stat-derived signature for keys that are not
        stat-able paths: a URL-keyed footer validates against the remote
        source's generation() — (size, ETag) — instead of (size, mtime)."""
        path = os.fspath(path)
        if sig is None:
            try:
                sig = self._sig(path)
            except OSError:
                sig = None
        with self._lock:
            hit = self._entries.get(path)
            if hit is not None and sig is not None and hit[0] == sig:
                self._entries.move_to_end(path)
                _metrics.inc("io_footer_cache_hits_total")
                return hit[1]
            if hit is not None:
                del self._entries[path]  # stale generation
        _metrics.inc("io_footer_cache_misses_total")
        return None

    def put(self, path, meta, sig=None) -> None:
        path = os.fspath(path)
        if sig is None:
            try:
                sig = self._sig(path)
            except OSError:
                return  # can't pin a generation: don't cache
        with self._lock:
            self._entries[path] = (sig, meta)
            self._entries.move_to_end(path)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


_shared_footer: FooterCache | None = None
_shared_lock = threading.Lock()


def shared_footer_cache() -> FooterCache:
    """The process-wide footer cache (what ScanPlan/ParquetDataset use by
    default, so footers parse once per file generation per process no
    matter how many plans, epochs or dataset objects touch them)."""
    global _shared_footer
    with _shared_lock:
        if _shared_footer is None:
            _shared_footer = FooterCache()
        return _shared_footer

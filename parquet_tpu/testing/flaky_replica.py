"""FlakyReplica: a seeded fault-injecting proxy in front of a LIVE replica.

Where RangeHttpStub fakes an object store, this wraps a real ScanServer
(or any HTTP daemon) and misbehaves at the TRANSPORT layer between the
mesh router and the replica — the layer the MeshClient failover ladder
must absorb. Point a router's --replica at `proxy.url` instead of the
daemon and the daemon's answers stay real; only the wire gets hostile:

    replica = ScanServer(ServeConfig(port=0, root=d)).start_background()
    proxy = FlakyReplica(replica.url, seed=7, error_rate=0.2)
    with proxy:
        router = MeshRouter(MeshConfig(port=0, replicas=(proxy.url, ...)))

Fault knobs (plain attributes, mutable mid-test; every draw comes from
ONE seeded numpy rng stream under a lock, so a failing chaos run replays
exactly — the httpstub discipline):

  error_rate   probability a request answers an injected 503 (code
               "injected_fault") WITHOUT reaching the replica — the
               residual-5xx shape that must feed the breaker
  drop_rate    probability the connection dies with NO status line
               (RemoteDisconnected at the client: the reset/LB-kill
               shape -> typed transport failover)
  short_rate   probability a proxied response body is TRUNCATED below
               its declared Content-Length and the socket slammed — the
               TORN REPLICA STREAM shape: the router must fail over and
               re-fetch, never splice the prefix into its merge
  latency_s    per-request injected RTT (feeds the client's p95 window,
               so hedging tests can arm deterministically)
  spike_rate/spike_s  occasional EXTRA stall (the tail the hedge
               duplicates past)
  permanent    every request 503s (blackout; flip mid-test to model a
               replica dying and recovering without restarting anything)

The proxy reads each backend response FULLY before answering, so every
proxied response is Content-Length framed — which is exactly what makes
`short_rate` a clean torn-transfer: declared N, delivered < N, FIN.

Counters: `requests`, `faults_injected`, `proxied`, and `traceparents`
(every traceparent header seen, in arrival order — the replica-side half
of a propagation pin when tests want the hop recorded at the wire).
"""

from __future__ import annotations

import http.client
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit

import numpy as np

__all__ = ["FlakyReplica"]

# headers the proxy must not blindly forward: it re-frames the body with
# Content-Length, and hop-by-hop headers never cross a proxy (RFC 7230)
_HOP_HEADERS = frozenset(
    (
        "connection",
        "content-length",
        "keep-alive",
        "proxy-authenticate",
        "proxy-authorization",
        "te",
        "trailer",
        "transfer-encoding",
        "upgrade",
    )
)


class _ProxyHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    proxy: "FlakyReplica" = None  # set per served proxy via type()

    def log_message(self, fmt, *args):  # quiet: tests read assertions,
        pass  # not access logs

    def _drop(self) -> None:
        # no status line at all: the client sees the connection die
        self.close_connection = True
        try:
            self.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _fail_503(self) -> None:
        body = b'{"error": {"code": "injected_fault", "message": "chaos"}}'
        self.send_response(503)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n > 0 else b""

    def _relay(self, method: str) -> None:
        proxy = self.proxy
        proxy._record_traceparent(self.headers.get("traceparent"))
        body = self._read_body()
        verdict = proxy._draw_and_wait()
        if verdict == "drop":
            self._drop()
            return
        if verdict == "error":
            self._fail_503()
            return
        try:
            status, reason, headers, payload = proxy._roundtrip(
                method, self.path, self.headers, body
            )
        except OSError:
            # the REAL replica is down/gone: surface it as the same
            # transport fault a dead host shows — never a fake answer
            self._drop()
            return
        truncate_to = proxy._maybe_truncate(len(payload))
        self.send_response(status, reason)
        for k, v in headers:
            if k.lower() not in _HOP_HEADERS:
                self.send_header(k, v)
        self.send_header("Content-Length", str(len(payload)))
        if truncate_to is not None:
            self.close_connection = True
        self.end_headers()
        if method == "HEAD":
            return
        sent = payload if truncate_to is None else payload[:truncate_to]
        try:
            self.wfile.write(sent)
        except OSError:
            self.close_connection = True
            return
        if truncate_to is not None:
            # promise len(payload), deliver less, FIN: the client's read
            # raises IncompleteRead — the torn replica stream
            try:
                self.wfile.flush()
                self.connection.shutdown(socket.SHUT_RDWR)
            except (OSError, ValueError):
                pass

    def do_GET(self):
        self._relay("GET")

    def do_HEAD(self):
        self._relay("HEAD")

    def do_POST(self):
        self._relay("POST")

    def do_PUT(self):
        self._relay("PUT")

    def do_DELETE(self):
        self._relay("DELETE")


class FlakyReplica:
    """See module docstring. Construct with the live replica's base URL,
    `start()` (or use as a context manager), route traffic at `url`."""

    def __init__(
        self,
        backend_url: str,
        *,
        seed: int = 0,
        error_rate: float = 0.0,
        drop_rate: float = 0.0,
        short_rate: float = 0.0,
        latency_s: float = 0.0,
        spike_rate: float = 0.0,
        spike_s: float = 0.0,
        permanent: bool = False,
        backend_timeout_s: float = 30.0,
        sleep=time.sleep,
    ):
        parts = urlsplit(backend_url)
        if parts.scheme != "http" or not parts.hostname:
            raise ValueError(
                f"FlakyReplica: need an http://host:port backend, "
                f"got {backend_url!r}"
            )
        self.backend_host = parts.hostname
        self.backend_port = parts.port or 80
        self._rng = np.random.default_rng(seed)
        self.error_rate = float(error_rate)
        self.drop_rate = float(drop_rate)
        self.short_rate = float(short_rate)
        self.latency_s = float(latency_s)
        self.spike_rate = float(spike_rate)
        self.spike_s = float(spike_s)
        self.permanent = bool(permanent)
        self.backend_timeout_s = float(backend_timeout_s)
        self._sleep = sleep
        self._lock = threading.Lock()
        self.requests = 0
        self.faults_injected = 0
        self.proxied = 0
        self.traceparents: list = []
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "FlakyReplica":
        if self._server is not None:
            return self
        handler = type("_FlakyHandler", (_ProxyHandler,), {"proxy": self})
        self._server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="pqt-flaky-replica",
            daemon=True,
        )
        self._thread.start()
        return self

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None

    stop = close

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("FlakyReplica: not started")
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- the backend hop -------------------------------------------------------

    def _roundtrip(self, method, path, headers, body):
        """One fresh-connection round trip to the real replica; the whole
        body is read here so the proxy re-frames with Content-Length."""
        conn = http.client.HTTPConnection(
            self.backend_host, self.backend_port,
            timeout=self.backend_timeout_s,
        )
        try:
            fwd = {
                k: v
                for k, v in headers.items()
                if k.lower() not in _HOP_HEADERS and k.lower() != "host"
            }
            conn.request(method, path, body=body or None, headers=fwd)
            resp = conn.getresponse()
            payload = b"" if method == "HEAD" else resp.read()
            out = (resp.status, resp.reason, resp.getheaders(), payload)
        finally:
            conn.close()
        with self._lock:
            self.proxied += 1
        return out

    # -- seeded fault draws ----------------------------------------------------

    def _draw_and_wait(self) -> str:
        """Latency + the per-request fault draw (seeded, lock-serialized).
        Returns "ok", "error", or "drop"."""
        with self._lock:
            self.requests += 1
            spike = 0.0
            if self.spike_rate and float(self._rng.random()) < self.spike_rate:
                spike = self.spike_s
            verdict = "ok"
            if self.permanent:
                verdict = "error"
            elif self.error_rate or self.drop_rate:
                roll = float(self._rng.random())
                if roll < self.error_rate:
                    verdict = "error"
                elif roll < self.error_rate + self.drop_rate:
                    verdict = "drop"
            if verdict != "ok":
                self.faults_injected += 1
        # sleep OUTSIDE the lock: injected latency must overlap across
        # concurrent requests or it models a single-threaded replica
        if self.latency_s or spike:
            self._sleep(self.latency_s + spike)
        return verdict

    def _maybe_truncate(self, declared: int):
        if declared <= 1:
            return None
        with self._lock:
            if self.short_rate and float(self._rng.random()) < self.short_rate:
                self.faults_injected += 1
                return int(self._rng.integers(0, declared))
        return None

    def _record_traceparent(self, raw) -> None:
        if raw is not None:
            with self._lock:
                self.traceparents.append(str(raw))

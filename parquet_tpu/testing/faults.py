"""Deterministic fault-injection harness for the decode ladder.

Production ingest meets truncated objects, bit-rotted blocks and lying
metadata from foreign writers; this module turns a WELL-FORMED parquet byte
string into a seeded, reproducible stream of corrupted variants — truncations
at arbitrary offsets, bit flips in page payloads, scrambled page headers,
wrong stored CRCs, lying `num_values`/`uncompressed_size`, mangled level
runs — and checks one contract over each:

    a corrupt file may only ever surface as a typed Parquet error
    (ParquetFileError / ChunkError / PageError / ThriftError family) or as a
    byte-identical successful read — never a raw struct.error / zlib.error /
    IndexError / OverflowError, never a hang, never silently wrong data.

Everything is derived from an integer seed (numpy default_rng), so a failing
case replays exactly; tests/test_faults.py runs a fast subset in tier-1 and
an extended sweep under the `slow` marker (`make fuzz`).

    from parquet_tpu.testing.faults import iter_fault_cases, run_case
    for case in iter_fault_cases(pristine_bytes, seed=7):
        run_case(case)           # raises FaultViolation on a contract breach
"""

from __future__ import annotations

import io
from dataclasses import dataclass

import numpy as np

__all__ = [
    "FaultCase",
    "FaultViolation",
    "PageSite",
    "iter_fault_cases",
    "map_pages",
    "run_case",
]


class FaultViolation(AssertionError):
    """A mutation broke the corruption contract: a raw (untyped) exception
    escaped, a must-fail case read "successfully", or a nominally-benign
    mutation silently changed the decoded data."""


@dataclass(frozen=True)
class PageSite:
    """One page's location inside the file, for surgical mutations."""

    group: int
    column: str
    page_index: int
    kind: int  # PageType value (0 data v1, 2 dict, 3 data v2)
    header_offset: int  # absolute byte offset of the Thrift page header
    header_len: int
    payload_offset: int  # absolute byte offset of the stored payload
    payload_len: int


@dataclass(frozen=True)
class FaultCase:
    """One corrupted variant of a pristine file.

    must_fail=True: every read of `data` MUST raise a typed Parquet error
    (the mutation provably breaks an invariant a reader checks). With
    must_fail=False the mutation may be benign (e.g. a flipped bit inside a
    skipped statistics field) — then the read must either raise typed or
    return data byte-identical to the pristine decode (check_data=True).
    check_data=False marks mutations that legitimately alter decoded values
    without ANY detectable trace: a flipped bit in an uncompressed PLAIN
    payload of a CRC-less file is indistinguishable from real data — no
    format on earth detects it, so the harness only asserts typed-or-ok
    there (the case FOR writing page checksums, see README)."""

    name: str
    data: bytes
    must_fail: bool
    validate_crc: bool
    description: str = ""
    check_data: bool = True


def map_pages(data: bytes) -> list[PageSite]:
    """Walk every chunk's pages and return their exact byte locations
    (well-formed input only; the walk itself is core.chunk.iter_page_sites,
    shared with parquet-tool verify so the two agree on page boundaries)."""
    from ..core.chunk import iter_page_sites
    from ..core.reader import FileReader

    sites: list[PageSite] = []
    with FileReader(io.BytesIO(data)) as r:
        for gi in range(r.num_row_groups):
            for path, cc, _col in r._selected_chunks(gi):
                for page_index, (pos, header, hlen, plen) in enumerate(
                    iter_page_sites(r._f, cc)
                ):
                    sites.append(
                        PageSite(
                            group=gi,
                            column=".".join(path),
                            page_index=page_index,
                            kind=header.type or 0,
                            header_offset=pos,
                            header_len=hlen,
                            payload_offset=pos + hlen,
                            payload_len=plen,
                        )
                    )
    return sites


def _parse_header(data: bytes, site: PageSite):
    """The Python-parsed PageHeader at `site`, or None when our declarative
    reader cannot round-trip the writer's exact bytes (then length-preserving
    patches are impossible and patch-based cases are skipped)."""
    from ..meta.parquet_types import PageHeader
    from ..meta.thrift import CompactReader, ThriftError

    window = data[site.header_offset : site.header_offset + site.header_len]
    try:
        header = PageHeader.read(CompactReader(window))
    except ThriftError:
        return None
    if header.dumps() != bytes(window):
        return None  # foreign field order: cannot patch in place
    return header


def _patched(data: bytes, site: PageSite, mutate) -> bytes | None:
    """Re-serialize `site`'s header after `mutate(header)`; splice it back
    in place when (and only when) the byte length is preserved — page and
    footer offsets must not move, the lie is the point."""
    header = _parse_header(data, site)
    if header is None:
        return None
    mutate(header)
    blob = header.dumps()
    if len(blob) != site.header_len:
        return None
    return (
        data[: site.header_offset]
        + blob
        + data[site.header_offset + site.header_len :]
    )


def _first_data_site(sites: list[PageSite]) -> PageSite | None:
    for s in sites:
        if s.kind in (0, 3):
            return s
    return None


def iter_fault_cases(
    data: bytes,
    seed: int,
    truncations: int = 4,
    bit_flips: int = 4,
    header_flips: int = 3,
    validate_crc: bool = True,
):
    """Yield seeded FaultCases over a pristine file's bytes.

    `validate_crc` should be True when the file carries stored page CRCs
    (then payload bit flips are PROVABLY detectable and marked must_fail);
    pass False for CRC-less files — payload flips become may-be-benign
    cases checked for silent wrong data instead."""
    data = bytes(data)
    rng = np.random.default_rng(seed)
    sites = map_pages(data)
    data_sites = [s for s in sites if s.kind in (0, 3) and s.payload_len > 0]

    # -- truncation at arbitrary offsets (always fatal: the footer and the
    #    trailing magic live at the end of the file) ---------------------------
    n = len(data)
    cut_points = [n - 1, n - 4, max(n - 13, 1)]  # magic, footer-len, mid-footer
    cut_points += [int(x) for x in rng.integers(4, max(n - 1, 5), truncations)]
    for off in cut_points:
        yield FaultCase(
            name=f"truncate@{off}",
            data=data[:off],
            must_fail=True,
            validate_crc=validate_crc,
            description=f"file cut to {off}/{n} bytes",
        )

    # -- bit flips inside page payloads ---------------------------------------
    for k in range(bit_flips):
        if not data_sites:
            break
        s = data_sites[int(rng.integers(0, len(data_sites)))]
        off = s.payload_offset + int(rng.integers(0, s.payload_len))
        bit = int(rng.integers(0, 8))
        mutated = bytearray(data)
        mutated[off] ^= 1 << bit
        yield FaultCase(
            name=f"bitflip@{off}.{bit}",
            data=bytes(mutated),
            # a stored CRC covers the whole payload, so under validate_crc
            # the flip is provably detected; without CRCs it may be benign
            # or silent — run_case then checks data identity on success
            must_fail=validate_crc,
            validate_crc=validate_crc,
            description=(
                f"bit {bit} of byte {off} flipped in {s.column} rg{s.group} "
                f"page {s.page_index}"
            ),
            check_data=validate_crc,
        )

    # -- scrambled page headers (may parse to something harmless: skipped
    #    statistics bytes — so not must_fail; wrong data is still checked) -----
    for k in range(header_flips):
        if not sites:
            break
        s = sites[int(rng.integers(0, len(sites)))]
        off = s.header_offset + int(rng.integers(0, s.header_len))
        mutated = bytearray(data)
        mutated[off] ^= 0xFF
        yield FaultCase(
            name=f"hdrflip@{off}",
            data=bytes(mutated),
            must_fail=False,
            validate_crc=validate_crc,
            description=f"header byte {off} xor 0xff in {s.column} rg{s.group}",
        )

    # -- wrong stored CRC (length-preserving header patch) --------------------
    site = _first_data_site(sites)
    if site is not None and validate_crc:
        for delta in (1, 2, 16, 255):
            def bump_crc(h, delta=delta):
                if h.crc is None:
                    raise _Unpatchable
                v = (h.crc ^ delta) & 0xFFFFFFFF
                h.crc = v - (1 << 32) if v >= (1 << 31) else v

            patched = _try_patch(data, site, bump_crc)
            if patched is not None:
                yield FaultCase(
                    name=f"wrong_crc^{delta}",
                    data=patched,
                    must_fail=True,
                    validate_crc=True,
                    description=f"stored CRC xor {delta} on {site.column}",
                )
                break

    # -- lying num_values (the chunk-level count cross-check must trip) -------
    if site is not None:
        for delta in (1, -1, 7):
            def bump_nv(h, delta=delta):
                hh = h.data_page_header or h.data_page_header_v2
                if hh is None or hh.num_values is None or hh.num_values + delta < 0:
                    raise _Unpatchable
                hh.num_values += delta

            patched = _try_patch(data, site, bump_nv)
            if patched is not None:
                yield FaultCase(
                    name=f"lying_num_values{delta:+d}",
                    data=patched,
                    must_fail=True,
                    validate_crc=validate_crc,
                    description=f"num_values {delta:+d} on {site.column}",
                )
                break

    # -- lying uncompressed_size ----------------------------------------------
    if site is not None:
        for delta in (1, -1, 64):
            def bump_us(h, delta=delta):
                if h.uncompressed_page_size is None:
                    raise _Unpatchable
                v = h.uncompressed_page_size + delta
                if v < 0:
                    raise _Unpatchable
                h.uncompressed_page_size = v

            patched = _try_patch(data, site, bump_us)
            if patched is not None:
                yield FaultCase(
                    name=f"lying_uncompressed_size{delta:+d}",
                    data=patched,
                    # an uncompressed chunk's fused walk never consults the
                    # claimed size for V2 raw values, so the read may succeed
                    # with correct bytes; compressed chunks always trip the
                    # size cross-check — either way, typed-or-identical
                    must_fail=False,
                    validate_crc=validate_crc,
                    description=f"uncompressed_page_size {delta:+d} on {site.column}",
                )
                break

    # -- mangled level runs: stomp the first bytes of a data page payload
    #    (V1: the 4-byte level-stream length prefix + first run headers) ------
    if data_sites:
        s = data_sites[0]
        stomp = min(6, s.payload_len)
        mutated = bytearray(data)
        for j in range(stomp):
            mutated[s.payload_offset + j] = int(rng.integers(0, 256))
        yield FaultCase(
            name="bad_level_runs",
            data=bytes(mutated),
            must_fail=validate_crc,  # CRC provably catches the stomp
            validate_crc=validate_crc,
            description=f"first {stomp} payload bytes randomized on {s.column}",
            check_data=validate_crc,
        )

    # -- adversarial footer: giant thrift list length in the schema ----------
    # (preflight size guards must reject it without a multi-GB allocation)
    mutated = bytearray(data)
    # footer layout: [footer bytes][4B len LE][PAR1]; poison the first bytes
    # of the footer with a huge-list header (0xf9 = size-15 marker, list of
    # i64) followed by a maximal varint count
    footer_len = int.from_bytes(data[-8:-4], "little")
    fstart = n - 8 - footer_len
    if footer_len > 12:
        mutated[fstart : fstart + 7] = bytes([0x19, 0xF6]) + b"\xff\xff\xff\xff\x7f"
        yield FaultCase(
            name="footer_giant_list",
            data=bytes(mutated),
            must_fail=True,
            validate_crc=validate_crc,
            description="footer poisoned with an adversarial list length",
        )


class _Unpatchable(Exception):
    pass


def _try_patch(data: bytes, site: PageSite, mutate) -> bytes | None:
    try:
        return _patched(data, site, mutate)
    except _Unpatchable:
        return None


def _read_all(data: bytes, validate_crc: bool, backend: str):
    """Full decode of every row group; returns {path: (num_values, digest)}
    summaries so successful reads can be compared for silent corruption."""
    import hashlib

    from ..core.arrays import ByteArrayData
    from ..core.reader import FileReader

    out = {}
    with FileReader(
        io.BytesIO(data), validate_crc=validate_crc, backend=backend
    ) as r:
        for gi in range(r.num_row_groups):
            for path, cd in r.read_row_group(gi).items():
                v = cd.values
                h = hashlib.sha256()
                if isinstance(v, ByteArrayData):
                    h.update(np.ascontiguousarray(v.offsets).tobytes())
                    h.update(bytes(v.data))
                elif v is not None:
                    h.update(np.ascontiguousarray(np.asarray(v)).tobytes())
                for lv in (cd.def_levels, cd.rep_levels):
                    if lv is not None:
                        h.update(np.ascontiguousarray(np.asarray(lv)).tobytes())
                key = (gi, path)
                out[key] = (cd.num_values, h.hexdigest())
    return out


def run_case(
    case: FaultCase,
    pristine: dict | None = None,
    backend: str = "host",
) -> str:
    """Read a mutated file end-to-end and enforce the corruption contract.

    Returns "error" (a typed Parquet error was raised — the expected outcome
    for real corruption) or "ok" (the mutation was benign). Raises
    FaultViolation when a raw exception escapes, a must_fail case succeeds,
    or a successful read returns data differing from `pristine` (the
    pristine file's _read_all summary — pass it to catch silent corruption).
    `backend` picks the decode ladder rung: "host" is the staged reference
    walk, "tpu_roundtrip" drives the fused native prepare."""
    from ..core.reader import PARQUET_ERRORS

    try:
        got = _read_all(case.data, case.validate_crc, backend)
    except PARQUET_ERRORS:
        return "error"
    except Exception as e:  # noqa: BLE001 — the whole point of the harness
        raise FaultViolation(
            f"{case.name}: raw {type(e).__name__} escaped the decode ladder "
            f"({case.description}): {e!r}"
        ) from e
    if case.must_fail:
        raise FaultViolation(
            f"{case.name}: mutation must raise a typed Parquet error, but the "
            f"read succeeded ({case.description})"
        )
    if case.check_data and pristine is not None and got != pristine:
        raise FaultViolation(
            f"{case.name}: benign-looking mutation silently changed decoded "
            f"data ({case.description})"
        )
    return "ok"

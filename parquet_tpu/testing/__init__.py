"""Test-support tooling shipped with the package: byte-level fault
injection (faults.py) and transport-level fault injection (flaky.py)."""

from .flaky import FlakySource  # noqa: F401

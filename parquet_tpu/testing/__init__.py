"""Test-support tooling shipped with the package: byte-level fault
injection (faults.py), transport-level fault injection (flaky.py), and
wire-level chaos for serve-mesh replicas (flaky_replica.py)."""

from .flaky import FlakySource  # noqa: F401

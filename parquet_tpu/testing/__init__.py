"""Test-support tooling shipped with the package (fault injection)."""

"""Scripted chaos: phased fault schedules driven through the whole stack.

faults.py corrupts bytes, flaky.py corrupts single operations; this module
corrupts TIME — it scripts a fault timeline (phases with durations and
FlakySource/FlakySink knob overrides) and drives it through every byte
source the system opens, so "the store had a latency spike, then an error
burst, then went dark, then recovered" becomes one deterministic,
replayable object:

    schedule = standard_schedule(phase_s=2.0)   # spike -> errors -> blackout -> recovery
    with ChaosHarness(schedule, seed=7, breaker=True, retry=True) as chaos:
        report = run_dataset_chaos(glob, batch_size=4096,
                                   slo_wait_ms=50.0, chaos=chaos)

Pieces:

  Phase / FaultSchedule   the timeline. `params_at(t)` returns the knob
                          overrides of the phase containing `t` (relative
                          to the schedule's armed start). Phases validate
                          their knob names at construction — a typo'd
                          "eror_rate" fails the script, not silently
                          no-ops the burst. Deterministic under fake time:
                          FlakySource reads the schedule through its own
                          injectable clock.
  ChaosHarness            a context manager that (a) arms the schedule,
                          (b) installs a resilience policy through
                          io.hedge.configure_resilience whose innermost
                          chaos_wrapper wraps every concrete source the
                          process opens in a schedule-driven FlakySource
                          (seeded per source_id, so multi-threaded opens
                          stay reproducible), with the breaker/retry/hedge
                          stack under test layered above, and (c) restores
                          the previous policy and resets the breakers on
                          exit — chaos never leaks past its block.
  run_dataset_chaos       stream a ParquetDataset under the harness,
                          timing every next() and attributing it to the
                          phase it landed in; returns per-phase consumer-
                          wait percentiles + SLO violation shares + the
                          hedge/breaker/skip counters — the measured
                          "degraded in typed steps, never collapsed"
                          artifact bench.py --chaos records.

The serve-side chaos run lives in tests/bench (it needs a daemon and HTTP
clients); it builds on the same ChaosHarness via ServeConfig.source_factory
or the installed policy.
"""

from __future__ import annotations

import threading
import time
import zlib
from dataclasses import dataclass, field

from ..utils import metrics as _metrics
from .flaky import _SOURCE_KNOBS, FlakySource

__all__ = [
    "Phase",
    "FaultSchedule",
    "standard_schedule",
    "ChaosHarness",
    "run_dataset_chaos",
    "percentile",
]

# every knob a phase may script (source + sink vocabularies share names;
# sink-only knobs listed explicitly)
_PHASE_KNOBS = set(_SOURCE_KNOBS) | {"flush_error_rate"}


@dataclass(frozen=True)
class Phase:
    """One segment of a fault timeline: `duration_s` of the FlakySource/
    FlakySink overrides in `params` (empty params = healthy)."""

    name: str
    duration_s: float
    params: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.duration_s <= 0:
            raise ValueError(f"phase {self.name!r}: duration_s must be positive")
        unknown = set(self.params) - _PHASE_KNOBS
        if unknown:
            raise ValueError(
                f"phase {self.name!r}: unknown fault knobs {sorted(unknown)} "
                f"(known: {sorted(_PHASE_KNOBS)})"
            )


class FaultSchedule:
    """A sequence of Phases on a time axis.

    The schedule arms at the first `params_at()`/`phase_at()` call (or an
    explicit `start()`), then each query maps clock time to the phase
    containing it. Past the end, the LAST phase's params hold — end a
    timeline with a healthy "recovery" phase to model a store that came
    back. The schedule holds no clock of its own: every consumer passes
    its OWN (injectable) clock's now, which is what makes chaos
    deterministic under fake time."""

    def __init__(self, phases):
        phases = list(phases)
        if not phases:
            raise ValueError("schedule: need at least one phase")
        self.phases = phases
        self.total_s = sum(p.duration_s for p in phases)
        self._t0: float | None = None

    def start(self, now: float) -> "FaultSchedule":
        """Arm the timeline at `now` (idempotent; queries self-arm too)."""
        if self._t0 is None:
            self._t0 = float(now)
        return self

    @property
    def started(self) -> bool:
        return self._t0 is not None

    def phase_at(self, now: float) -> Phase:
        """The Phase containing `now` (arms at `now` on first query; the
        last phase holds past the end)."""
        self.start(now)
        t = now - self._t0
        for p in self.phases:
            if t < p.duration_s:
                return p
            t -= p.duration_s
        return self.phases[-1]

    def params_at(self, now: float) -> dict:
        """The FlakySource/FlakySink overrides in force at `now` (the hook
        flaky.py consults per operation)."""
        return self.phase_at(now).params

    def elapsed(self, now: float) -> float:
        self.start(now)
        return now - self._t0

    def done(self, now: float) -> bool:
        self.start(now)
        return now - self._t0 >= self.total_s


def standard_schedule(
    *,
    phase_s: float = 2.0,
    spike_p: float = 0.3,
    spike_ms: float = 30.0,
    error_rate: float = 0.3,
    warmup_s: float | None = None,
    base: dict | None = None,
) -> FaultSchedule:
    """The canonical four-act chaos timeline: healthy warmup, latency
    spike, error burst, blackout, recovery. One knob (`phase_s`) scales the
    whole run; the individual severities have the defaults the acceptance
    pins were tuned against. `base` (e.g. a constant latency_s modeling a
    remote store) overlays EVERY phase under its own params."""
    base = dict(base or {})
    return FaultSchedule([
        Phase("warmup", warmup_s if warmup_s is not None else phase_s, base),
        Phase("latency_spike", phase_s,
              {**base, "spike_rate": spike_p, "spike_s": spike_ms / 1e3}),
        Phase("error_burst", phase_s, {**base, "error_rate": error_rate}),
        Phase("blackout", phase_s, {**base, "permanent": True}),
        Phase("recovery", phase_s, base),
    ])


class ChaosHarness:
    """Install a schedule-driven fault wrapper (plus the resilience stack
    under test) as the process resilience policy, scoped to a with-block.

    Parameters mirror io.hedge.ResilienceConfig: `breaker`/`retry`/`hedge`
    enable those layers ABOVE the injected faults (breaker_kw/retry_kw/
    hedge_kw pass through). Each wrapper's rng seed mixes `seed`, the
    source_id (crc32) and that source's OPEN ORDINAL — the ordinal
    matters: unit decodes open a fresh source per row group, and a seed
    that were a pure function of source_id would replay the same first
    draw on every one-read open, collapsing "30% of reads spike" into
    all-or-nothing per file. The stream is exactly reproducible when each
    file's opens are sequential (single-threaded tests; the fake-clock
    suites), and statistically faithful under concurrent opens.
    `clock`/`sleep` are injected into every FlakySource (fake time drives
    the phases; a no-op sleep makes latency phases free in unit tests).
    """

    def __init__(
        self,
        schedule: FaultSchedule,
        *,
        seed: int = 0,
        breaker: bool = False,
        retry: bool = False,
        hedge: bool = False,
        breaker_kw: dict | None = None,
        retry_kw: dict | None = None,
        hedge_kw: dict | None = None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        self.schedule = schedule
        self.seed = int(seed)
        self._clock = clock
        self._sleep = sleep
        self._breaker = breaker
        self._retry = retry
        self._hedge = hedge
        self._breaker_kw = dict(breaker_kw or {})
        self._retry_kw = dict(retry_kw or {})
        self._hedge_kw = dict(hedge_kw or {})
        self._prev = None
        self._config = None
        self._wrap_lock = threading.Lock()
        self._ordinals: dict[str, int] = {}  # per-source_id open counter
        self.sources: list[FlakySource] = []  # every wrapper handed out

    # -- the wrapper (usable standalone: ServeConfig.source_factory) -----------

    def wrap(self, source) -> FlakySource:
        """Wrap one ByteSource in a schedule-driven FlakySource (seed mixed
        from the harness seed, the source_id and its open ordinal). Also
        the building block for a daemon's source_factory:
        `lambda path: chaos.wrap(LocalFileSource(path))`."""
        sid = source.source_id
        with self._wrap_lock:
            ordinal = self._ordinals.get(sid, 0)
            self._ordinals[sid] = ordinal + 1
        fs = FlakySource(
            source,
            seed=(
                zlib.crc32(sid.encode()) ^ self.seed ^ (ordinal << 16)
            ) & 0x7FFFFFFF,
            schedule=self.schedule,
            clock=self._clock,
            sleep=self._sleep,
        )
        with self._wrap_lock:
            self.sources.append(fs)
        return fs

    # -- scoped install --------------------------------------------------------

    def __enter__(self) -> "ChaosHarness":
        from ..io.hedge import ResilienceConfig, configure_resilience

        self.schedule.start(self._clock())
        # retries in chaos tests must not sleep real wall time unless the
        # caller wants them to: default the ladder's sleep to the harness's
        retry_kw = dict(self._retry_kw)
        retry_kw.setdefault("sleep", self._sleep)
        self._config = ResilienceConfig(
            breaker=self._breaker,
            breaker_kw=self._breaker_kw,
            retry=self._retry,
            retry_kw=retry_kw,
            hedge=self._hedge,
            hedge_kw=self._hedge_kw,
            chaos_wrapper=self.wrap,
        )
        self._prev = configure_resilience(self._config)
        return self

    def __exit__(self, *exc):
        from ..io.hedge import breaker_registry, configure_resilience

        configure_resilience(self._prev)
        if self._config is not None and self._config.registry is not None:
            self._config.registry.reset()
        else:
            breaker_registry().reset()
        return False

    def faults_injected(self) -> int:
        return sum(s.faults_injected for s in self.sources)

    def spikes_injected(self) -> int:
        return sum(s.spikes_injected for s in self.sources)


def percentile(values, q: float) -> float | None:
    """The q-quantile (0..1) of `values` by rank (None when empty) — the
    chaos report's p50/p99 without a numpy dependency on the hot path."""
    if not values:
        return None
    vals = sorted(values)
    k = min(len(vals) - 1, max(0, int(q * len(vals))))
    return vals[k]


def run_dataset_chaos(
    paths_or_glob,
    *,
    chaos: ChaosHarness,
    batch_size: int,
    slo_wait_ms: float | None = None,
    controller=None,
    enable_controller: bool = True,
    columns=None,
    cache_bytes: int = 0,
    prefetch: int = 2,
    step_s: float = 0.0,
    max_batches: int | None = None,
    until_schedule_done: bool = True,
    dataset_kw: dict | None = None,
) -> dict:
    """Stream a dataset under an (already entered) ChaosHarness and report
    per-phase consumer waits.

    The consumer loop times every `next()` (the wait a train step would
    feel), attributes it to the schedule phase at that moment, optionally
    sleeps `step_s` (a device-bound step), and keeps cycling epochs until
    the schedule has played out (`until_schedule_done`) or `max_batches`.
    Corrupt/blacked-out units quarantine via on_error="skip" — the typed
    degradation under test; a raised error here IS a harness failure.
    `enable_controller=False` keeps the SLO for REPORTING (violation
    counts) but detaches the controller — the A/B bench.py --chaos runs
    to demonstrate the controller is what holds the SLO.

    Returns {"phases": {name: {waits, p50_ms, p99_ms, max_ms, violations,
    violation_share}}, "batches", "rows", "units_skipped", "hedge": {...},
    "breaker_fast_fails", "controller": {...}} — the measured shape of the
    degradation."""
    from ..data.dataset import ParquetDataset

    clock = chaos._clock
    per_phase: dict[str, list[float]] = {p.name: [] for p in chaos.schedule.phases}
    snap0 = _metrics.snapshot()
    kw = dict(dataset_kw or {})
    ds = ParquetDataset(
        paths_or_glob,
        batch_size=batch_size,
        columns=columns,
        prefetch=prefetch,
        num_epochs=None if until_schedule_done else 1,
        remainder="keep",
        on_error="skip",
        cache_bytes=cache_bytes,
        slo_wait_ms=(slo_wait_ms if enable_controller else None),
        controller=controller,
        **kw,
    )
    batches = rows = 0
    t_wall0 = time.perf_counter()
    with ds:
        it = iter(ds)
        while True:
            if max_batches is not None and batches >= max_batches:
                break
            if (
                until_schedule_done
                and chaos.schedule.done(clock())
                and batches > 0
            ):
                break
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            wait_s = time.perf_counter() - t0
            phase = chaos.schedule.phase_at(clock())
            per_phase.setdefault(phase.name, []).append(wait_s)
            batches += 1
            rows += int(next(iter(batch.values())).shape[0])
            if step_s:
                chaos._sleep(step_s)
        it.close()
    wall = time.perf_counter() - t_wall0
    d = _metrics.delta(snap0)
    slo_s = (slo_wait_ms / 1e3) if slo_wait_ms is not None else None
    phases = {}
    for name, waits in per_phase.items():
        viol = (
            sum(1 for w in waits if w > slo_s) if slo_s is not None else 0
        )
        phases[name] = {
            "waits": len(waits),
            "p50_ms": _ms(percentile(waits, 0.50)),
            "p99_ms": _ms(percentile(waits, 0.99)),
            "max_ms": _ms(max(waits) if waits else None),
            "violations": viol,
            "violation_share": (
                round(viol / len(waits), 4) if waits else None
            ),
        }
    hedge = {
        k.split('"')[1]: v
        for k, v in d.items()
        if k.startswith("io_hedges_total")
    }
    return {
        "phases": phases,
        "batches": batches,
        "rows": rows,
        "wall_s": round(wall, 4),
        "slo_wait_ms": slo_wait_ms,
        "units_skipped": d.get('events_total{event="dataset_units_skipped"}', 0),
        "faults_injected": chaos.faults_injected(),
        "spikes_injected": chaos.spikes_injected(),
        "hedge": hedge,
        "retries": sum(
            v for k, v in d.items() if k.startswith("io_retries_total")
        ),
        "slo_violations_total": d.get("dataset_slo_violations_total", 0),
        "controller": (
            ds._controller.state() if ds._controller is not None else None
        ),
    }


def _ms(seconds) -> float | None:
    return None if seconds is None else round(seconds * 1e3, 3)

"""FlakySource/FlakySink: seeded transport-fault injection for the IO layers.

The fault-injection harness in testing/faults.py corrupts BYTES (what a
rotten disk or lying writer produces); this module corrupts the TRANSPORT —
what a loaded object store or flaky NFS mount produces: transient EIO,
short reads, injected latency, and (optionally) permanent failure. Wrapped
around any ByteSource and driven from an integer seed, it gives the retry
ladder (io.source.RetryingSource) a deterministic adversary:

    src = RetryingSource(
        FlakySource(LocalFileSource(path), seed=7, error_rate=0.3),
        sleep=lambda s: None,    # tests: no real backoff waits
    )

Every fault draw comes from one numpy default_rng stream, so a failing test
replays exactly; each CALL re-rolls, so a retried read naturally has a fresh
chance to succeed — the transient-fault shape. `fault_window` confines
faults to a byte region (e.g. only the footer tail); `permanent=True` makes
every read fail, the budget-exhaustion shape.

FlakySink is the WRITE-side mirror: wrapped around any ByteSink it injects
seeded write/flush/commit faults, the adversary for the FileWriter error
path — flush failures must surface as typed WriterError and, because path
sinks commit atomically, the destination must never hold a torn file:

    sink = FlakySink(LocalFileSink(path), seed=7, error_rate=0.3)
    with pytest.raises(WriterError):
        with FileWriter(sink, schema) as w: ...
    assert not os.path.exists(path)          # nothing committed
"""

from __future__ import annotations

import errno as _errno
import time

import numpy as np

__all__ = ["FlakySource", "FlakySink"]


class FlakySource:
    """A ByteSource wrapper injecting seeded transport faults.

    Parameters
    ----------
    inner        the wrapped ByteSource
    seed         rng seed; one stream across all fault draws
    error_rate   probability a read raises a transient OSError(EIO)
    short_rate   probability a read returns a truncated buffer (a contract
                 violation real transports commit; RetryingSource re-reads)
    latency_s    fixed sleep added to every read (the range-GET shape);
                 latency_jitter_s adds a uniform extra draw on top
    spike_rate   probability a read stalls an EXTRA spike_s on top of the
                 base latency — the hot-shard / GC-pause / tail-latency
                 shape (see the latency_spike preset)
    permanent    every read fails with EIO — the budget-exhaustion case
    fault_window (offset, length) confining faults to reads that overlap
                 the window (None = everywhere)
    """

    def __init__(
        self,
        inner,
        *,
        seed: int = 0,
        error_rate: float = 0.0,
        short_rate: float = 0.0,
        latency_s: float = 0.0,
        latency_jitter_s: float = 0.0,
        spike_rate: float = 0.0,
        spike_s: float = 0.0,
        permanent: bool = False,
        fault_window: tuple[int, int] | None = None,
        sleep=time.sleep,
    ):
        self.inner = inner
        self._rng = np.random.default_rng(seed)
        self.error_rate = float(error_rate)
        self.short_rate = float(short_rate)
        self.latency_s = float(latency_s)
        self.latency_jitter_s = float(latency_jitter_s)
        self.spike_rate = float(spike_rate)
        self.spike_s = float(spike_s)
        self.permanent = bool(permanent)
        self.fault_window = fault_window
        self._sleep = sleep
        self.faults_injected = 0
        self.reads = 0
        self.spikes_injected = 0

    @classmethod
    def latency_spike(cls, inner, *, seed: int = 0, p: float = 0.05, ms: float = 50.0, **kw):
        """Preset: a source whose reads occasionally STALL — each read has
        probability `p` of an extra `ms`-millisecond spike (seeded, so a
        failing chaos run replays exactly). The serving-layer adversary: a
        latency-spiked source must produce slow responses or typed
        timeouts, never a hung worker or a torn response body."""
        return cls(inner, seed=seed, spike_rate=p, spike_s=ms / 1e3, **kw)

    @property
    def source_id(self) -> str:
        return self.inner.source_id

    def size(self) -> int:
        return self.inner.size()

    def _in_window(self, offset: int, n: int) -> bool:
        if self.fault_window is None:
            return True
        w_off, w_len = self.fault_window
        return offset < w_off + w_len and offset + n > w_off

    def read_at(self, offset: int, n: int) -> bytes:
        self.reads += 1
        if self.latency_s or self.latency_jitter_s:
            extra = (
                float(self._rng.uniform(0, self.latency_jitter_s))
                if self.latency_jitter_s
                else 0.0
            )
            self._sleep(self.latency_s + extra)
        # spikes draw only when enabled so existing seeds' fault streams
        # are unchanged by the knob's existence
        if self.spike_rate and float(self._rng.random()) < self.spike_rate:
            self.spikes_injected += 1
            self._sleep(self.spike_s)
        if self._in_window(offset, n):
            if self.permanent:
                self.faults_injected += 1
                raise OSError(_errno.EIO, f"injected permanent EIO at {offset}")
            roll = float(self._rng.random())
            if roll < self.error_rate:
                self.faults_injected += 1
                raise OSError(_errno.EIO, f"injected transient EIO at {offset}")
            if roll < self.error_rate + self.short_rate and n > 1:
                self.faults_injected += 1
                cut = int(self._rng.integers(0, n))
                return self.inner.read_at(offset, cut)
        return self.inner.read_at(offset, n)

    def read_ranges(self, ranges) -> list:
        # per-range faults: one flaky range in a batch, not all-or-nothing
        return [self.read_at(off, n) for off, n in ranges]

    def close(self) -> None:
        self.inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class FlakySink:
    """A ByteSink wrapper injecting seeded write-path faults (the mirror of
    FlakySource for the FileWriter/sink error ladder).

    Parameters
    ----------
    inner            the wrapped ByteSink
    seed             rng seed; one stream across all fault draws
    error_rate       probability a write raises a transient OSError(EIO)
                     BEFORE any bytes reach the inner sink (clean failure)
    fail_after_bytes when set, every write past this many successfully
                     written bytes fails — the disk-full / quota shape
    flush_error_rate probability flush() raises OSError(EIO)
    commit_error     close() (the commit) raises OSError(EIO) — the
                     rename-fails shape; abort stays clean
    latency_s        fixed sleep added to every write (the PUT shape)
    permanent        every write fails with EIO
    """

    def __init__(
        self,
        inner,
        *,
        seed: int = 0,
        error_rate: float = 0.0,
        fail_after_bytes: int | None = None,
        flush_error_rate: float = 0.0,
        commit_error: bool = False,
        latency_s: float = 0.0,
        permanent: bool = False,
        sleep=time.sleep,
    ):
        self.inner = inner
        self._rng = np.random.default_rng(seed)
        self.error_rate = float(error_rate)
        self.fail_after_bytes = fail_after_bytes
        self.flush_error_rate = float(flush_error_rate)
        self.commit_error = bool(commit_error)
        self.latency_s = float(latency_s)
        self.permanent = bool(permanent)
        self._sleep = sleep
        self.faults_injected = 0
        self.writes = 0
        self.bytes_written = 0

    @property
    def sink_id(self) -> str:
        return self.inner.sink_id

    def write(self, data) -> int:
        self.writes += 1
        if self.latency_s:
            self._sleep(self.latency_s)
        if self.permanent:
            self.faults_injected += 1
            raise OSError(_errno.EIO, "injected permanent EIO on write")
        if (
            self.fail_after_bytes is not None
            and self.bytes_written + len(data) > self.fail_after_bytes
        ):
            self.faults_injected += 1
            raise OSError(
                _errno.ENOSPC,
                f"injected write failure past {self.fail_after_bytes} bytes",
            )
        if self.error_rate and float(self._rng.random()) < self.error_rate:
            self.faults_injected += 1
            raise OSError(
                _errno.EIO, f"injected transient EIO at write {self.writes}"
            )
        n = self.inner.write(data)
        self.bytes_written += len(data)
        return n

    def tell(self) -> int:
        return self.inner.tell()

    def flush(self) -> None:
        if self.flush_error_rate and float(self._rng.random()) < self.flush_error_rate:
            self.faults_injected += 1
            raise OSError(_errno.EIO, "injected EIO on flush")
        self.inner.flush()

    def close(self) -> None:
        if self.commit_error:
            self.faults_injected += 1
            # the inner sink must not commit either: a failed commit that
            # still renamed the temp file would be the torn-file bug itself
            self.inner.abort()
            raise OSError(_errno.EIO, "injected EIO on commit")
        self.inner.close()

    def abort(self) -> None:
        self.inner.abort()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
        else:
            self.abort()
        return False

"""FlakySource: seeded transient-fault injection for the IO retry ladder.

The fault-injection harness in testing/faults.py corrupts BYTES (what a
rotten disk or lying writer produces); this module corrupts the TRANSPORT —
what a loaded object store or flaky NFS mount produces: transient EIO,
short reads, injected latency, and (optionally) permanent failure. Wrapped
around any ByteSource and driven from an integer seed, it gives the retry
ladder (io.source.RetryingSource) a deterministic adversary:

    src = RetryingSource(
        FlakySource(LocalFileSource(path), seed=7, error_rate=0.3),
        sleep=lambda s: None,    # tests: no real backoff waits
    )

Every fault draw comes from one numpy default_rng stream, so a failing test
replays exactly; each CALL re-rolls, so a retried read naturally has a fresh
chance to succeed — the transient-fault shape. `fault_window` confines
faults to a byte region (e.g. only the footer tail); `permanent=True` makes
every read fail, the budget-exhaustion shape.
"""

from __future__ import annotations

import errno as _errno
import time

import numpy as np

__all__ = ["FlakySource"]


class FlakySource:
    """A ByteSource wrapper injecting seeded transport faults.

    Parameters
    ----------
    inner        the wrapped ByteSource
    seed         rng seed; one stream across all fault draws
    error_rate   probability a read raises a transient OSError(EIO)
    short_rate   probability a read returns a truncated buffer (a contract
                 violation real transports commit; RetryingSource re-reads)
    latency_s    fixed sleep added to every read (the range-GET shape);
                 latency_jitter_s adds a uniform extra draw on top
    permanent    every read fails with EIO — the budget-exhaustion case
    fault_window (offset, length) confining faults to reads that overlap
                 the window (None = everywhere)
    """

    def __init__(
        self,
        inner,
        *,
        seed: int = 0,
        error_rate: float = 0.0,
        short_rate: float = 0.0,
        latency_s: float = 0.0,
        latency_jitter_s: float = 0.0,
        permanent: bool = False,
        fault_window: tuple[int, int] | None = None,
        sleep=time.sleep,
    ):
        self.inner = inner
        self._rng = np.random.default_rng(seed)
        self.error_rate = float(error_rate)
        self.short_rate = float(short_rate)
        self.latency_s = float(latency_s)
        self.latency_jitter_s = float(latency_jitter_s)
        self.permanent = bool(permanent)
        self.fault_window = fault_window
        self._sleep = sleep
        self.faults_injected = 0
        self.reads = 0

    @property
    def source_id(self) -> str:
        return self.inner.source_id

    def size(self) -> int:
        return self.inner.size()

    def _in_window(self, offset: int, n: int) -> bool:
        if self.fault_window is None:
            return True
        w_off, w_len = self.fault_window
        return offset < w_off + w_len and offset + n > w_off

    def read_at(self, offset: int, n: int) -> bytes:
        self.reads += 1
        if self.latency_s or self.latency_jitter_s:
            extra = (
                float(self._rng.uniform(0, self.latency_jitter_s))
                if self.latency_jitter_s
                else 0.0
            )
            self._sleep(self.latency_s + extra)
        if self._in_window(offset, n):
            if self.permanent:
                self.faults_injected += 1
                raise OSError(_errno.EIO, f"injected permanent EIO at {offset}")
            roll = float(self._rng.random())
            if roll < self.error_rate:
                self.faults_injected += 1
                raise OSError(_errno.EIO, f"injected transient EIO at {offset}")
            if roll < self.error_rate + self.short_rate and n > 1:
                self.faults_injected += 1
                cut = int(self._rng.integers(0, n))
                return self.inner.read_at(offset, cut)
        return self.inner.read_at(offset, n)

    def read_ranges(self, ranges) -> list:
        # per-range faults: one flaky range in a batch, not all-or-nothing
        return [self.read_at(off, n) for off, n in ranges]

    def close(self) -> None:
        self.inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

"""FlakySource/FlakySink: seeded transport-fault injection for the IO layers.

The fault-injection harness in testing/faults.py corrupts BYTES (what a
rotten disk or lying writer produces); this module corrupts the TRANSPORT —
what a loaded object store or flaky NFS mount produces: transient EIO,
short reads, injected latency, latency spikes, and (optionally) permanent
failure. Wrapped around any ByteSource and driven from an integer seed, it
gives the retry ladder (io.source.RetryingSource) a deterministic
adversary:

    src = RetryingSource(
        FlakySource(LocalFileSource(path), seed=7, error_rate=0.3),
        sleep=lambda s: None,    # tests: no real backoff waits
    )

Every fault draw comes from one numpy default_rng stream, so a failing test
replays exactly; each CALL re-rolls, so a retried read naturally has a fresh
chance to succeed — the transient-fault shape. `fault_window` confines
faults to a byte region (e.g. only the footer tail); `permanent=True` makes
every read fail, the budget-exhaustion shape.

`schedule=` accepts a testing.chaos.FaultSchedule (anything with a
`params_at(t)` -> dict): each operation reads the schedule's CURRENT phase
parameters at the injected `clock` and overlays them on the constructor
knobs — the chaos harness drives a whole latency-spike -> error-burst ->
blackout -> recovery timeline through one wrapper, deterministically under
fake time (advance the fake clock, the phase changes; the rng stream stays
one seeded sequence either way).

FlakySink is the WRITE-side mirror: wrapped around any ByteSink it injects
seeded write/flush/commit faults (including latency spikes, the same knobs
and `latency_spike` preset as FlakySource), the adversary for the
FileWriter error path — flush failures must surface as typed WriterError
and, because path sinks commit atomically, the destination must never hold
a torn file:

    sink = FlakySink(LocalFileSink(path), seed=7, error_rate=0.3)
    with pytest.raises(WriterError):
        with FileWriter(sink, schema) as w: ...
    assert not os.path.exists(path)          # nothing committed
"""

from __future__ import annotations

import errno as _errno
import time

import numpy as np

__all__ = ["FlakySource", "FlakySink"]

# the knobs a FaultSchedule phase may override, shared by source and sink
# (unknown keys in a phase are rejected by the schedule, not silently
# ignored here — see testing.chaos.Phase)
_SOURCE_KNOBS = (
    "error_rate", "short_rate", "latency_s", "latency_jitter_s",
    "spike_rate", "spike_s", "permanent",
)
_SINK_KNOBS = (
    "error_rate", "flush_error_rate", "latency_s", "spike_rate", "spike_s",
    "permanent",
)


class FlakySource:
    """A ByteSource wrapper injecting seeded transport faults.

    Parameters
    ----------
    inner        the wrapped ByteSource
    seed         rng seed; one stream across all fault draws
    error_rate   probability a read raises a transient OSError(EIO)
    short_rate   probability a read returns a truncated buffer (a contract
                 violation real transports commit; RetryingSource re-reads)
    latency_s    fixed sleep added to every read (the range-GET shape);
                 latency_jitter_s adds a uniform extra draw on top
    spike_rate   probability a read stalls an EXTRA spike_s on top of the
                 base latency — the hot-shard / GC-pause / tail-latency
                 shape (see the latency_spike preset)
    permanent    every read fails with EIO — the budget-exhaustion case
    fault_window (offset, length) confining faults to reads that overlap
                 the window (None = everywhere)
    schedule     a FaultSchedule whose current phase overrides the knobs
                 above per operation (chaos timelines)
    clock        the schedule's time base (injectable: fake time makes
                 chaos phases deterministic)
    """

    def __init__(
        self,
        inner,
        *,
        seed: int = 0,
        error_rate: float = 0.0,
        short_rate: float = 0.0,
        latency_s: float = 0.0,
        latency_jitter_s: float = 0.0,
        spike_rate: float = 0.0,
        spike_s: float = 0.0,
        permanent: bool = False,
        fault_window: tuple[int, int] | None = None,
        schedule=None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        self.inner = inner
        self._rng = np.random.default_rng(seed)
        self.error_rate = float(error_rate)
        self.short_rate = float(short_rate)
        self.latency_s = float(latency_s)
        self.latency_jitter_s = float(latency_jitter_s)
        self.spike_rate = float(spike_rate)
        self.spike_s = float(spike_s)
        self.permanent = bool(permanent)
        self.fault_window = fault_window
        self.schedule = schedule
        self._clock = clock
        self._sleep = sleep
        self.faults_injected = 0
        self.reads = 0
        self.spikes_injected = 0

    @classmethod
    def latency_spike(cls, inner, *, seed: int = 0, p: float = 0.05, ms: float = 50.0, **kw):
        """Preset: a source whose reads occasionally STALL — each read has
        probability `p` of an extra `ms`-millisecond spike (seeded, so a
        failing chaos run replays exactly). The serving-layer adversary: a
        latency-spiked source must produce slow responses or typed
        timeouts, never a hung worker or a torn response body."""
        return cls(inner, seed=seed, spike_rate=p, spike_s=ms / 1e3, **kw)

    @property
    def source_id(self) -> str:
        return self.inner.source_id

    def generation(self):
        gen = getattr(self.inner, "generation", None)
        return gen() if gen is not None else None

    def size(self) -> int:
        return self.inner.size()

    def _in_window(self, offset: int, n: int) -> bool:
        if self.fault_window is None:
            return True
        w_off, w_len = self.fault_window
        return offset < w_off + w_len and offset + n > w_off

    def _params(self) -> dict:
        """The effective knobs for THIS operation: the constructor values,
        overlaid with the schedule's current phase when one is attached."""
        p = {k: getattr(self, k) for k in _SOURCE_KNOBS}
        if self.schedule is not None:
            p.update(
                (k, v)
                for k, v in self.schedule.params_at(self._clock()).items()
                if k in p
            )
        return p

    def read_at(self, offset: int, n: int) -> bytes:
        self.reads += 1
        p = self._params()
        if p["latency_s"] or p["latency_jitter_s"]:
            extra = (
                float(self._rng.uniform(0, p["latency_jitter_s"]))
                if p["latency_jitter_s"]
                else 0.0
            )
            self._sleep(p["latency_s"] + extra)
        # spikes draw only when enabled so existing seeds' fault streams
        # are unchanged by the knob's existence
        if p["spike_rate"] and float(self._rng.random()) < p["spike_rate"]:
            self.spikes_injected += 1
            self._sleep(p["spike_s"])
        if self._in_window(offset, n):
            if p["permanent"]:
                self.faults_injected += 1
                raise OSError(_errno.EIO, f"injected permanent EIO at {offset}")
            roll = float(self._rng.random())
            if roll < p["error_rate"]:
                self.faults_injected += 1
                raise OSError(_errno.EIO, f"injected transient EIO at {offset}")
            if roll < p["error_rate"] + p["short_rate"] and n > 1:
                self.faults_injected += 1
                cut = int(self._rng.integers(0, n))
                return self.inner.read_at(offset, cut)
        return self.inner.read_at(offset, n)

    def read_ranges(self, ranges) -> list:
        # per-range faults: one flaky range in a batch, not all-or-nothing
        return [self.read_at(off, n) for off, n in ranges]

    def close(self) -> None:
        self.inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class FlakySink:
    """A ByteSink wrapper injecting seeded write-path faults (the mirror of
    FlakySource for the FileWriter/sink error ladder).

    Parameters
    ----------
    inner            the wrapped ByteSink
    seed             rng seed; one stream across all fault draws
    error_rate       probability a write raises a transient OSError(EIO)
                     BEFORE any bytes reach the inner sink (clean failure)
    fail_after_bytes when set, every write past this many successfully
                     written bytes fails — the disk-full / quota shape
    flush_error_rate probability flush() raises OSError(EIO)
    commit_error     close() (the commit) raises OSError(EIO) — the
                     rename-fails shape; abort stays clean
    latency_s        fixed sleep added to every write (the PUT shape)
    spike_rate       probability a write stalls an EXTRA spike_s — the
                     stalled-PUT / throttled-store shape (see the
                     latency_spike preset, FlakySource parity)
    permanent        every write fails with EIO
    schedule         a FaultSchedule whose current phase overrides the
                     knobs above per operation (chaos timelines)
    clock            the schedule's time base (injectable)
    """

    def __init__(
        self,
        inner,
        *,
        seed: int = 0,
        error_rate: float = 0.0,
        fail_after_bytes: int | None = None,
        flush_error_rate: float = 0.0,
        commit_error: bool = False,
        latency_s: float = 0.0,
        spike_rate: float = 0.0,
        spike_s: float = 0.0,
        permanent: bool = False,
        schedule=None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        self.inner = inner
        self._rng = np.random.default_rng(seed)
        self.error_rate = float(error_rate)
        self.fail_after_bytes = fail_after_bytes
        self.flush_error_rate = float(flush_error_rate)
        self.commit_error = bool(commit_error)
        self.latency_s = float(latency_s)
        self.spike_rate = float(spike_rate)
        self.spike_s = float(spike_s)
        self.permanent = bool(permanent)
        self.schedule = schedule
        self._clock = clock
        self._sleep = sleep
        self.faults_injected = 0
        self.writes = 0
        self.bytes_written = 0
        self.spikes_injected = 0

    @classmethod
    def latency_spike(cls, inner, *, seed: int = 0, p: float = 0.05, ms: float = 50.0, **kw):
        """Preset: a sink whose writes occasionally STALL — each write has
        probability `p` of an extra `ms`-millisecond spike (seeded). The
        FlakySource.latency_spike mirror for the encode/flush pipeline."""
        return cls(inner, seed=seed, spike_rate=p, spike_s=ms / 1e3, **kw)

    @property
    def sink_id(self) -> str:
        return self.inner.sink_id

    def _params(self) -> dict:
        p = {k: getattr(self, k) for k in _SINK_KNOBS}
        if self.schedule is not None:
            p.update(
                (k, v)
                for k, v in self.schedule.params_at(self._clock()).items()
                if k in p
            )
        return p

    def write(self, data) -> int:
        self.writes += 1
        p = self._params()
        if p["latency_s"]:
            self._sleep(p["latency_s"])
        if p["spike_rate"] and float(self._rng.random()) < p["spike_rate"]:
            self.spikes_injected += 1
            self._sleep(p["spike_s"])
        if p["permanent"]:
            self.faults_injected += 1
            raise OSError(_errno.EIO, "injected permanent EIO on write")
        if (
            self.fail_after_bytes is not None
            and self.bytes_written + len(data) > self.fail_after_bytes
        ):
            self.faults_injected += 1
            raise OSError(
                _errno.ENOSPC,
                f"injected write failure past {self.fail_after_bytes} bytes",
            )
        if p["error_rate"] and float(self._rng.random()) < p["error_rate"]:
            self.faults_injected += 1
            raise OSError(
                _errno.EIO, f"injected transient EIO at write {self.writes}"
            )
        n = self.inner.write(data)
        self.bytes_written += len(data)
        return n

    def tell(self) -> int:
        return self.inner.tell()

    def flush(self) -> None:
        rate = self._params()["flush_error_rate"]
        if rate and float(self._rng.random()) < rate:
            self.faults_injected += 1
            raise OSError(_errno.EIO, "injected EIO on flush")
        self.inner.flush()

    def close(self) -> None:
        if self.commit_error:
            self.faults_injected += 1
            # the inner sink must not commit either: a failed commit that
            # still renamed the temp file would be the torn-file bug itself
            self.inner.abort()
            raise OSError(_errno.EIO, "injected EIO on commit")
        self.inner.close()

    def abort(self) -> None:
        self.inner.abort()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
        else:
            self.abort()
        return False

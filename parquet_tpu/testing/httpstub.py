"""RangeHttpStub: a loopback range-GET HTTP server with injectable faults.

The chaos substrate for parquet_tpu.io.remote — what FlakySource is to a
local ByteSource, this is to a real HTTP transport: a stdlib
ThreadingHTTPServer on 127.0.0.1:<ephemeral> serving a dict of named
blobs (or files from a directory) with honest range semantics — 206 +
Content-Range for `Range: bytes=a-b`, 200 for full GETs, HEAD, strong
ETags, 404/416 where HTTP says so — and SEEDED transport faults layered
on top:

    stub = RangeHttpStub(files={"corpus.parquet": data}, seed=7,
                         error_rate=0.2, latency_s=0.005)
    with stub:
        src = HttpSource(stub.url_for("corpus.parquet"))
        ...

Fault knobs (each draw from ONE seeded numpy rng stream, so a failing
test replays exactly; knobs are plain attributes, mutable mid-test):

  error_rate       probability a request answers 503 (the transient
                   server-fault shape RetryingSource must absorb)
  drop_rate        probability the connection closes with NO response
                   (the reset/LB-kill shape -> client-side transport
                   fault)
  short_rate       probability a response body is TRUNCATED below its
                   declared Content-Length (the torn-transfer shape ->
                   typed truncated_body)
  latency_s (+latency_jitter_s)  per-request injected RTT (the remote
                   profile the IO auto-tuner keys on)
  spike_rate/spike_s  occasional EXTRA stall (tail-latency shape)
  permanent        every request 503s (blackout)

`schedule=` accepts the same testing.chaos.FaultSchedule the FlakySource
machinery uses: the current phase's params overlay the knobs per request
(under the injectable `clock`), so one scripted spike -> errors ->
blackout -> recovery timeline drives local sources AND this stub from a
single object. Fault draws and the request counters are lock-serialized;
payload writes are not (requests stream concurrently).

`writable=True` grows the stub the WRITE side of an object store — the
multipart protocol io.remote_sink speaks (initiate/part/complete/abort
plus single-shot PUT), with the zero-torn-object semantics a real store
guarantees baked in as assertable state: an object becomes visible ONLY
at complete (atomically, under the lock), an aborted upload vanishes, and
`has_object()`/`live_uploads()` let tests prove both. Write-side faults
draw from the SAME seeded rng stream: the shared knobs above apply to
every write request, plus

  complete_error_rate  probability complete-multipart answers 500 BEFORE
                       publishing (the commit-time transient the sink's
                       ladder must absorb)
  ack_drop_rate        probability a write op is APPLIED but its ack is
                       dropped (the ambiguous-ack / truncated-ack shape:
                       the client must retry idempotently)
  corrupt_part_etag    every part PUT acks with a WRONG CRC ETag (the
                       torn-transfer-acknowledged-as-success shape)

`credentials={access_key: secret}` arms signed mode: EVERY request (reads
included) must carry a valid PQT4-HMAC-SHA256 signature — verified with
the same io.sign code the client signs with — or it answers a typed 403
(counted in `auth_rejects`).
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

__all__ = ["RangeHttpStub"]

# the knobs a FaultSchedule phase may override here (chaos.Phase validates
# names against the FlakySource vocabulary; drop_rate and the write-side
# rates are stub-local and settable only via the constructor/attribute)
_STUB_KNOBS = (
    "error_rate", "short_rate", "latency_s", "latency_jitter_s",
    "spike_rate", "spike_s", "permanent", "drop_rate",
    "complete_error_rate", "ack_drop_rate",
)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # keep-alive: the connection-pool shape
    stub: "RangeHttpStub" = None  # set per served stub via type()

    # -- plumbing --------------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet: tests read assertions,
        pass  # not access logs

    def _fail_503(self) -> None:
        body = b'{"error": "injected fault"}'
        self.send_response(503)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, status: int, obj, *, etag: str | None = None) -> None:
        body = json.dumps(obj).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        if etag is not None:
            self.send_header("ETag", etag)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n > 0 else b""

    def _query(self) -> dict:
        out = {}
        for kv in self.path.partition("?")[2].split("&"):
            if kv:
                k, _, v = kv.partition("=")
                out[k] = v
        return out

    def _drop(self) -> None:
        # no status line at all: the client sees the connection die
        # (RemoteDisconnected), the transport-fault shape. shutdown, not
        # close — the framework's post-handler wfile.flush() must stay a
        # no-op instead of raising into the server thread
        import socket

        self.close_connection = True
        try:
            self.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    # -- request handling ------------------------------------------------------

    def _serve(self, head_only: bool) -> None:
        stub = self.stub
        stub._record_traceparent(self.headers.get("traceparent"))
        p = stub._draw_and_wait()
        if p is None:  # drop was drawn
            self._drop()
            return
        if p["permanent"] or p["__error"]:
            stub._count_fault()
            self._fail_503()
            return
        if stub.credentials is not None:
            # signed mode: reads must verify like writes — symmetric auth
            reason = stub._verify(self, "HEAD" if head_only else "GET", b"")
            if reason is not None:
                body = json.dumps({"error": reason}).encode("utf-8")
                self.send_response(403)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if not head_only:
                    self.wfile.write(body)
                return
        if stub.require_token is not None:
            # the presigned-URL shape: a `token` query param must match
            # the currently-valid signature or the store answers 403 —
            # the ObjectStoreSource reactive re-sign adversary
            query = self.path.partition("?")[2]
            tokens = [
                kv.partition("=")[2]
                for kv in query.split("&")
                if kv.startswith("token=")
            ]
            if stub.require_token not in tokens:
                body = b'{"error": "signature rejected"}'
                self.send_response(403)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if not head_only:
                    self.wfile.write(body)
                return
        name = self.path.lstrip("/").split("?", 1)[0]
        entry = stub._entry(name)
        if entry is None:
            body = b'{"error": "no such object"}'
            self.send_response(404)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if not head_only:
                self.wfile.write(body)
            return
        data, etag = entry
        size = len(data)
        rng_header = self.headers.get("Range")
        if_range = self.headers.get("If-Range")
        if rng_header is not None and if_range is not None and if_range != etag:
            # RFC 7233 If-Range: a stale validator downgrades the ranged
            # GET to 200 + the FULL current body — the rewrite-mid-scan
            # shape HttpSource must surface as typed source_changed
            rng_header = None
        if rng_header is None or stub.ignore_range:
            status, start, end = 200, 0, size - 1
        elif "," in rng_header and not head_only:
            # multi-range: served as multipart/byteranges, or — with
            # reject_multirange — refused with the 416 a single-range
            # server answers (pins HttpSource's per-range fallback)
            spans = (
                None
                if stub.reject_multirange
                else stub._parse_ranges(rng_header, size)
            )
            if spans is None:
                self.send_response(416)
                self.send_header("Content-Range", f"bytes */{size}")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            with stub._lock:
                stub.multirange_requests += 1
            self._send_multipart(data, spans, etag)
            return
        else:
            span = stub._parse_range(rng_header, size)
            if span is None:
                self.send_response(416)
                self.send_header("Content-Range", f"bytes */{size}")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            status, (start, end) = 206, span
        payload = data[start : end + 1] if size else b""
        declared = len(payload)
        truncate_to = stub._maybe_truncate(declared) if not head_only else None
        self.send_response(status)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Accept-Ranges", "bytes")
        if stub.send_etag:
            self.send_header("ETag", etag)
        if status == 206:
            self.send_header("Content-Range", f"bytes {start}-{end}/{size}")
        self.send_header("Content-Length", str(declared))
        if truncate_to is not None:
            # a torn transfer: promise `declared`, deliver less, slam the
            # connection — the client's read raises IncompleteRead
            self.send_header("Connection", "close")
            self.close_connection = True
        self.end_headers()
        if head_only:
            return
        sent = payload if truncate_to is None else payload[:truncate_to]
        try:
            self.wfile.write(sent)
            stub._count_sent(len(sent))
        except OSError:
            self.close_connection = True
        if truncate_to is not None:
            # flush + FIN below the declared length: the client's read
            # comes up short (IncompleteRead), the torn-transfer shape
            import socket

            try:
                self.wfile.flush()
                self.connection.shutdown(socket.SHUT_RDWR)
            except (OSError, ValueError):
                pass

    _MR_BOUNDARY = "pqt_stub_byteranges"

    def _send_multipart(self, data: bytes, spans, etag: str) -> None:
        """One 206 multipart/byteranges response: a part per span, each
        with its own Content-Range — exactly the RFC 7233 shape
        HttpSource._read_multirange parses."""
        size = len(data)
        b = self._MR_BOUNDARY
        chunks = []
        for start, end in spans:
            chunks.append(
                (
                    f"--{b}\r\n"
                    "Content-Type: application/octet-stream\r\n"
                    f"Content-Range: bytes {start}-{end}/{size}\r\n\r\n"
                ).encode()
                + data[start : end + 1]
                + b"\r\n"
            )
        chunks.append(f"--{b}--\r\n".encode())
        body = b"".join(chunks)
        self.send_response(206)
        self.send_header(
            "Content-Type", f"multipart/byteranges; boundary={b}"
        )
        self.send_header("Accept-Ranges", "bytes")
        if self.stub.send_etag:
            self.send_header("ETag", etag)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
            self.stub._count_sent(len(body))
        except OSError:
            self.close_connection = True

    def do_GET(self):
        self._serve(head_only=False)

    def do_HEAD(self):
        if self.stub.reject_head:
            body = b""
            self.send_response(405)
            self.send_header("Content-Length", "0")
            self.send_header("Allow", "GET")
            self.end_headers()
            self.wfile.write(body)
            return
        self._serve(head_only=True)

    # -- the write side (multipart object-store mode) --------------------------

    def do_PUT(self):
        self._write_op("PUT")

    def do_POST(self):
        self._write_op("POST")

    def do_DELETE(self):
        self._write_op("DELETE")

    def _write_op(self, method: str) -> None:
        stub = self.stub
        stub._record_traceparent(self.headers.get("traceparent"))
        # the body is read BEFORE the fault draw: a dropped connection
        # must model an ack lost in flight, not a request never sent
        body = self._read_body()
        p = stub._draw_and_wait()
        if p is None:
            self._drop()
            return
        if p["permanent"] or p["__error"]:
            stub._count_fault()
            self._fail_503()
            return
        if not stub.writable:
            self._json(405, {"error": "read-only stub"})
            return
        if stub.credentials is not None:
            reason = stub._verify(self, method, body)
            if reason is not None:
                self._json(403, {"error": reason})
                return
        name = self.path.lstrip("/").split("?", 1)[0]
        q = self._query()
        if method == "POST" and "uploads" in q:
            self._mp_initiate(name)
        elif method == "PUT" and "partNumber" in q and "uploadId" in q:
            self._mp_part(name, q, body)
        elif method == "POST" and "uploadId" in q:
            self._mp_complete(name, q, body)
        elif method == "DELETE" and "uploadId" in q:
            self._mp_abort(q)
        elif method == "PUT" and not q:
            self._put_object(name, body)
        else:
            self._json(400, {"error": f"unsupported write operation {method} {self.path}"})

    @staticmethod
    def _crc_etag(data: bytes) -> str:
        return f'"crc32-{zlib.crc32(data) & 0xFFFFFFFF:08x}"'

    def _mp_initiate(self, name: str) -> None:
        stub = self.stub
        with stub._lock:
            uid = f"upload-{next(stub._upload_seq):06d}"
            stub._uploads[uid] = {"name": name, "parts": {}}
            stub.uploads_started += 1
        if stub._draw_rate("ack_drop_rate"):
            # the upload EXISTS but the client never learns its id — the
            # orphan a real store reaps by lifecycle rule, never a torn
            # object
            self._drop()
            return
        self._json(200, {"upload_id": uid})

    def _mp_part(self, name: str, q: dict, body: bytes) -> None:
        stub = self.stub
        try:
            pn = int(q.get("partNumber", ""))
        except ValueError:
            self._json(400, {"error": "malformed partNumber"})
            return
        with stub._lock:
            up = stub._uploads.get(q.get("uploadId", ""))
            if up is None or up["name"] != name:
                self._json(404, {"error": "no such upload"})
                return
            # storing by part number makes the retry of an ambiguous ack
            # idempotent: same part, same slot
            up["parts"][pn] = bytes(body)
            stub.put_requests += 1
        etag = (
            '"crc32-deadbeef"'
            if stub.corrupt_part_etag
            else self._crc_etag(body)
        )
        if stub._draw_rate("ack_drop_rate"):
            self._drop()  # part stored, ack lost: the truncated-ack shape
            return
        self._json(200, {"part_number": pn}, etag=etag)

    def _mp_complete(self, name: str, q: dict, body: bytes) -> None:
        stub = self.stub
        uid = q.get("uploadId", "")
        with stub._lock:
            done = stub._completed.get(uid)
        if done is not None:
            # idempotent replay of a commit whose ack was lost — answering
            # anything else would turn one ambiguous ack into a client
            # that can never learn its object committed
            self._json(200, {"etag": done})
            return
        with stub._lock:
            up = stub._uploads.get(uid)
            parts = dict(up["parts"]) if up is not None else None
        if up is None or up["name"] != name:
            self._json(404, {"error": "no such upload"})
            return
        try:
            listed = [
                (int(p["part_number"]), str(p["etag"]), int(p["size"]))
                for p in json.loads(body.decode("utf-8"))["parts"]
            ]
        except (ValueError, KeyError, TypeError):
            self._json(400, {"error": "malformed manifest"})
            return
        if not listed:
            self._json(400, {"error": "empty manifest"})
            return
        for pn, etag, size in listed:
            data = parts.get(pn)
            if (
                data is None
                or len(data) != size
                or self._crc_etag(data) != etag
            ):
                self._json(400, {"error": f"part {pn} mismatch"})
                return
        if stub._draw_rate("complete_error_rate"):
            # the commit-time transient: 500 BEFORE publishing — nothing
            # became visible, the retry ladder gets another shot
            body500 = b'{"error": "injected commit fault"}'
            self.send_response(500)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body500)))
            self.end_headers()
            self.wfile.write(body500)
            return
        data = b"".join(parts[pn] for pn, _, _ in sorted(listed))
        obj_etag = self._crc_etag(data)
        with stub._lock:
            # the ATOMIC publish: the object flips visible in one step,
            # full bytes or nothing — there is no code path that installs
            # a prefix
            stub._files[name] = data
            stub._entries.pop(name, None)
            stub._completed[uid] = obj_etag
            stub._uploads.pop(uid, None)
            stub.uploads_completed += 1
        if stub._draw_rate("ack_drop_rate"):
            self._drop()  # committed, ack lost: the replay above answers
            return
        self._json(200, {"etag": obj_etag})

    def _mp_abort(self, q: dict) -> None:
        stub = self.stub
        with stub._lock:
            if q.get("uploadId", "") in stub._uploads:
                del stub._uploads[q["uploadId"]]
                stub.uploads_aborted += 1
        # idempotent: aborting an unknown/done upload is still a 204 (and
        # NEVER touches a published object)
        if stub._draw_rate("ack_drop_rate"):
            self._drop()
            return
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _put_object(self, name: str, body: bytes) -> None:
        stub = self.stub
        etag = (
            '"crc32-deadbeef"'
            if stub.corrupt_part_etag
            else self._crc_etag(body)
        )
        with stub._lock:
            stub._files[name] = bytes(body)
            stub._entries.pop(name, None)
            stub.put_requests += 1
            stub.objects_put += 1
        if stub._draw_rate("ack_drop_rate"):
            self._drop()  # published, ack lost: the retry re-PUTs the
            return  # same bytes (idempotent), never a torn object
        self._json(200, {"etag": etag}, etag=etag)


class RangeHttpStub:
    """See module docstring. Construct, `start()` (or use as a context
    manager), point HttpSource at `url_for(name)`.

    files         {name: bytes} served from memory
    root          a directory; files load (and cache) on first request
    seed          the fault rng seed (one stream across all draws)
    ignore_range  serve 200 + the FULL object even for ranged GETs (the
                  misbehaving-server shape HttpSource must slice through)
    reject_multirange  416 every comma-form Range header (the
                  single-range-only server shape: HttpSource must latch
                  its per-range fallback); default False serves RFC 7233
                  multipart/byteranges (counted in multirange_requests)
    reject_head   405 every HEAD (forces HttpSource's range-GET stat
                  fallback)
    send_etag     False omits the ETag header entirely (the validator-less
                  server shape: only Content-Length can betray a rewrite)
    writable      enable the multipart write protocol (PUT/POST/DELETE)
    credentials   {access_key: secret} arms signed mode on EVERY request
    schedule      a chaos.FaultSchedule overlaying the knobs per request
    """

    def __init__(
        self,
        *,
        files: dict | None = None,
        root=None,
        seed: int = 0,
        error_rate: float = 0.0,
        drop_rate: float = 0.0,
        short_rate: float = 0.0,
        latency_s: float = 0.0,
        latency_jitter_s: float = 0.0,
        spike_rate: float = 0.0,
        spike_s: float = 0.0,
        permanent: bool = False,
        ignore_range: bool = False,
        reject_multirange: bool = False,
        reject_head: bool = False,
        send_etag: bool = True,
        require_token: str | None = None,
        writable: bool = False,
        credentials: dict | None = None,
        complete_error_rate: float = 0.0,
        ack_drop_rate: float = 0.0,
        corrupt_part_etag: bool = False,
        schedule=None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        self._files = {str(k): bytes(v) for k, v in (files or {}).items()}
        self.root = os.fspath(root) if root is not None else None
        if not self._files and self.root is None and not writable:
            raise ValueError(
                "RangeHttpStub: need files= and/or root= (or writable=True)"
            )
        self._rng = np.random.default_rng(seed)
        self.error_rate = float(error_rate)
        self.drop_rate = float(drop_rate)
        self.short_rate = float(short_rate)
        self.latency_s = float(latency_s)
        self.latency_jitter_s = float(latency_jitter_s)
        self.spike_rate = float(spike_rate)
        self.spike_s = float(spike_s)
        self.permanent = bool(permanent)
        self.ignore_range = bool(ignore_range)
        self.reject_multirange = bool(reject_multirange)
        self.reject_head = bool(reject_head)
        self.send_etag = bool(send_etag)
        self.require_token = require_token
        self.writable = bool(writable)
        self.credentials = dict(credentials) if credentials else None
        self.complete_error_rate = float(complete_error_rate)
        self.ack_drop_rate = float(ack_drop_rate)
        self.corrupt_part_etag = bool(corrupt_part_etag)
        self.schedule = schedule
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._entries: dict[str, tuple] = {}  # name -> (bytes, etag)
        self._uploads: dict[str, dict] = {}  # id -> {name, parts{pn: bytes}}
        self._completed: dict[str, str] = {}  # id -> object etag (replays)
        self._upload_seq = itertools.count(1)
        self.requests = 0
        self.faults_injected = 0
        self.bytes_served = 0
        self.multirange_requests = 0  # comma-form Range GETs served multipart
        # every traceparent header received, in arrival order — the
        # store-side half of the end-to-end propagation pin (recorded
        # BEFORE the fault draw: a faulted request was still received)
        self.traceparents: list = []
        self.put_requests = 0
        self.objects_put = 0
        self.auth_rejects = 0
        self.uploads_started = 0
        self.uploads_completed = 0
        self.uploads_aborted = 0
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "RangeHttpStub":
        if self._server is not None:
            return self
        handler = type("_StubHandler", (_Handler,), {"stub": self})
        self._server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="pqt-httpstub",
            daemon=True,
        )
        self._thread.start()
        return self

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None

    stop = close

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- addressing ------------------------------------------------------------

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("RangeHttpStub: not started")
        return self._server.server_address[1]

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def url_for(self, name: str) -> str:
        return f"{self.base_url}/{name}"

    def set_file(self, name: str, data: bytes) -> None:
        """Publish (or REWRITE — new ETag, the source_changed shape) one
        in-memory object."""
        with self._lock:
            self._files[str(name)] = bytes(data)
            self._entries.pop(str(name), None)

    # -- the zero-torn-object assertion surface --------------------------------

    def has_object(self, name: str) -> bool:
        """Is `name` VISIBLE (published via complete/PUT/set_file)? The
        write-path acceptance pins: False until the writer commits, False
        forever after an abort."""
        with self._lock:
            return str(name) in self._files

    def object_bytes(self, name: str):
        """The published bytes of `name`, or None — byte-identity is the
        other half of the zero-torn contract."""
        with self._lock:
            data = self._files.get(str(name))
            return None if data is None else bytes(data)

    def live_uploads(self) -> int:
        """Uploads initiated but neither completed nor aborted. Zero after
        a clean commit or abort; ambiguous-ack chaos may legitimately
        orphan some (a real store reaps those by lifecycle rule)."""
        with self._lock:
            return len(self._uploads)

    def _verify(self, handler, method: str, payload: bytes):
        """Signed-mode check: same io.sign code path the client signs
        with. Returns None (ok) or the 403 reason."""
        from ..io.sign import verify_request

        reason = verify_request(
            method, handler.path, handler.headers, payload,
            self.credentials.get,
        )
        if reason is not None:
            with self._lock:
                self.auth_rejects += 1
        return reason

    def _draw_rate(self, name: str) -> bool:
        """One seeded draw against the named write-fault rate (same rng
        stream as every other fault — a failing chaos run replays)."""
        with self._lock:
            rate = self._params().get(name) or 0.0
            if rate and float(self._rng.random()) < rate:
                self.faults_injected += 1
                return True
        return False

    # -- handler callbacks -----------------------------------------------------

    @staticmethod
    def _parse_range(header: str, size: int):
        """`bytes=a-b` / `bytes=a-` / `bytes=-n` -> (start, end) clamped
        inclusive, or None for unsatisfiable/malformed (-> 416)."""
        if not header.startswith("bytes=") or "," in header:
            return None  # multi-range is _parse_ranges' job
        spec = header[len("bytes="):].strip()
        first, _, last = spec.partition("-")
        try:
            if first == "":  # suffix form: the last N bytes
                n = int(last)
                if n <= 0 or size == 0:
                    return None
                return (max(0, size - n), size - 1)
            start = int(first)
            end = int(last) if last else size - 1
        except ValueError:
            return None
        if start >= size or end < start:
            return None
        return (start, min(end, size - 1))

    @classmethod
    def _parse_ranges(cls, header: str, size: int):
        """`bytes=a-b,c-d,...` -> [(start, end), ...] in request order,
        or None when any piece is unsatisfiable (-> 416)."""
        if not header.startswith("bytes="):
            return None
        spans = []
        for piece in header[len("bytes="):].split(","):
            span = cls._parse_range(f"bytes={piece.strip()}", size)
            if span is None:
                return None
            spans.append(span)
        return spans or None

    def _entry(self, name: str):
        with self._lock:
            hit = self._entries.get(name)
            if hit is not None:
                return hit
            data = self._files.get(name)
        if data is None and self.root is not None and name:
            realroot = os.path.realpath(self.root)
            path = os.path.normpath(os.path.join(realroot, name))
            # stay inside the root (the stub is a test double, but an
            # escape-serving double invites escape-shaped tests)
            if path.startswith(realroot + os.sep):
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError:
                    data = None
        if data is None:
            return None
        etag = f'"{hashlib.sha1(data).hexdigest()[:16]}"'
        with self._lock:
            self._entries[name] = (data, etag)
            return self._entries[name]

    def _params(self) -> dict:
        p = {k: getattr(self, k) for k in _STUB_KNOBS}
        if self.schedule is not None:
            p.update(
                (k, v)
                for k, v in self.schedule.params_at(self._clock()).items()
                if k in p
            )
        return p

    def _draw_and_wait(self):
        """Latency + the per-request fault draw (seeded, lock-serialized).
        Returns the effective params with "__error" resolved, or None when
        the connection should drop."""
        with self._lock:
            self.requests += 1
            p = self._params()
            extra = (
                float(self._rng.uniform(0, p["latency_jitter_s"]))
                if p["latency_jitter_s"]
                else 0.0
            )
            spike = 0.0
            if p["spike_rate"] and float(self._rng.random()) < p["spike_rate"]:
                spike = p["spike_s"]
            roll = (
                float(self._rng.random())
                if (p["error_rate"] or p["drop_rate"])
                else 1.0
            )
            p["__error"] = roll < p["error_rate"]
            dropped = not p["__error"] and roll < p["error_rate"] + p["drop_rate"]
            if p["__error"] or dropped:
                self.faults_injected += 1
        # sleep OUTSIDE the lock: injected latency must overlap across
        # concurrent requests or it models a single-threaded store
        if p["latency_s"] or extra or spike:
            self._sleep(p["latency_s"] + extra + spike)
        return None if dropped else p

    def _maybe_truncate(self, declared: int):
        if declared <= 1:
            return None
        with self._lock:
            rate = self._params()["short_rate"]
            if rate and float(self._rng.random()) < rate:
                self.faults_injected += 1
                return int(self._rng.integers(0, declared))
        return None

    def _record_traceparent(self, raw) -> None:
        """Keep every traceparent header received (None headers skipped):
        the store-side record the end-to-end propagation pin asserts on."""
        if raw is not None:
            with self._lock:
                self.traceparents.append(str(raw))

    def _count_fault(self) -> None:
        pass  # counted at draw time (one lock acquisition per request)

    def _count_sent(self, n: int) -> None:
        with self._lock:
            self.bytes_served += n

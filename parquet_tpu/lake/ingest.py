"""Streaming ingest: row batches -> size-bounded files -> one generation
per flush.

The write path mirrors the read path's two wire formats (serve/protocol's
FORMATS): jsonl (one JSON object per line) and arrow-ipc (a pyarrow
stream). rows_from_payload() decodes either into the plain row dicts
FileWriter.write_rows ingests.

IngestWriter buffers appended rows in memory up to `flush_bytes` of
estimated payload, then flushes: rows are (optionally) sorted by the
table's sort key, encoded into ONE data/ingest-*.parquet through the
parallel EncodePipeline (FileWriter(parallel=...) on the pqt-encode
pool), and the manifest commits generation N+1 referencing it. The sink
contract makes the data file atomic and the manifest commit makes it
visible — a crash mid-flush loses only the un-acked buffer, never a
committed generation. Thread-safe: the daemon's handler threads append
concurrently under one lock (encoding happens inside the lock too — the
flush IS the serialization point that gives each flush one generation).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

from ..core.writer import FileWriter
from ..utils import metrics as _metrics
from .manifest import FileEntry, LakeError, LakeTable, Snapshot

__all__ = ["rows_from_payload", "IngestWriter"]

# data-file names must be unique across every writer THIS process ever
# creates, not per-writer: a retained generation may still reference a
# name the current snapshot dropped (compaction), and the atomic sink
# would happily replace those bytes — breaking time-travel identity.
# pid handles other processes; this counter handles this one.
_FILE_SEQ = itertools.count(1)

_JSONL_TYPES = ("application/x-ndjson", "application/json")
_ARROW_TYPES = ("application/vnd.apache.arrow.stream",)


def rows_from_payload(body: bytes, content_type: str) -> list:
    """Decode one append body into row dicts, by declared content type.
    Raises LakeError(code="unsupported_format") for an unknown type and
    LakeError(code="bad_payload") for a body that does not parse."""
    ct = (content_type or "").partition(";")[0].strip().lower()
    if ct in _JSONL_TYPES or ct == "":
        rows = []
        for ln, line in enumerate(body.splitlines(), 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                raise LakeError(
                    f"append: jsonl line {ln} does not parse: {e}",
                    code="bad_payload",
                ) from None
            if not isinstance(row, dict):
                raise LakeError(
                    f"append: jsonl line {ln} is not an object "
                    f"(got {type(row).__name__})", code="bad_payload",
                )
            rows.append(row)
        return rows
    if ct in _ARROW_TYPES:
        try:
            import pyarrow as pa
        except ImportError:
            raise LakeError(
                "append: arrow-ipc needs pyarrow, which this daemon "
                "does not have", code="unsupported_format",
            ) from None
        try:
            with pa.ipc.open_stream(body) as reader:
                table = reader.read_all()
        except (pa.ArrowInvalid, OSError, ValueError) as e:
            raise LakeError(
                f"append: arrow-ipc stream does not parse: {e}",
                code="bad_payload",
            ) from None
        return table.to_pylist()
    raise LakeError(
        f"append: unsupported content type {content_type!r} (expected "
        f"{_JSONL_TYPES[0]} or {_ARROW_TYPES[0]})", code="unsupported_format",
    )


def _row_cost(row: dict) -> int:
    """Cheap upper-ish estimate of a row's encoded footprint, for the
    flush threshold only (exact sizes come from the committed file)."""
    cost = 8
    for v in row.values():
        if isinstance(v, (bytes, str)):
            cost += len(v) + 8
        elif isinstance(v, (list, tuple, dict)):
            cost += 16 * (len(v) + 1)
        else:
            cost += 8
    return cost


class IngestWriter:
    """The append buffer of one lake table (one per daemon)."""

    def __init__(
        self,
        table: LakeTable,
        *,
        flush_bytes: int = 4 << 20,
        codec: str = "snappy",
        row_group_size: int = 1 << 16,
        parallel=True,
        clock=time.time,
    ):
        if flush_bytes < 1:
            raise ValueError("ingest: flush_bytes must be >= 1")
        self.table = table
        self.flush_bytes = int(flush_bytes)
        self.codec = codec
        self.row_group_size = int(row_group_size)
        self.parallel = parallel
        self._clock = clock
        self._lock = threading.Lock()
        self._rows: list = []
        self._buffered = 0
        self._closed = False
        self.appended_rows = 0
        self.flushes = 0

    @property
    def buffered_rows(self) -> int:
        return len(self._rows)

    def append(self, rows, *, flush: bool = False) -> dict:
        """Buffer `rows`; flush when asked or when the buffer crosses the
        size bound. Returns the ack body: rows taken, buffered backlog,
        and the generation the rows are durable under (None = buffered
        only — not yet committed)."""
        rows = list(rows)
        with self._lock:
            if self._closed:
                raise LakeError("ingest: writer is closed", code="closed")
            self._rows.extend(rows)
            cost = sum(_row_cost(r) for r in rows)
            self._buffered += cost
            self.appended_rows += len(rows)
            _metrics.inc("lake_append_rows_total", len(rows))
            _metrics.inc("lake_append_bytes_total", cost)
            snap = None
            if self._rows and (flush or self._buffered >= self.flush_bytes):
                snap = self._flush_locked()
            return {
                "rows": len(rows),
                "buffered_rows": len(self._rows),
                "flushed": snap is not None,
                "generation": (
                    snap.generation
                    if snap is not None
                    else self.table.manifest.current_generation() or None
                ),
            }

    def flush(self):
        """Commit the buffer as one file + one generation; None if empty."""
        with self._lock:
            if self._closed:
                raise LakeError("ingest: writer is closed", code="closed")
            if not self._rows:
                return None
            return self._flush_locked()

    def _flush_locked(self) -> Snapshot:
        rows, self._rows = self._rows, []
        self._buffered = 0
        key = self.table.sort_key
        if key is not None:
            # sort-keyed flushes: every committed file carries tight
            # min/max key stats, so even pre-compaction scans prune
            rows.sort(key=lambda r: (r.get(key) is None, r.get(key)))
        rel = os.path.join(
            "data", f"ingest-{os.getpid()}-{next(_FILE_SEQ):06d}.parquet"
        )
        path = self.table.manifest.data_path(rel)
        self.table.manifest.ensure_dirs()
        t0 = time.perf_counter()
        writer = FileWriter(
            path,
            self.table.schema,
            codec=self.codec,
            row_group_size=self.row_group_size,
            parallel=self.parallel,
            sorting_columns=[key] if key is not None else None,
            key_value_metadata={"parquet_tpu.lake": "ingest"},
        )
        try:
            writer.write_rows(rows)
            writer.close()
        except BaseException:
            writer.abort()
            # the buffer is gone but nothing was committed: surface the
            # failure to the caller, who still owns the rows it sent
            raise
        nbytes = os.path.getsize(path)
        min_key = max_key = None
        if key is not None:
            keyed = [r.get(key) for r in rows if r.get(key) is not None]
            if keyed:
                min_key, max_key = keyed[0], keyed[-1]
        snap = self.table.manifest.commit(
            add=[FileEntry(rel, len(rows), nbytes, min_key, max_key)],
            sort_key=key,
        )
        self.flushes += 1
        _metrics.inc("lake_flushes_total")
        _metrics.observe("lake_flush_seconds", time.perf_counter() - t0)
        return snap

    def close(self):
        """Flush the tail and refuse further appends. Returns the final
        snapshot (None when nothing was buffered)."""
        with self._lock:
            if self._closed:
                return None
            snap = self._flush_locked() if self._rows else None
            self._closed = True
            return snap

"""The lake table's snapshot manifest: generation-numbered, atomic, bounded.

A lake table is a directory:

    table/
      _lake/
        TABLE.json          # immutable identity: schema DSL + sort key
        CURRENT             # {"generation": N} — THE commit point
        gen-00000001.json   # one manifest per generation (file list +
        gen-00000002.json   #   per-file row/byte counts + sort-key range)
      data/
        ingest-*.parquet    # flush-committed append files
        compact-*.parquet   # compactor rewrites

Every metadata write goes through the LocalFileSink tmp+fsync+rename
contract, so readers NEVER observe a torn manifest: a generation file is
written durably first, then CURRENT is renamed over — the rename of
CURRENT is the single commit point. A crash between the two leaves an
unreferenced gen file (harmless; the next commit overwrites that slot or
moves past it), a crash before either leaves nothing.

open_snapshot(gen=None) pins one generation: the returned Snapshot's file
list never changes under the reader, which is what makes concurrent
append/compact/scan race-free on the happy path (the PR 13 size/mtime and
ETag generation machinery stays as the typed backstop for out-of-band
rewrites). Generations are retained up to `retain` back from current —
time travel within the window is byte-identical because a data file is
unlinked ONLY when no retained generation references it (and only after
the dropping commit is durable). Orphan data/tmp files — a crash between
a compactor rewrite and its manifest commit — are reaped by
reap_orphans(), age-gated so in-flight writers are never raced.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..core.schema import Schema
from ..schema.dsl import parse_schema, schema_to_string
from ..sink.sink import LocalFileSink
from ..utils import metrics as _metrics

__all__ = [
    "LakeError",
    "FileEntry",
    "Snapshot",
    "LakeManifest",
    "LakeTable",
    "is_lake_table",
    "manifest_ref_root",
]

_LAKE_DIR = "_lake"
_DATA_DIR = "data"
_CURRENT = "CURRENT"
_GEN_FMT = "gen-%08d.json"


class LakeError(RuntimeError):
    """Typed lake failure; `code` is the machine-readable taxonomy the
    serve layer maps onto ServeError codes."""

    def __init__(self, message: str, *, code: str = "lake_error"):
        super().__init__(message)
        self.code = code


def _check_rel(path: str) -> str:
    """Manifest file entries are table-relative POSIX paths; anything that
    could escape the table root is refused at both write and read time
    (a hand-edited manifest must not become a confinement escape)."""
    p = str(path).replace(os.sep, "/")
    if not p or p.startswith("/") or os.path.isabs(p):
        raise LakeError(
            f"manifest: absolute file path {path!r}", code="bad_manifest"
        )
    if any(seg in ("", "..") for seg in p.split("/")):
        raise LakeError(
            f"manifest: path {path!r} escapes the table root",
            code="bad_manifest",
        )
    return p


class FileEntry:
    """One data file of one generation: where it is (table-relative), how
    many rows/bytes it holds, and the sort-key range it covers (None when
    the table has no sort key)."""

    __slots__ = ("path", "rows", "bytes", "min_key", "max_key")

    def __init__(self, path, rows, nbytes, min_key=None, max_key=None):
        self.path = _check_rel(path)
        self.rows = int(rows)
        self.bytes = int(nbytes)
        self.min_key = min_key
        self.max_key = max_key

    def to_dict(self) -> dict:
        d = {"path": self.path, "rows": self.rows, "bytes": self.bytes}
        if self.min_key is not None:
            d["min_key"] = self.min_key
        if self.max_key is not None:
            d["max_key"] = self.max_key
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FileEntry":
        try:
            return cls(
                d["path"], d["rows"], d["bytes"],
                d.get("min_key"), d.get("max_key"),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise LakeError(
                f"manifest: bad file entry {d!r}: {e}", code="bad_manifest"
            ) from None

    def __repr__(self):
        return f"FileEntry({self.path!r}, rows={self.rows}, bytes={self.bytes})"


class Snapshot:
    """One pinned generation: an immutable view of the table."""

    __slots__ = ("generation", "parent", "sort_key", "files", "created_unix")

    def __init__(self, generation, parent, sort_key, files, created_unix):
        self.generation = int(generation)
        self.parent = parent
        self.sort_key = sort_key
        self.files = tuple(files)
        self.created_unix = created_unix

    @property
    def total_rows(self) -> int:
        return sum(f.rows for f in self.files)

    @property
    def total_bytes(self) -> int:
        return sum(f.bytes for f in self.files)

    def paths(self, root) -> list:
        """Absolute data-file paths, in manifest order."""
        root = os.fspath(root)
        return [os.path.join(root, f.path) for f in self.files]

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "parent": self.parent,
            "sort_key": self.sort_key,
            "created_unix": self.created_unix,
            "files": [f.to_dict() for f in self.files],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Snapshot":
        try:
            gen = int(d["generation"])
        except (KeyError, TypeError, ValueError):
            raise LakeError(
                "manifest: no usable generation number", code="bad_manifest"
            ) from None
        return cls(
            gen,
            d.get("parent"),
            d.get("sort_key"),
            [FileEntry.from_dict(f) for f in d.get("files", [])],
            d.get("created_unix"),
        )


def _write_json_atomic(path: str, obj: dict) -> None:
    """tmp + fsync + rename through the sink contract: the destination is
    either the old bytes or the complete new document, never a prefix."""
    sink = LocalFileSink(path)
    try:
        sink.write((json.dumps(obj, indent=1) + "\n").encode())
        sink.close()
    except BaseException:
        sink.abort()
        raise


def _read_json(path: str):
    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        raise LakeError(
            f"manifest: unreadable {path!r}: {e}", code="bad_manifest"
        ) from None


def is_lake_table(path) -> bool:
    """Does `path` look like a lake table root (a committed CURRENT)?"""
    try:
        return os.path.isfile(
            os.path.join(os.fspath(path), _LAKE_DIR, _CURRENT)
        )
    except (TypeError, ValueError):
        return False


def manifest_ref_root(path):
    """When `path` names a pinned manifest file (…/_lake/gen-N.json),
    return (table_root, generation); else None. This is how a scan spec
    pins one generation: pass the gen file instead of the table dir."""
    s = os.fspath(path)
    parent = os.path.dirname(s)
    name = os.path.basename(s)
    if (
        os.path.basename(parent) == _LAKE_DIR
        and name.startswith("gen-")
        and name.endswith(".json")
    ):
        gen_str = name[len("gen-"):-len(".json")]
        if gen_str.isdigit():
            return os.path.dirname(parent), int(gen_str)
    return None


class LakeManifest:
    """The generation log of one table. Thread-safe for one writing
    process (the daemon): commits serialize under an internal lock; any
    number of readers in any process pin snapshots lock-free."""

    def __init__(self, root, *, retain: int = 64, clock=time.time):
        if retain < 1:
            raise ValueError("manifest: retain must be >= 1")
        self.root = os.path.realpath(os.fspath(root))
        self.retain = int(retain)
        self._clock = clock
        self._lock = threading.Lock()
        self.lake_dir = os.path.join(self.root, _LAKE_DIR)
        self.data_dir = os.path.join(self.root, _DATA_DIR)

    # -- layout ----------------------------------------------------------------

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.lake_dir, _GEN_FMT % gen)

    def _current_path(self) -> str:
        return os.path.join(self.lake_dir, _CURRENT)

    def data_path(self, rel: str) -> str:
        return os.path.join(self.root, _check_rel(rel))

    def ensure_dirs(self) -> None:
        os.makedirs(self.lake_dir, exist_ok=True)
        os.makedirs(self.data_dir, exist_ok=True)

    # -- reads -----------------------------------------------------------------

    def current_generation(self) -> int:
        """The committed generation number; 0 = empty table (no commit)."""
        cur = _read_json(self._current_path())
        if cur is None:
            return 0
        try:
            return int(cur["generation"])
        except (KeyError, TypeError, ValueError):
            raise LakeError(
                f"manifest: corrupt CURRENT in {self.lake_dir!r}",
                code="bad_manifest",
            ) from None

    def generations(self) -> list:
        """Retained generation numbers on disk, ascending."""
        try:
            names = os.listdir(self.lake_dir)
        except FileNotFoundError:
            return []
        out = []
        for n in names:
            if n.startswith("gen-") and n.endswith(".json"):
                g = n[len("gen-"):-len(".json")]
                if g.isdigit():
                    out.append(int(g))
        return sorted(out)

    def open_snapshot(self, gen=None) -> Snapshot:
        """Pin one generation (default: current). Generation 0 is the
        empty table. A requested generation outside the retained window
        is a typed error — time travel is bounded by `retain`."""
        if gen is None:
            gen = self.current_generation()
        gen = int(gen)
        if gen == 0:
            return Snapshot(0, None, None, (), None)
        doc = _read_json(self._gen_path(gen))
        if doc is None:
            raise LakeError(
                f"manifest: generation {gen} is not retained "
                f"(have {self.generations() or 'none'})",
                code="no_such_generation",
            )
        snap = Snapshot.from_dict(doc)
        if snap.generation != gen:
            raise LakeError(
                f"manifest: {self._gen_path(gen)!r} claims generation "
                f"{snap.generation}", code="bad_manifest",
            )
        return snap

    # -- the one write path ----------------------------------------------------

    def commit(
        self, *, add=(), remove=(), sort_key=None, expect_generation=None,
    ) -> Snapshot:
        """Publish generation N+1 = current − `remove` + `add`, atomically.

        The gen file lands durably FIRST, then CURRENT renames over: a
        crash at any instant leaves the previous generation fully intact.
        After the commit point, generations older than the retention
        window are dropped, and any data file referenced ONLY by dropped
        generations is unlinked — never a file the new generation (or any
        retained one) still names, which is what keeps open_snapshot(k)
        byte-identical for every retained k across later compactions."""
        add = list(add)
        remove = {_check_rel(r) for r in remove}
        with self._lock:
            base_gen = self.current_generation()
            if expect_generation is not None and base_gen != expect_generation:
                raise LakeError(
                    f"manifest: concurrent commit (expected generation "
                    f"{expect_generation}, found {base_gen})",
                    code="commit_conflict",
                )
            base = self.open_snapshot(base_gen)
            have = {f.path for f in base.files}
            missing = remove - have
            if missing:
                raise LakeError(
                    f"manifest: cannot remove unreferenced {sorted(missing)}",
                    code="commit_conflict",
                )
            files = [f for f in base.files if f.path not in remove]
            for entry in add:
                if entry.path in have and entry.path not in remove:
                    raise LakeError(
                        f"manifest: {entry.path!r} already referenced",
                        code="commit_conflict",
                    )
                files.append(entry)
            self.ensure_dirs()
            new_gen = base_gen + 1
            snap = Snapshot(
                new_gen,
                base_gen or None,
                sort_key if sort_key is not None else base.sort_key,
                files,
                self._clock(),
            )
            _write_json_atomic(self._gen_path(new_gen), snap.to_dict())
            # THE commit point: readers switch generations on this rename
            _write_json_atomic(
                self._current_path(), {"generation": new_gen}
            )
            _metrics.inc("lake_manifest_commits_total")
            _metrics.set_gauge("lake_generation", new_gen)
            _metrics.set_gauge("lake_files", len(files))
            _metrics.set_gauge("lake_rows", snap.total_rows)
            self._prune_retention(new_gen)
            return snap

    def _prune_retention(self, current_gen: int) -> None:
        """Drop generations older than the window; unlink data files no
        retained generation references. Runs AFTER the commit is durable
        (lock held). Every unlink is best-effort — a lost race with an
        external cleaner must not fail the commit that triggered it."""
        floor = current_gen - self.retain + 1
        drop = [g for g in self.generations() if g < floor]
        if not drop:
            return
        retained = set()
        for g in self.generations():
            if g >= floor:
                try:
                    retained.update(
                        f.path for f in self.open_snapshot(g).files
                    )
                except LakeError:
                    continue
        for g in drop:
            try:
                old = self.open_snapshot(g)
            except LakeError:
                old = None
            if old is not None:
                for f in old.files:
                    if f.path not in retained:
                        try:
                            os.unlink(self.data_path(f.path))
                            _metrics.inc("lake_files_unlinked_total")
                        except OSError:
                            pass
            try:
                os.unlink(self._gen_path(g))
            except OSError:
                pass

    # -- crash hygiene ---------------------------------------------------------

    def reap_orphans(self, *, grace_s: float = 300.0) -> int:
        """Remove data-dir debris no retained generation references: sink
        tmp files (a writer that died mid-write) and committed-but-never-
        published parquet files (a crash between a rewrite and its
        manifest commit). Age-gated by `grace_s` so a file an in-flight
        writer is about to publish is never raced. Returns files removed;
        loses nothing — by definition nothing referenced is touched."""
        try:
            names = os.listdir(self.data_dir)
        except FileNotFoundError:
            return 0
        with self._lock:
            referenced = set()
            for g in self.generations():
                try:
                    referenced.update(
                        os.path.basename(f.path)
                        for f in self.open_snapshot(g).files
                    )
                except LakeError:
                    continue
            now = time.time()
            reaped = 0
            for name in names:
                if name in referenced:
                    continue
                is_tmp = name.startswith(".") and name.endswith(".tmp")
                if not (is_tmp or name.endswith(".parquet")):
                    continue
                path = os.path.join(self.data_dir, name)
                try:
                    if now - os.path.getmtime(path) < grace_s:
                        continue
                    os.unlink(path)
                    reaped += 1
                except OSError:
                    continue
            if reaped:
                _metrics.inc("lake_orphans_reaped_total", reaped)
            return reaped


class LakeTable:
    """A table = identity (schema + sort key, immutable) + its manifest.

    create() writes _lake/TABLE.json once; open() reads it back. The
    schema is stored as DSL text (schema/dsl.py round-trips exactly), so
    a table is self-describing to any process with no side channel."""

    def __init__(self, root, schema: Schema, sort_key, manifest: LakeManifest):
        self.root = manifest.root
        self.schema = schema
        self.sort_key = sort_key
        self.manifest = manifest

    @staticmethod
    def _table_path(root) -> str:
        return os.path.join(os.fspath(root), _LAKE_DIR, "TABLE.json")

    @classmethod
    def create(
        cls, root, schema, *, sort_key=None, retain: int = 64,
        clock=time.time,
    ) -> "LakeTable":
        if isinstance(schema, str):
            schema = parse_schema(schema)
        if sort_key is not None:
            leaves = {c.path_str for c in schema.leaves}
            if sort_key not in leaves:
                raise LakeError(
                    f"lake: sort key {sort_key!r} is not a schema leaf "
                    f"(have {sorted(leaves)})", code="bad_schema",
                )
        manifest = LakeManifest(root, retain=retain, clock=clock)
        if os.path.exists(cls._table_path(manifest.root)):
            raise LakeError(
                f"lake: table already exists at {manifest.root!r}",
                code="table_exists",
            )
        manifest.ensure_dirs()
        _write_json_atomic(
            cls._table_path(manifest.root),
            {
                "schema": schema_to_string(schema),
                "sort_key": sort_key,
                "retain": int(retain),
                "created_unix": clock(),
            },
        )
        return cls(manifest.root, schema, sort_key, manifest)

    @classmethod
    def open(cls, root, *, clock=time.time) -> "LakeTable":
        manifest_root = os.path.realpath(os.fspath(root))
        doc = _read_json(cls._table_path(manifest_root))
        if doc is None:
            raise LakeError(
                f"lake: no table at {manifest_root!r} (missing "
                f"{_LAKE_DIR}/TABLE.json — create it first)",
                code="no_such_table",
            )
        try:
            schema = parse_schema(doc["schema"])
        except (KeyError, TypeError, ValueError) as e:
            raise LakeError(
                f"lake: corrupt TABLE.json at {manifest_root!r}: {e}",
                code="bad_manifest",
            ) from None
        manifest = LakeManifest(
            manifest_root, retain=int(doc.get("retain") or 64), clock=clock
        )
        return cls(manifest_root, schema, doc.get("sort_key"), manifest)

    def snapshot_paths(self, gen=None) -> list:
        """Absolute file paths of one pinned generation (default current)."""
        return self.manifest.open_snapshot(gen).paths(self.root)

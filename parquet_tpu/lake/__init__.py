"""parquet_tpu.lake — the write-path table layer (Iceberg-lite).

Streaming ingest (serve /v1/append -> IngestWriter), background
compaction (Compactor, pqt-compact lane), and the atomic snapshot
manifest (LakeManifest) that makes concurrent append/scan/compact
race-free: every reader pins ONE generation, every writer publishes by
a single rename. See lake/manifest.py for the layout and crash story.
"""

from .compactor import CompactionResult, Compactor, pruned_ratio
from .ingest import IngestWriter, rows_from_payload
from .manifest import (
    FileEntry,
    LakeError,
    LakeManifest,
    LakeTable,
    Snapshot,
    is_lake_table,
    manifest_ref_root,
)

__all__ = [
    "CompactionResult",
    "Compactor",
    "FileEntry",
    "IngestWriter",
    "LakeError",
    "LakeManifest",
    "LakeTable",
    "Snapshot",
    "is_lake_table",
    "manifest_ref_root",
    "pruned_ratio",
    "rows_from_payload",
]

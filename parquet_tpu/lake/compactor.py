"""Background compaction: fold small ingest files into sort-keyed row
groups, one manifest generation per fold.

Every flush commits one small file; a few hundred flushes later the table
is a pile of row groups whose key ranges all overlap, and a filtered scan
prunes almost nothing. compact_once() picks the small files of the
current snapshot, rewrites them as ONE file — a k-way merge by the
table's sort key into full-size row groups (each carrying tight min/max
stats and a sorting_columns declaration), or a verbatim merge_files fold
when the table has no key — and commits the swap as one generation.

Crash safety is inherited, not bolted on: the rewrite lands through the
atomic sink, the manifest commit is the only publish, and the replaced
files stay on disk until retention drops every generation referencing
them (manifest._prune_retention). A crash at ANY point between rewrite
and commit loses nothing — the orphan rewrite is reaped by
reap_orphans() on a later cycle.

The worker thread is its own pool lane ("pqt-compact", sampled by
obs/prof like every other lane) and is clock-injectable: tests drive
compact_once() directly or tick a fake clock.

The before/after `pruned_ratio` recorded on each CompactionResult is the
measurable point of the exercise: the fraction of row-group units a
sort-key point probe (at the merged run's median key) prunes at plan
time, before vs after the rewrite.
"""

from __future__ import annotations

import heapq
import itertools
import os
import threading
import time

from ..core.merge import merge_files
from ..core.reader import FileReader
from ..core.writer import FileWriter
from ..obs import log as _obslog
from ..utils import metrics as _metrics
from .ingest import _FILE_SEQ
from .manifest import FileEntry, LakeError, LakeTable

__all__ = ["CompactionResult", "Compactor", "pruned_ratio"]


def pruned_ratio(paths, filters) -> float:
    """Fraction of row-group units plan-time pruning excludes for
    `filters` over `paths` (0.0 when there are no units)."""
    from ..data.plan import build_plan

    plan = build_plan(list(paths), filters=filters)
    if not plan.units_total:
        return 0.0
    pruned = plan.units_pruned_stats + plan.units_pruned_bloom
    return pruned / plan.units_total


class CompactionResult:
    """What one fold did, for operators and the bench trend store."""

    __slots__ = (
        "generation", "files_in", "rows", "bytes_in", "bytes_out",
        "pruned_ratio_before", "pruned_ratio_after", "seconds",
    )

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class Compactor:
    """One table's background folder. start()/stop() run the loop on a
    pqt-compact thread; compact_once() is the whole unit of work."""

    def __init__(
        self,
        table: LakeTable,
        *,
        min_files: int = 2,
        max_files: int = 32,
        small_file_bytes: int = 64 << 20,
        row_group_size: int = 1 << 16,
        codec: str = "snappy",
        interval_s: float = 5.0,
        reap_grace_s: float = 300.0,
        clock=time.monotonic,
    ):
        if min_files < 2:
            raise ValueError("compactor: min_files must be >= 2")
        if max_files < min_files:
            raise ValueError("compactor: max_files must be >= min_files")
        self.table = table
        self.min_files = int(min_files)
        self.max_files = int(max_files)
        self.small_file_bytes = int(small_file_bytes)
        self.row_group_size = int(row_group_size)
        self.codec = codec
        self.interval_s = float(interval_s)
        self.reap_grace_s = float(reap_grace_s)
        self._clock = clock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.compactions = 0
        self.last_result: CompactionResult | None = None

    # -- candidate selection ---------------------------------------------------

    def _candidates(self, snap):
        small = [
            f for f in snap.files
            if f.bytes < self.small_file_bytes and f.rows > 0
        ]
        if len(small) < self.min_files:
            return []
        return small[: self.max_files]

    # -- one fold --------------------------------------------------------------

    def compact_once(self):
        """Fold the current snapshot's small files into one; None when
        there is nothing worth folding."""
        manifest = self.table.manifest
        snap = manifest.open_snapshot()
        picked = self._candidates(snap)
        if not picked:
            return None
        t0 = time.perf_counter()
        key = self.table.sort_key
        in_paths = [manifest.data_path(f.path) for f in picked]
        rel = os.path.join(
            "data", f"compact-{os.getpid()}-{next(_FILE_SEQ):06d}.parquet"
        )
        out_path = manifest.data_path(rel)
        manifest.ensure_dirs()
        probe = None
        if key is not None:
            rows, min_key, max_key, probe = self._sorted_rewrite(
                in_paths, out_path, key
            )
        else:
            # no sort key: a verbatim row-group fold (no re-encode) still
            # collapses per-file overhead and footer count
            merge_files(
                out_path, in_paths,
                key_value_metadata={"parquet_tpu.lake": "compact"},
            )
            rows = sum(f.rows for f in picked)
            min_key = max_key = None
        before = after = None
        if probe is not None:
            filters = [(key, "==", probe)]
            try:
                before = pruned_ratio(in_paths, filters)
                after = pruned_ratio([out_path], filters)
            except (ValueError, OSError):
                before = after = None
        # THE swap: one generation replaces the inputs with the fold. The
        # inputs stay on disk for every retained generation that still
        # names them; retention (not this thread) unlinks them later.
        gen = manifest.commit(
            add=[FileEntry(rel, rows, os.path.getsize(out_path),
                           min_key, max_key)],
            remove=[f.path for f in picked],
        )
        dt = time.perf_counter() - t0
        result = CompactionResult(
            generation=gen.generation,
            files_in=len(picked),
            rows=rows,
            bytes_in=sum(f.bytes for f in picked),
            bytes_out=os.path.getsize(out_path),
            pruned_ratio_before=before,
            pruned_ratio_after=after,
            seconds=dt,
        )
        self.compactions += 1
        self.last_result = result
        _metrics.inc("lake_compactions_total")
        _metrics.inc("lake_compact_files_total", len(picked))
        _metrics.inc("lake_compact_rows_total", rows)
        _metrics.observe("lake_compact_seconds", dt)
        _obslog.log_event(
            "lake_compaction",
            generation=gen.generation,
            files_in=len(picked),
            rows=rows,
            pruned_ratio_before=before,
            pruned_ratio_after=after,
        )
        return result

    def _sorted_rewrite(self, in_paths, out_path, key):
        """k-way merge every input's rows by `key` into one sorted file.
        Inputs are themselves key-sorted (ingest flushes sort), so the
        heap holds one row per input — the memory bound is files, not
        rows. Returns (rows, min_key, max_key, median probe key)."""

        def keyed(path):
            with FileReader(path) as r:
                for row in r.iter_rows():
                    v = row.get(key)
                    yield ((v is None, v), row)

        writer = FileWriter(
            out_path,
            self.table.schema,
            codec=self.codec,
            row_group_size=self.row_group_size,
            sorting_columns=[key],
            key_value_metadata={"parquet_tpu.lake": "compact"},
        )
        rows = 0
        min_key = max_key = None
        keys_seen: list = []
        try:
            merged = heapq.merge(
                *(keyed(p) for p in in_paths), key=lambda kr: kr[0]
            )
            for k, row in merged:
                writer.write_row(row)
                rows += 1
                if not k[0]:
                    if min_key is None:
                        min_key = k[1]
                    max_key = k[1]
                    keys_seen.append(k[1])
            writer.close()
        except BaseException:
            writer.abort()
            raise
        probe = keys_seen[len(keys_seen) // 2] if keys_seen else None
        return rows, min_key, max_key, probe

    # -- the background loop ---------------------------------------------------

    def run_cycle(self) -> None:
        """One loop body: fold if worthwhile, then reap crash debris."""
        try:
            self.compact_once()
        except LakeError as e:
            # commit_conflict = an append won the race; next cycle re-plans
            _obslog.log_event(
                "lake_compact_skipped", level="warning",
                reason=getattr(e, "code", "lake_error"), detail=str(e),
            )
        self.table.manifest.reap_orphans(grace_s=self.reap_grace_s)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_cycle()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                _obslog.log_event(
                    "lake_compact_error", level="error",
                    error=f"{type(e).__name__}: {e}",
                )

    def start(self) -> "Compactor":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="pqt-compact", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

"""Parquet file footer handling: magic validation, footer length, FileMetaData.

Semantics follow the reference's file_meta.go: `PAR1` magic at both ends
(file_meta.go:14), 8-byte tail = footer length + magic, strict size checks before
reading (file_meta.go:25-62).
"""

from __future__ import annotations

import io
import struct

from .parquet_types import FileMetaData
from .thrift import CompactReader, ThriftError

MAGIC = b"PAR1"
FOOTER_TAIL = 8  # 4-byte little-endian footer length + MAGIC


class ParquetFileError(ValueError):
    pass


def read_file_metadata(f) -> FileMetaData:
    """Read and validate the footer of a seekable binary stream.

    Mirrors ReadFileMetaData (reference: file_meta.go:18-74): validates leading and
    trailing magic, bounds-checks the footer length against the file size, then
    decodes the Thrift FileMetaData.
    """
    size = f.seek(0, io.SEEK_END)
    if size < len(MAGIC) + FOOTER_TAIL:
        raise ParquetFileError(f"parquet: file too small ({size} bytes)")
    f.seek(0)
    if f.read(4) != MAGIC:
        raise ParquetFileError("parquet: invalid leading magic, not a parquet file")
    f.seek(size - FOOTER_TAIL)
    tail = f.read(FOOTER_TAIL)
    if tail[4:] != MAGIC:
        raise ParquetFileError("parquet: invalid trailing magic, not a parquet file")
    (footer_len,) = struct.unpack("<I", tail[:4])
    if footer_len == 0 or footer_len > size - len(MAGIC) - FOOTER_TAIL:
        raise ParquetFileError(f"parquet: invalid footer length {footer_len}")
    f.seek(size - FOOTER_TAIL - footer_len)
    footer = f.read(footer_len)
    if len(footer) != footer_len:
        raise ParquetFileError("parquet: truncated footer")
    try:
        meta = FileMetaData.read(CompactReader(footer))
    except (ThriftError, RecursionError) as e:
        # Internal decode errors are converted at the API boundary, the way the
        # reference recovers panics into errors (reference: file_reader.go:177-184).
        raise ParquetFileError(f"parquet: corrupt footer: {e}") from e
    if not meta.schema:
        raise ParquetFileError("parquet: footer has no schema")
    return meta


def serialize_footer(meta: FileMetaData) -> bytes:
    """Footer bytes (thrift + length + magic) to append after the last row group,
    as FileWriter.Close does (reference: file_writer.go:325-347)."""
    payload = meta.dumps()
    return payload + struct.pack("<I", len(payload)) + MAGIC

"""Parquet file-format metadata model (parquet-format 2.9.0).

Declarative equivalents of the structs the reference uses from its 12.5k-line
generated Thrift model (reference: parquet/parquet.go — Type :27, Encoding :344,
CompressionCodec :444, SchemaElement :3663, DataPageHeader :4314). Field ids and
types follow the public parquet-format thrift IDL.
"""

from __future__ import annotations

import enum

from .thrift import (
    T_BOOL,
    T_BYTE,
    T_I16,
    T_I32,
    T_I64,
    T_BINARY,
    T_STRING,
    T_LIST,
    T_STRUCT,
    TStruct,
)


class Type(enum.IntEnum):
    """Physical types (parquet.thrift Type)."""

    BOOLEAN = 0
    INT32 = 1
    INT64 = 2
    INT96 = 3
    FLOAT = 4
    DOUBLE = 5
    BYTE_ARRAY = 6
    FIXED_LEN_BYTE_ARRAY = 7


class ConvertedType(enum.IntEnum):
    UTF8 = 0
    MAP = 1
    MAP_KEY_VALUE = 2
    LIST = 3
    ENUM = 4
    DECIMAL = 5
    DATE = 6
    TIME_MILLIS = 7
    TIME_MICROS = 8
    TIMESTAMP_MILLIS = 9
    TIMESTAMP_MICROS = 10
    UINT_8 = 11
    UINT_16 = 12
    UINT_32 = 13
    UINT_64 = 14
    INT_8 = 15
    INT_16 = 16
    INT_32 = 17
    INT_64 = 18
    JSON = 19
    BSON = 20
    INTERVAL = 21


class FieldRepetitionType(enum.IntEnum):
    REQUIRED = 0
    OPTIONAL = 1
    REPEATED = 2


class Encoding(enum.IntEnum):
    PLAIN = 0
    PLAIN_DICTIONARY = 2
    RLE = 3
    BIT_PACKED = 4
    DELTA_BINARY_PACKED = 5
    DELTA_LENGTH_BYTE_ARRAY = 6
    DELTA_BYTE_ARRAY = 7
    RLE_DICTIONARY = 8
    BYTE_STREAM_SPLIT = 9


class CompressionCodec(enum.IntEnum):
    UNCOMPRESSED = 0
    SNAPPY = 1
    GZIP = 2
    LZO = 3
    BROTLI = 4
    LZ4 = 5
    ZSTD = 6
    LZ4_RAW = 7


class PageType(enum.IntEnum):
    DATA_PAGE = 0
    INDEX_PAGE = 1
    DICTIONARY_PAGE = 2
    DATA_PAGE_V2 = 3


# -- logical types (union of empty/parameterized structs) ----------------------


class StringType(TStruct):
    FIELDS = {}


class MapType(TStruct):
    FIELDS = {}


class ListType(TStruct):
    FIELDS = {}


class EnumType(TStruct):
    FIELDS = {}


class DateType(TStruct):
    FIELDS = {}


class NullType(TStruct):
    FIELDS = {}


class JsonType(TStruct):
    FIELDS = {}


class BsonType(TStruct):
    FIELDS = {}


class UUIDType(TStruct):
    FIELDS = {}


class Float16Type(TStruct):
    FIELDS = {}


class DecimalType(TStruct):
    FIELDS = {
        1: ("scale", T_I32, None),
        2: ("precision", T_I32, None),
    }


class MilliSeconds(TStruct):
    FIELDS = {}


class MicroSeconds(TStruct):
    FIELDS = {}


class NanoSeconds(TStruct):
    FIELDS = {}


class TimeUnit(TStruct):
    """Union MILLIS / MICROS / NANOS."""

    FIELDS = {
        1: ("MILLIS", T_STRUCT, MilliSeconds),
        2: ("MICROS", T_STRUCT, MicroSeconds),
        3: ("NANOS", T_STRUCT, NanoSeconds),
    }

    def unit_name(self) -> str:
        if self.MILLIS is not None:
            return "MILLIS"
        if self.MICROS is not None:
            return "MICROS"
        if self.NANOS is not None:
            return "NANOS"
        return "?"

    @classmethod
    def millis(cls):
        return cls(MILLIS=MilliSeconds())

    @classmethod
    def micros(cls):
        return cls(MICROS=MicroSeconds())

    @classmethod
    def nanos(cls):
        return cls(NANOS=NanoSeconds())


class TimestampType(TStruct):
    FIELDS = {
        1: ("isAdjustedToUTC", T_BOOL, None),
        2: ("unit", T_STRUCT, TimeUnit),
    }


class TimeType(TStruct):
    FIELDS = {
        1: ("isAdjustedToUTC", T_BOOL, None),
        2: ("unit", T_STRUCT, TimeUnit),
    }


class IntType(TStruct):
    FIELDS = {
        1: ("bitWidth", T_BYTE, None),
        2: ("isSigned", T_BOOL, None),
    }


class LogicalType(TStruct):
    """Union over all logical type annotations (parquet.thrift LogicalType)."""

    FIELDS = {
        1: ("STRING", T_STRUCT, StringType),
        2: ("MAP", T_STRUCT, MapType),
        3: ("LIST", T_STRUCT, ListType),
        4: ("ENUM", T_STRUCT, EnumType),
        5: ("DECIMAL", T_STRUCT, DecimalType),
        6: ("DATE", T_STRUCT, DateType),
        7: ("TIME", T_STRUCT, TimeType),
        8: ("TIMESTAMP", T_STRUCT, TimestampType),
        # 9 reserved (interval)
        10: ("INTEGER", T_STRUCT, IntType),
        11: ("UNKNOWN", T_STRUCT, NullType),
        12: ("JSON", T_STRUCT, JsonType),
        13: ("BSON", T_STRUCT, BsonType),
        14: ("UUID", T_STRUCT, UUIDType),
        15: ("FLOAT16", T_STRUCT, Float16Type),
    }

    def which(self) -> str | None:
        for _fid, (name, _ft, _spec) in self.FIELDS.items():
            if getattr(self, name) is not None:
                return name
        return None


# -- schema / statistics -------------------------------------------------------


class SchemaElement(TStruct):
    FIELDS = {
        1: ("type", T_I32, None),
        2: ("type_length", T_I32, None),
        3: ("repetition_type", T_I32, None),
        4: ("name", T_STRING, None),
        5: ("num_children", T_I32, None),
        6: ("converted_type", T_I32, None),
        7: ("scale", T_I32, None),
        8: ("precision", T_I32, None),
        9: ("field_id", T_I32, None),
        10: ("logicalType", T_STRUCT, LogicalType),
    }


class Statistics(TStruct):
    FIELDS = {
        1: ("max", T_BINARY, None),
        2: ("min", T_BINARY, None),
        3: ("null_count", T_I64, None),
        4: ("distinct_count", T_I64, None),
        5: ("max_value", T_BINARY, None),
        6: ("min_value", T_BINARY, None),
        7: ("is_max_value_exact", T_BOOL, None),
        8: ("is_min_value_exact", T_BOOL, None),
    }


class SplitBlockAlgorithm(TStruct):
    FIELDS = {}


class BloomFilterAlgorithm(TStruct):
    FIELDS = {1: ("BLOCK", T_STRUCT, SplitBlockAlgorithm)}


class XxHash(TStruct):
    FIELDS = {}


class BloomFilterHash(TStruct):
    FIELDS = {1: ("XXHASH", T_STRUCT, XxHash)}


class BloomFilterUncompressed(TStruct):  # thrift name: Uncompressed
    FIELDS = {}


class BloomFilterCompression(TStruct):
    FIELDS = {1: ("UNCOMPRESSED", T_STRUCT, BloomFilterUncompressed)}


class BloomFilterHeader(TStruct):
    """Precedes the split-block bloom bitset at
    ColumnMetaData.bloom_filter_offset (parquet.thrift)."""

    FIELDS = {
        1: ("numBytes", T_I32, None),
        2: ("algorithm", T_STRUCT, BloomFilterAlgorithm),
        3: ("hash", T_STRUCT, BloomFilterHash),
        4: ("compression", T_STRUCT, BloomFilterCompression),
    }


class BoundaryOrder(enum.IntEnum):
    """Ordering of min/max values across a ColumnIndex (parquet.thrift)."""

    UNORDERED = 0
    ASCENDING = 1
    DESCENDING = 2


class PageLocation(TStruct):
    FIELDS = {
        1: ("offset", T_I64, None),
        2: ("compressed_page_size", T_I32, None),
        3: ("first_row_index", T_I64, None),
    }


class OffsetIndex(TStruct):
    """Per-page physical locations of one column chunk (the page index's
    row-range half; written after the row groups, referenced from
    ColumnChunk.offset_index_offset/_length)."""

    FIELDS = {
        1: ("page_locations", T_LIST, (T_STRUCT, PageLocation)),
        2: ("unencoded_byte_array_data_bytes", T_LIST, (T_I64, None)),
    }


class ColumnIndex(TStruct):
    """Per-page min/max/null statistics of one column chunk (the page
    index's pruning half; ColumnChunk.column_index_offset/_length)."""

    FIELDS = {
        1: ("null_pages", T_LIST, (T_BOOL, None)),
        2: ("min_values", T_LIST, (T_BINARY, None)),
        3: ("max_values", T_LIST, (T_BINARY, None)),
        4: ("boundary_order", T_I32, None),
        5: ("null_counts", T_LIST, (T_I64, None)),
        6: ("repetition_level_histograms", T_LIST, (T_I64, None)),
        7: ("definition_level_histograms", T_LIST, (T_I64, None)),
    }


class KeyValue(TStruct):
    FIELDS = {
        1: ("key", T_STRING, None),
        2: ("value", T_STRING, None),
    }


class SortingColumn(TStruct):
    FIELDS = {
        1: ("column_idx", T_I32, None),
        2: ("descending", T_BOOL, None),
        3: ("nulls_first", T_BOOL, None),
    }


class PageEncodingStats(TStruct):
    FIELDS = {
        1: ("page_type", T_I32, None),
        2: ("encoding", T_I32, None),
        3: ("count", T_I32, None),
    }


# -- column / row-group metadata -----------------------------------------------


class ColumnMetaData(TStruct):
    FIELDS = {
        1: ("type", T_I32, None),
        2: ("encodings", T_LIST, (T_I32, None)),
        3: ("path_in_schema", T_LIST, (T_STRING, None)),
        4: ("codec", T_I32, None),
        5: ("num_values", T_I64, None),
        6: ("total_uncompressed_size", T_I64, None),
        7: ("total_compressed_size", T_I64, None),
        8: ("key_value_metadata", T_LIST, (T_STRUCT, KeyValue)),
        9: ("data_page_offset", T_I64, None),
        10: ("index_page_offset", T_I64, None),
        11: ("dictionary_page_offset", T_I64, None),
        12: ("statistics", T_STRUCT, Statistics),
        13: ("encoding_stats", T_LIST, (T_STRUCT, PageEncodingStats)),
        14: ("bloom_filter_offset", T_I64, None),
        15: ("bloom_filter_length", T_I32, None),
    }


class ColumnChunk(TStruct):
    FIELDS = {
        1: ("file_path", T_STRING, None),
        2: ("file_offset", T_I64, None),
        3: ("meta_data", T_STRUCT, ColumnMetaData),
        4: ("offset_index_offset", T_I64, None),
        5: ("offset_index_length", T_I32, None),
        6: ("column_index_offset", T_I64, None),
        7: ("column_index_length", T_I32, None),
    }


class RowGroup(TStruct):
    FIELDS = {
        1: ("columns", T_LIST, (T_STRUCT, ColumnChunk)),
        2: ("total_byte_size", T_I64, None),
        3: ("num_rows", T_I64, None),
        4: ("sorting_columns", T_LIST, (T_STRUCT, SortingColumn)),
        5: ("file_offset", T_I64, None),
        6: ("total_compressed_size", T_I64, None),
        7: ("ordinal", T_I16, None),
    }


class TypeDefinedOrder(TStruct):
    FIELDS = {}


class ColumnOrder(TStruct):
    FIELDS = {
        1: ("TYPE_ORDER", T_STRUCT, TypeDefinedOrder),
    }


class FileMetaData(TStruct):
    FIELDS = {
        1: ("version", T_I32, None),
        2: ("schema", T_LIST, (T_STRUCT, SchemaElement)),
        3: ("num_rows", T_I64, None),
        4: ("row_groups", T_LIST, (T_STRUCT, RowGroup)),
        5: ("key_value_metadata", T_LIST, (T_STRUCT, KeyValue)),
        6: ("created_by", T_STRING, None),
        7: ("column_orders", T_LIST, (T_STRUCT, ColumnOrder)),
    }


# -- page headers --------------------------------------------------------------


class DataPageHeader(TStruct):
    FIELDS = {
        1: ("num_values", T_I32, None),
        2: ("encoding", T_I32, None),
        3: ("definition_level_encoding", T_I32, None),
        4: ("repetition_level_encoding", T_I32, None),
        5: ("statistics", T_STRUCT, Statistics),
    }


class IndexPageHeader(TStruct):
    FIELDS = {}


class DictionaryPageHeader(TStruct):
    FIELDS = {
        1: ("num_values", T_I32, None),
        2: ("encoding", T_I32, None),
        3: ("is_sorted", T_BOOL, None),
    }


class DataPageHeaderV2(TStruct):
    FIELDS = {
        1: ("num_values", T_I32, None),
        2: ("num_nulls", T_I32, None),
        3: ("num_rows", T_I32, None),
        4: ("encoding", T_I32, None),
        5: ("definition_levels_byte_length", T_I32, None),
        6: ("repetition_levels_byte_length", T_I32, None),
        7: ("is_compressed", T_BOOL, None),
        8: ("statistics", T_STRUCT, Statistics),
    }


class PageHeader(TStruct):
    FIELDS = {
        1: ("type", T_I32, None),
        2: ("uncompressed_page_size", T_I32, None),
        3: ("compressed_page_size", T_I32, None),
        4: ("crc", T_I32, None),
        5: ("data_page_header", T_STRUCT, DataPageHeader),
        6: ("index_page_header", T_STRUCT, IndexPageHeader),
        7: ("dictionary_page_header", T_STRUCT, DictionaryPageHeader),
        8: ("data_page_header_v2", T_STRUCT, DataPageHeaderV2),
    }

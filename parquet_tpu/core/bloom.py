"""Split-block bloom filters (parquet-format BloomFilter.md).

Beyond the reference (no bloom support there). A chunk's filter is an array
of 32-byte blocks (8 uint32 words); a value hashes with XXH64 (seed 0) over
its PLAIN-encoded bytes, the hash's top 32 bits pick the block, and the low
32 bits x 8 fixed odd salts pick one bit per word. Equality predicates on
high-cardinality columns — exactly where min/max statistics are useless —
prune row groups whose filter proves the value absent.

Hashing and block ops run in native C (utils/native.py); a pure-Python XXH64
keeps the feature correct without the library. pyarrow (bloom_filter_options)
is the cross-implementation write oracle.
"""

from __future__ import annotations

import math
import struct

import numpy as np

from ..meta.parquet_types import (
    BloomFilterAlgorithm,
    BloomFilterCompression,
    BloomFilterHash,
    BloomFilterHeader,
    BloomFilterUncompressed,
    SplitBlockAlgorithm,
    Type,
    XxHash,
)

__all__ = ["BloomFilter", "bloom_hash_values", "plain_bytes_for_hash"]

_SALT = np.array(
    [
        0x47B6137B, 0x44974D91, 0x8824AD5B, 0xA2B7289D,
        0x705495C7, 0x2DF1424B, 0x9EFC4947, 0x5C6BFB31,
    ],
    dtype=np.uint64,
)

_M64 = (1 << 64) - 1
_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def xxh64(data: bytes, seed: int = 0) -> int:
    """Pure-Python XXH64 (spec implementation; the native C path is the hot
    one — this keeps bloom filters correct without the library)."""
    p, end = 0, len(data)
    if end >= 32:
        vs = [
            (seed + _P1 + _P2) & _M64,
            (seed + _P2) & _M64,
            seed & _M64,
            (seed - _P1) & _M64,
        ]
        while p + 32 <= end:
            for j in range(4):
                lane = int.from_bytes(data[p + 8 * j : p + 8 * j + 8], "little")
                vs[j] = (_rotl((vs[j] + lane * _P2) & _M64, 31) * _P1) & _M64
            p += 32
        h = (
            _rotl(vs[0], 1) + _rotl(vs[1], 7) + _rotl(vs[2], 12) + _rotl(vs[3], 18)
        ) & _M64
        for acc in vs:
            h = ((h ^ (_rotl((acc * _P2) & _M64, 31) * _P1) & _M64) * _P1 + _P4) & _M64
    else:
        h = (seed + _P5) & _M64
    h = (h + end) & _M64
    while p + 8 <= end:
        k = (_rotl((int.from_bytes(data[p : p + 8], "little") * _P2) & _M64, 31) * _P1) & _M64
        h = (_rotl(h ^ k, 27) * _P1 + _P4) & _M64
        p += 8
    if p + 4 <= end:
        h = (_rotl(h ^ ((int.from_bytes(data[p : p + 4], "little") * _P1) & _M64), 23) * _P2 + _P3) & _M64
        p += 4
    while p < end:
        h = (_rotl(h ^ ((data[p] * _P5) & _M64), 11) * _P1) & _M64
        p += 1
    h ^= h >> 33
    h = (h * _P2) & _M64
    h ^= h >> 29
    h = (h * _P3) & _M64
    h ^= h >> 32
    return h


_FIXED_WIDTH = {Type.INT32: 4, Type.INT64: 8, Type.FLOAT: 4, Type.DOUBLE: 8}


def plain_bytes_for_hash(ptype, value, unsigned: bool = False) -> bytes | None:
    """PLAIN-encoded bytes of one filter value (the hash input), or None
    when the value has no exact physical form for this type."""
    try:
        if ptype == Type.INT32:
            return struct.pack("<I" if unsigned else "<i", value)
        if ptype == Type.INT64:
            return struct.pack("<Q" if unsigned else "<q", value)
        if ptype == Type.FLOAT:
            # +0.0 == -0.0 but their bit patterns differ; both sides of the
            # bloom (insert and probe) normalize to +0.0 so equality survives
            return struct.pack("<f", value + 0.0)
        if ptype == Type.DOUBLE:
            return struct.pack("<d", value + 0.0)
        if ptype in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
            if isinstance(value, str):
                return value.encode("utf-8")
            if isinstance(value, (bytes, bytearray, memoryview)):
                return bytes(value)
    except struct.error:
        return None
    return None


def bloom_hash_values(ptype, values) -> np.ndarray:
    """XXH64 of every value's PLAIN bytes -> uint64 hashes (native batch
    path when built)."""
    from ..utils.native import get_native
    from .arrays import ByteArrayData

    lib = get_native()
    if isinstance(values, ByteArrayData):
        if lib is not None and lib.has_xxh64:
            return lib.xxh64_offsets(values.data, values.offsets)
        return np.array(
            [xxh64(v) for v in values.to_list()], dtype=np.uint64
        )
    arr = np.ascontiguousarray(np.asarray(values))
    if arr.ndim == 2:  # FLBA rows
        width = arr.shape[1]
        if lib is not None and lib.has_xxh64:
            return lib.xxh64_fixed(arr, len(arr), width)
        return np.array([xxh64(r.tobytes()) for r in arr], dtype=np.uint64)
    width = _FIXED_WIDTH.get(ptype)
    if width is None or arr.itemsize != width:
        raise ValueError(f"bloom: unsupported type {ptype} for hashing")
    if ptype in (Type.FLOAT, Type.DOUBLE):
        # normalize -0.0 -> +0.0 (see plain_bytes_for_hash)
        arr = np.ascontiguousarray(arr + arr.dtype.type(0.0))
    if lib is not None and lib.has_xxh64:
        return lib.xxh64_fixed(arr, len(arr), width)
    raw = arr.tobytes()
    return np.array(
        [xxh64(raw[i * width : (i + 1) * width]) for i in range(len(arr))],
        dtype=np.uint64,
    )


class BloomFilter:
    """One column chunk's split-block bloom filter."""

    MIN_BYTES = 32
    MAX_BYTES = 128 << 20

    def __init__(self, blocks: np.ndarray):
        if blocks.dtype != np.uint32 or len(blocks) % 8:
            raise ValueError("bloom: bitset must be uint32 words in 8-word blocks")
        self.blocks = blocks

    @classmethod
    def sized_for(cls, ndv: int, fpp: float = 0.05) -> "BloomFilter":
        """Empty filter sized for `ndv` distinct values at false-positive
        rate `fpp` (parquet-mr's optimal-bits formula, bytes rounded up to a
        power of two within [32 B, 128 MB])."""
        ndv = max(int(ndv), 1)
        if not 0.0 < fpp < 1.0:
            raise ValueError("bloom: fpp must be in (0, 1)")
        bits = -8.0 * ndv / math.log(1.0 - fpp ** (1.0 / 8.0))
        # ceil to whole bytes BEFORE the power-of-two round-up: int() here
        # would undershoot the requested fpp whenever optimal bytes lands
        # just above a power of two
        nbytes = 1 << max(math.ceil(bits / 8.0) - 1, 0).bit_length()
        nbytes = min(max(nbytes, cls.MIN_BYTES), cls.MAX_BYTES)
        return cls(np.zeros(nbytes // 4, dtype=np.uint32))

    @property
    def num_bytes(self) -> int:
        return self.blocks.nbytes

    def insert_hashes(self, hashes: np.ndarray) -> None:
        from ..utils.native import get_native

        lib = get_native()
        if lib is not None and lib.has_xxh64:
            lib.bloom_insert(self.blocks, hashes)
            return
        nb = len(self.blocks) // 8
        for h in hashes.tolist():
            bi = ((h >> 32) * nb) >> 32
            x = np.uint64(h & 0xFFFFFFFF)
            bits = ((x * _SALT) & np.uint64(0xFFFFFFFF)) >> np.uint64(27)
            self.blocks[bi * 8 : bi * 8 + 8] |= (
                np.uint32(1) << bits.astype(np.uint32)
            )

    def might_contain_hash(self, h: int) -> bool:
        nb = len(self.blocks) // 8
        bi = ((h >> 32) * nb) >> 32
        x = np.uint64(h & 0xFFFFFFFF)
        bits = ((x * _SALT) & np.uint64(0xFFFFFFFF)) >> np.uint64(27)
        words = self.blocks[bi * 8 : bi * 8 + 8]
        return bool(
            np.all((words >> bits.astype(np.uint32)) & np.uint32(1))
        )

    def might_contain(self, ptype, value, unsigned: bool = False) -> bool:
        """False only when the value is PROVABLY absent; unsupported value
        forms answer True (no pruning)."""
        raw = plain_bytes_for_hash(ptype, value, unsigned)
        if raw is None:
            return True
        from ..utils.native import get_native

        lib = get_native()

        def _hash(b):
            return lib.xxh64(b) if lib is not None and lib.has_xxh64 else xxh64(b)

        if self.might_contain_hash(_hash(raw)):
            return True
        if ptype in (Type.FLOAT, Type.DOUBLE) and value == 0.0:
            # our writer normalizes -0.0 -> +0.0, but FOREIGN writers may
            # have inserted the raw -0.0 bit pattern; 0.0 == -0.0, so the
            # probe must admit either before claiming provable absence
            neg = struct.pack("<f" if ptype == Type.FLOAT else "<d", -0.0)
            return self.might_contain_hash(_hash(neg))
        return False

    # -- wire form -------------------------------------------------------------

    def header(self) -> BloomFilterHeader:
        return BloomFilterHeader(
            numBytes=self.num_bytes,
            algorithm=BloomFilterAlgorithm(BLOCK=SplitBlockAlgorithm()),
            hash=BloomFilterHash(XXHASH=XxHash()),
            compression=BloomFilterCompression(
                UNCOMPRESSED=BloomFilterUncompressed()
            ),
        )

    def to_bytes(self) -> bytes:
        return self.header().dumps() + self.blocks.tobytes()

    @classmethod
    def from_buffer(cls, buf) -> "BloomFilter":
        """Parse [BloomFilterHeader][bitset] as stored in the file."""
        from ..meta.thrift import CompactReader

        r = CompactReader(buf)
        header = BloomFilterHeader.read(r)
        n = header.numBytes or 0
        if n <= 0 or n % 32 or r.pos + n > len(buf):
            raise ValueError(f"bloom: bad bitset size {n}")
        if header.algorithm is not None and header.algorithm.BLOCK is None:
            raise ValueError("bloom: unsupported algorithm")
        if header.hash is not None and header.hash.XXHASH is None:
            raise ValueError("bloom: unsupported hash")
        if (
            header.compression is not None
            and header.compression.UNCOMPRESSED is None
        ):
            raise ValueError("bloom: unsupported compression")
        bits = np.frombuffer(buf, dtype=np.uint32, count=n // 4, offset=r.pos)
        return cls(bits.copy())

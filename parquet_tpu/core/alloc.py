"""Decoded-memory ceiling against adversarial files.

Analogue of the reference's allocTracker (reference: alloc.go:10-89,
WithMaximumMemorySize file_reader.go:144-149): advertised uncompressed sizes
are *checked* before decompression and *registered* after, raising a clean
error past the ceiling instead of OOMing on decompression bombs. Python's GC
replaces the reference's finalizer-based deregistration: a row group's budget
is released when the reader moves on (release()).
"""

from __future__ import annotations

__all__ = ["AllocTracker", "AllocError"]


class AllocError(MemoryError):
    pass


class AllocTracker:
    def __init__(self, max_bytes: int):
        if max_bytes <= 0:
            raise ValueError("alloc: ceiling must be positive")
        self.max_bytes = max_bytes
        self.used = 0

    def check(self, size: int) -> None:
        """Pre-check an advertised allocation (reference: alloc.go test())."""
        if size < 0:
            raise AllocError("alloc: negative advertised size")
        if self.used + size > self.max_bytes:
            raise AllocError(
                f"alloc: would exceed memory ceiling "
                f"({self.used} + {size} > {self.max_bytes})"
            )

    def register(self, size: int) -> None:
        self.check(size)
        self.used += size

    def release(self, size: int | None = None) -> None:
        if size is None:
            self.used = 0
        else:
            self.used = max(0, self.used - size)

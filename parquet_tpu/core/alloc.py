"""Decoded-memory ceiling against adversarial files.

Analogue of the reference's allocTracker (reference: alloc.go:10-89,
WithMaximumMemorySize file_reader.go:144-149): advertised uncompressed sizes
are *checked* before decompression, and the ACTUAL decoded buffers (value
arrays, levels, dictionaries — which a lying header cannot understate, e.g.
an RLE run expanding a few bytes into millions of values) are *registered*
as they materialize, raising a clean error past the ceiling instead of
OOMing. Python's GC replaces the reference's finalizer-based deregistration:
a row group's budget is released when the reader moves on (release()).

Thread-safe: chunk preparation runs on worker threads (core/reader.py).
"""

from __future__ import annotations

import threading

__all__ = ["AllocTracker", "AllocError", "decoded_nbytes"]


class AllocError(MemoryError):
    pass


class AllocTracker:
    def __init__(self, max_bytes: int):
        if max_bytes <= 0:
            raise ValueError("alloc: ceiling must be positive")
        self.max_bytes = max_bytes
        self.used = 0
        self._lock = threading.Lock()

    def check(self, size: int) -> None:
        """Pre-check an advertised allocation (reference: alloc.go test())."""
        if size < 0:
            raise AllocError("alloc: negative advertised size")
        if self.used + size > self.max_bytes:
            raise AllocError(
                f"alloc: would exceed memory ceiling "
                f"({self.used} + {size} > {self.max_bytes})"
            )

    def register(self, size: int) -> None:
        """Account an actual materialized buffer (reference: alloc.go
        register()); raises once the ceiling is crossed."""
        with self._lock:
            self.check(size)
            self.used += size

    def register_buffers(self, *buffers) -> None:
        """Register the actual byte sizes of decoded buffers (ndarrays,
        ByteArrayData, bytes-likes); None entries are skipped."""
        self.register(sum(decoded_nbytes(b) for b in buffers))

    def release(self, size: int | None = None) -> None:
        with self._lock:
            if size is None:
                self.used = 0
            else:
                self.used = max(0, self.used - size)


def decoded_nbytes(v) -> int:
    """Actual in-memory size of a decoded buffer, in bytes."""
    if v is None:
        return 0
    nbytes = getattr(v, "nbytes", None)  # ndarray / memoryview
    if nbytes is not None:
        return int(nbytes)
    offsets = getattr(v, "offsets", None)  # ByteArrayData
    if offsets is not None:
        return int(offsets.nbytes) + len(v.data)
    try:
        return len(v)  # bytes / bytearray
    except TypeError:
        return 0

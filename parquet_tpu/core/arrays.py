"""Columnar value containers.

The reference moves decoded values as `[]interface{}` — one heap-boxed value
per cell (reference: interfaces.go:29-52, SURVEY §7.1 'invert the execution
model'). Here every column is a typed array end-to-end:

  - numeric/boolean columns: NumPy arrays (bit-exact views of the wire bytes)
  - BYTE_ARRAY columns: Arrow-style (offsets, flat byte buffer) — no per-string
    materialization (SURVEY §7.3 hard-part #3)
  - INT96: (n, 12) uint8 rows (legacy Impala timestamps)
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["ByteArrayData", "byte_array_from_items"]

try:  # CPython extension (native/pyext.c); every caller degrades without it
    from .. import _native_ext as _ext
except ImportError:  # pragma: no cover
    _ext = None


@dataclass
class ByteArrayData:
    """Variable-length binary column: values[i] = data[offsets[i]:offsets[i+1]]."""

    offsets: np.ndarray  # int64, length n+1, offsets[0] == 0
    data: bytes

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, i: int) -> bytes:
        return self.data[self.offsets[i] : self.offsets[i + 1]]

    def to_list(self, cache: bool = False) -> list[bytes]:
        """Per-value bytes. The write path asks repeatedly on the same chunk
        (dictionary build, PLAIN encode, stats) and opts into memoization
        with cache=True — those callers share one list and must not mutate
        it (the writer wraps caller-owned arrays, so the cache never pins a
        user object). cache=False always builds a fresh list: read-path
        callers neither retain extra memory nor alias the shared one."""
        if cache:
            cached = getattr(self, "_list_cache", None)
            if cached is not None:
                return cached
        o = self.offsets.tolist()
        d = self.data
        out = [d[o[i] : o[i + 1]] for i in range(len(o) - 1)]
        if cache:
            self._list_cache = out
        return out

    @classmethod
    def from_list(cls, items) -> "ByteArrayData":
        lengths = np.fromiter((len(x) for x in items), dtype=np.int64, count=len(items))
        offsets = np.zeros(len(items) + 1, dtype=np.int64)
        np.cumsum(lengths, out=offsets[1:])
        return cls(offsets=offsets, data=b"".join(items))


    def take(self, indices: np.ndarray) -> "ByteArrayData":
        """Gather rows by index (dictionary expansion), fully vectorized.

        Builds one fancy-index over the source buffer: for output row k the
        source positions are starts[k] + [0, len_k); expressed as
        arange(total) - repeat(out_starts) + repeat(src_starts).
        """
        indices = np.asarray(indices, dtype=np.int64)
        if _ext is not None and len(indices):
            # one C pass, ONE uninitialized output allocation (offsets,
            # lengths, bounds checks and the gather all inside); ~2x the
            # ctypes route, which pays a memset + an extra result copy
            off_b, data = _ext.take_bytes(
                self.data,
                np.ascontiguousarray(self.offsets, dtype=np.int64),
                np.ascontiguousarray(indices),
            )
            return ByteArrayData(
                offsets=np.frombuffer(off_b, dtype=np.int64), data=data
            )
        if len(indices) and (
            int(indices.min()) < 0 or int(indices.max()) >= len(self)
        ):
            raise IndexError("byte-array take: index out of range")
        o = self.offsets
        lengths = (o[1:] - o[:-1])[indices]
        new_off = np.zeros(len(indices) + 1, dtype=np.int64)
        np.cumsum(lengths, out=new_off[1:])
        total = int(new_off[-1])
        if total == 0:
            return ByteArrayData(offsets=new_off, data=b"")
        from ..utils.native import get_native

        lib = get_native()
        if lib is not None and lib.has_bytearray_take:
            data = lib.bytearray_take(self.data, o, indices, new_off, total)
            return ByteArrayData(offsets=new_off, data=data)
        src = np.frombuffer(self.data, dtype=np.uint8)
        starts = o[:-1][indices]
        gather = (
            np.arange(total, dtype=np.int64)
            - np.repeat(new_off[:-1], lengths)
            + np.repeat(starts, lengths)
        )
        return ByteArrayData(offsets=new_off, data=src[gather].tobytes())

    def __eq__(self, other) -> bool:
        if not isinstance(other, ByteArrayData):
            return NotImplemented
        return (
            np.array_equal(self.offsets, other.offsets) and self.data == other.data
        )


def byte_array_from_items(items, to_bytes=None) -> ByteArrayData:
    """Sequence of str/bytes (or anything `to_bytes` can convert) -> column.

    The common all-str/bytes case runs as one C pass (native/_native_ext);
    exotic item types fall back to per-item conversion."""
    if _ext is not None:
        try:
            flat, lens_b = _ext.encode_items(items)
        except TypeError:
            pass
        else:
            lengths = np.frombuffer(lens_b, dtype="<i8")
            offsets = np.zeros(len(lengths) + 1, dtype=np.int64)
            np.cumsum(lengths, out=offsets[1:])
            return ByteArrayData(offsets=offsets, data=flat)
    if to_bytes is None:
        to_bytes = _default_to_bytes
    return ByteArrayData.from_list([to_bytes(x) for x in items])


def _default_to_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode("utf-8")
    if isinstance(v, (bytearray, memoryview)):
        return bytes(v)
    raise TypeError(f"cannot convert {type(v).__name__} to bytes")

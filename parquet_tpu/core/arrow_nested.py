"""General Dremel-levels -> Arrow assembly for to_arrow's nested shapes.

The flat and canonical-LIST fast paths live in reader.to_arrow; this module
covers everything else — structs, MAPs, multi-level lists, list-of-struct,
struct-of-list, legacy repeated groups/leaves — by converting the
offsets/validity intermediate the vectorized assembly engine builds
(core/assembly_vec.build_field_vec, mode="arrow") into pyarrow arrays: the
SAME level prefix scan feeds row assembly and to_arrow, handed off
zero-copy at the buffer level — offsets, null masks and dense value slices
are shared numpy/chunk buffers, never re-derived or touched row by row
(reference semantics: schema.go:216-312, floor/reader.go:302-409).

What stays here is the pyarrow-facing half: leaf array construction over
the dense value slice (buffer handoff for byte arrays, retyping to logical
Arrow types), list/map/struct array assembly from IR offsets and masks,
and the Arrow type derivation (nested_arrow_type) that the builder's
dispatch must match exactly.
"""

from __future__ import annotations

import numpy as np

from ..meta.file_meta import ParquetFileError
from ..meta.parquet_types import ConvertedType, FieldRepetitionType, Type

__all__ = ["build_top_field", "nested_arrow_type", "retype_leaf"]


def _is_list_annotated(node) -> bool:
    return (
        node.converted_type == ConvertedType.LIST
        and not node.is_leaf
        and len(node.children) == 1
        and node.children[0].repetition == FieldRepetitionType.REPEATED
        and not node.children[0].is_leaf
    )


def _is_map_annotated(node) -> bool:
    if node.converted_type not in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE):
        return False
    if node.is_leaf or len(node.children) != 1:
        return False
    kv = node.children[0]
    return (
        kv.repetition == FieldRepetitionType.REPEATED
        and not kv.is_leaf
        and len(kv.children) == 2
    )


def _leaf_storage_type(pa, leaf):
    """The Arrow type of the STORAGE array the builders produce (physical
    parquet layout, before logical-type conversion)."""
    if leaf.type == Type.BYTE_ARRAY:
        return pa.large_string() if leaf.is_string() else pa.large_binary()
    if leaf.type in (Type.FIXED_LEN_BYTE_ARRAY, Type.INT96):
        return pa.binary(12 if leaf.type == Type.INT96 else leaf.type_length)
    return {
        Type.INT32: pa.int32(),
        Type.INT64: pa.int64(),
        Type.FLOAT: pa.float32(),
        Type.DOUBLE: pa.float64(),
        Type.BOOLEAN: pa.bool_(),
    }[leaf.type]


def _logical_target(pa, leaf):
    """The FINAL Arrow type the leaf's logical/converted annotation maps to
    (pyarrow.parquet.read_table's convention), or None when the storage
    type IS the final type (strings, plain numerics, unannotated binary)."""
    t = leaf.type
    if t == Type.INT96:
        return pa.timestamp("ns")  # Impala/Hive timestamps; pyarrow: ns
    lt = leaf.logical_type
    ct = leaf.converted_type
    if lt is not None:
        if lt.TIMESTAMP is not None and t == Type.INT64:
            u = lt.TIMESTAMP.unit
            unit = (
                "ms" if u and u.MILLIS is not None
                else "ns" if u and u.NANOS is not None
                else "us"
            )
            tz = "UTC" if lt.TIMESTAMP.isAdjustedToUTC else None
            return pa.timestamp(unit, tz=tz)
        if lt.TIME is not None:
            # Spec-pinned unit/physical pairs only: MILLIS stores INT32,
            # MICROS/NANOS store INT64. Any other combination (a foreign
            # writer annotating TIME(MILLIS) on INT64, a missing unit) is
            # spec-invalid: keep raw storage rather than silently misreading
            # the values in a wrong unit.
            u = lt.TIME.unit
            if u is not None and u.MILLIS is not None:
                return pa.time32("ms") if t == Type.INT32 else None
            if u is not None and u.MICROS is not None:
                return pa.time64("us") if t == Type.INT64 else None
            if u is not None and u.NANOS is not None:
                return pa.time64("ns") if t == Type.INT64 else None
            return None
        if lt.DATE is not None and t == Type.INT32:
            return pa.date32()
        if lt.DECIMAL is not None:
            return _decimal_type(pa, leaf, lt.DECIMAL.precision, lt.DECIMAL.scale)
        if lt.INTEGER is not None:
            return _int_arrow_type(pa, lt.INTEGER.bitWidth, bool(lt.INTEGER.isSigned))
        if (
            lt.FLOAT16 is not None
            and t == Type.FIXED_LEN_BYTE_ARRAY
            and leaf.type_length == 2  # spec-invalid widths stay raw binary
        ):
            return pa.float16()
        # UUID/JSON extension types deliberately NOT mapped: pyarrow's
        # arrow.uuid/arrow.json extensions cannot ride every lane here
        # (zero-group empty arrays, nested structs, dictionary-preserved
        # columns all reject extension types), and JSON would force a
        # UTF-8-validating cast that crashes on foreign non-UTF-8 payloads
        # our raw-binary convention reads fine. write_column still accepts
        # extension ARRAYS (storage unwrap in column_store._from_arrow).
        return None
    if ct is None:
        return None
    if ct == ConvertedType.DATE and t == Type.INT32:
        return pa.date32()
    if ct == ConvertedType.TIME_MILLIS and t == Type.INT32:
        return pa.time32("ms")
    if ct == ConvertedType.TIME_MICROS and t == Type.INT64:
        return pa.time64("us")
    if ct == ConvertedType.TIMESTAMP_MILLIS and t == Type.INT64:
        return pa.timestamp("ms")
    if ct == ConvertedType.TIMESTAMP_MICROS and t == Type.INT64:
        return pa.timestamp("us")
    if ct == ConvertedType.DECIMAL:
        el = leaf.element
        return _decimal_type(pa, leaf, el.precision, el.scale)
    ints = {
        # INT_32/INT_64 omitted: identity with the storage type
        ConvertedType.UINT_8: (8, False), ConvertedType.UINT_16: (16, False),
        ConvertedType.UINT_32: (32, False), ConvertedType.UINT_64: (64, False),
        ConvertedType.INT_8: (8, True), ConvertedType.INT_16: (16, True),
    }
    if ct in ints:
        return _int_arrow_type(pa, *ints[ct])
    return None


def _int_arrow_type(pa, bit_width, signed: bool):
    m = {
        (8, True): pa.int8, (16, True): pa.int16,
        (32, True): pa.int32, (64, True): pa.int64,
        (8, False): pa.uint8, (16, False): pa.uint16,
        (32, False): pa.uint32, (64, False): pa.uint64,
    }
    f = m.get((bit_width, signed))
    return f() if f is not None else None


def _decimal_type(pa, leaf, precision, scale):
    if precision is None or not 1 <= precision <= 38:
        return None  # >38 needs decimal256; malformed: keep storage
    if leaf.type in (Type.INT32, Type.INT64):
        return pa.decimal128(precision, scale or 0)
    if leaf.type == Type.FIXED_LEN_BYTE_ARRAY and 1 <= (leaf.type_length or 0) <= 16:
        # pyarrow's own bound: FromBigEndian accepts 1..16 bytes; wider
        # FLBA decimals error in pyarrow, so they stay raw binary here
        return pa.decimal128(precision, scale or 0)
    return None  # BYTE_ARRAY-backed decimals: keep raw bytes


def _leaf_arrow_type(pa, leaf):
    """The FINAL Arrow type for a leaf (logical conversion applied)."""
    return _logical_target(pa, leaf) or _leaf_storage_type(pa, leaf)


def retype_leaf(pa, leaf, arr):
    """Convert a STORAGE array to the leaf's final Arrow type: zero-copy
    view() where widths agree (timestamps, date32, time, uint32/64,
    float16), compute cast for narrowing ints, and buffer rebuilds for
    decimal128 and INT96->timestamp[ns]. Mirrors pyarrow.read_table's
    logical-type handling so a pyarrow user sees the same schema."""
    ft = _logical_target(pa, leaf)
    if ft is None or arr.type == ft:
        return arr
    if arr.offset != 0:  # rebase so raw-buffer math below is position 0
        arr = pa.concat_arrays([arr])
    if pa.types.is_decimal(ft):
        return _to_decimal128(pa, leaf, arr, ft)
    if leaf.type == Type.INT96:
        return _int96_to_timestamp(pa, arr, ft)
    bw = {pa.int8(): 8, pa.int16(): 16, pa.uint8(): 8, pa.uint16(): 16}
    if ft in bw:
        try:
            # narrowing: our own writer's values fit by construction, but a
            # malformed FOREIGN file can annotate INT_8/UINT_16/... on stored
            # values outside the annotated range — fail through the
            # documented error surface, not a raw pyarrow exception
            return arr.cast(ft)
        except pa.lib.ArrowInvalid as e:
            raise ParquetFileError(
                f"parquet: stored values overflow annotated type {ft}: {e}"
            ) from e
    return arr.view(ft)  # same-width reinterpretation, zero-copy


def _validity(arr):
    bufs = arr.buffers()
    return bufs[0] if bufs else None


def _to_decimal128(pa, leaf, arr, ft):
    n = len(arr)
    out = np.zeros((n, 16), dtype=np.uint8)
    if leaf.type in (Type.INT32, Type.INT64):
        npdt = np.int32 if leaf.type == Type.INT32 else np.int64
        v = np.frombuffer(arr.buffers()[1], dtype=npdt, count=n).astype(np.int64)
        lohi = out.view(np.int64).reshape(n, 2)
        lohi[:, 0] = v
        lohi[:, 1] = v >> 63  # sign extension
    else:  # FLBA big-endian two's complement, width 1..16 (_decimal_type)
        w = leaf.type_length or 0
        m = np.frombuffer(arr.buffers()[1], dtype=np.uint8, count=n * w).reshape(n, w)
        out[:, :w] = m[:, ::-1]  # BE -> LE
        out[m[:, 0] >= 0x80, w:] = 0xFF
    return pa.Array.from_buffers(
        ft, n, [_validity(arr), pa.py_buffer(out)], null_count=arr.null_count
    )


def _int96_to_timestamp(pa, arr, ft):
    n = len(arr)
    m = np.frombuffer(arr.buffers()[1], dtype=np.uint8, count=n * 12).reshape(n, 12)
    nanos = np.ascontiguousarray(m[:, :8]).view("<u8").reshape(n)
    days = np.ascontiguousarray(m[:, 8:12]).view("<u4").reshape(n)
    ns = (days.astype(np.int64) - 2440588) * 86_400_000_000_000 + nanos.astype(
        np.int64
    )
    return pa.Array.from_buffers(
        ft, n, [_validity(arr), pa.py_buffer(ns)], null_count=arr.null_count
    )


def nested_arrow_type(pa, node, selected=None):
    """The Arrow type this builder produces for a schema node.

    ``selected`` (a set of leaf paths, or None for all) prunes struct
    members whose leaves are projected out — mirroring _build_struct's
    data-side skip, so a projected read and its zero-row schema agree."""
    if node.is_leaf:
        base = _leaf_arrow_type(pa, node)
        if node.repetition == FieldRepetitionType.REPEATED:
            return pa.large_list(base)  # legacy bare repeated primitive
        return base
    if _is_map_annotated(node):
        kv = node.children[0]
        if not all(_selects(selected, c) for c in kv.children):
            # key or value projected out: no Arrow MAP without both —
            # degrade to the underlying list-of-struct shape (pruned)
            return pa.large_list(_struct_type(pa, kv, selected))
        return pa.map_(
            nested_arrow_type(pa, kv.children[0], selected),
            nested_arrow_type(pa, kv.children[1], selected),
        )
    if _is_list_annotated(node):
        rep = node.children[0]
        if len(rep.children) == 1:
            elem = rep.children[0]
            return pa.large_list(nested_arrow_type(pa, elem, selected))
        # canonical list whose repeated group holds several fields:
        # list of structs
        return pa.large_list(_struct_type(pa, rep, selected))
    if node.repetition == FieldRepetitionType.REPEATED:
        # legacy repeated group: list of structs, elements non-null
        return pa.large_list(_struct_type(pa, node, selected))
    return _struct_type(pa, node, selected)


def _selects(selected, node) -> bool:
    if selected is None:
        return True
    k = len(node.path)
    return any(p[:k] == node.path for p in selected)


def _struct_type(pa, node, selected=None):
    return pa.struct(
        [
            pa.field(
                c.name,
                nested_arrow_type(pa, c, selected),
                nullable=c.repetition != FieldRepetitionType.REQUIRED,
            )
            for c in node.children
            if _selects(selected, c)
        ]
    )


def build_top_field(pa, schema, top_name: str, chunks: dict) -> "pa.Array":
    """Assemble one top-level field (all its leaf chunks from one row group)
    into a pyarrow Array of length = the group's row count, by converting
    the assembly engine's offsets/validity IR."""
    from .assembly_vec import VecStructureError, build_field_vec

    sub = {p: cd for p, cd in chunks.items() if p[0] == top_name}
    if not sub:
        raise ParquetFileError(f"parquet: no leaf chunks for field {top_name}")
    try:
        vec, _n = build_field_vec(schema, top_name, sub, mode="arrow")
    except VecStructureError as e:
        raise ParquetFileError(f"parquet: {e}") from e
    return _field_from_vec(pa, vec)


def _field_from_vec(pa, vec):
    """IR node -> pyarrow array. Offsets/null-mask ndarrays and dense leaf
    buffers pass through without per-row work."""
    from .assembly_vec import LeafVec, ListVec

    if isinstance(vec, LeafVec):
        return _leaf_array(pa, vec)

    if isinstance(vec, ListVec):
        valid = None if vec.null_mask is None else vec.null_mask == 0
        if vec.kind == "map":
            # arrow mode guarantees both kv children selected here
            keys = _field_from_vec(pa, vec.child.children[0])
            items = _field_from_vec(pa, vec.child.children[1])
            off32 = vec.offsets.astype(np.int32)
            if valid is not None:
                # a null offset at i marks map i null; the final offset (the
                # appended False) must stay valid
                moff = pa.array(
                    off32,
                    mask=np.append(vec.null_mask.astype(bool), False),
                    type=pa.int32(),
                )
                return pa.MapArray.from_arrays(moff, keys, items)
            return pa.MapArray.from_arrays(
                pa.array(off32, type=pa.int32()), keys, items
            )
        values = _field_from_vec(pa, vec.child)
        return _list_with_validity(pa, vec.offsets, values, valid)

    # StructVec
    children = []
    fields = []
    for name, child_vec in zip(vec.names, vec.children):
        arr = _field_from_vec(pa, child_vec)
        children.append(arr)
        fields.append(
            pa.field(
                name,
                arr.type,
                nullable=child_vec.node.repetition != FieldRepetitionType.REQUIRED,
            )
        )
    mask = None
    if vec.null_mask is not None:
        mask = pa.array(vec.null_mask.astype(bool))
    return pa.StructArray.from_arrays(children, fields=fields, mask=mask)


def _list_with_validity(pa, offsets, values, valid):
    if valid is not None and not valid.all():
        # a null offset at i marks list i null; the final offset (the
        # appended False) must stay valid
        return pa.LargeListArray.from_arrays(
            pa.array(
                offsets.astype(np.int64),
                mask=np.append(~valid, False),
                type=pa.int64(),
            ),
            values,
        )
    return pa.LargeListArray.from_arrays(offsets, values)


def _leaf_array(pa, vec):
    """Build a LeafVec's Arrow array (one entry per slot). The dense values
    of the selected entries are one contiguous slice of the chunk: a
    value-bearing entry (def == max_def) can never be dropped by a list
    filter above it."""
    from .arrays import ByteArrayData

    leaf = vec.node
    values = vec.chunk.values
    n_slots = vec.n
    nv = vec.nv
    k0 = vec.k0
    valid = vec.valid  # bool[n_slots] | None (None = every slot present)
    mask = None if valid is None else ~valid

    if isinstance(values, ByteArrayData):
        atype = pa.large_string() if leaf.is_string() else pa.large_binary()
        all_offsets = np.asarray(values.offsets, dtype=np.int64)
        dense_off = all_offsets[k0 : k0 + nv + 1]
        if mask is None:
            out_off = np.zeros(n_slots + 1, dtype=np.int64)
            if nv:
                np.cumsum(np.diff(dense_off), out=out_off[1:])
        else:
            lens = np.zeros(n_slots, dtype=np.int64)
            if nv:
                lens[valid] = np.diff(dense_off)
            out_off = np.zeros(n_slots + 1, dtype=np.int64)
            np.cumsum(lens, out=out_off[1:])
        data = values.data[
            int(dense_off[0]) if nv else 0 : int(dense_off[-1]) if nv else 0
        ]
        bufs = [
            None
            if mask is None
            else pa.py_buffer(np.packbits(valid, bitorder="little").tobytes()),
            pa.py_buffer(out_off),
            pa.py_buffer(data),
        ]
        return pa.Array.from_buffers(
            atype, n_slots, bufs, null_count=int(mask.sum()) if mask is not None else 0
        )  # byte-array leaves have no logical retype (BYTE_ARRAY decimals stay raw)

    np_vals = np.asarray(values)
    if np_vals.ndim == 2:  # FLBA / INT96 byte rows
        atype = pa.binary(np_vals.shape[1])
        dense = np_vals[k0 : k0 + nv]
        if mask is None:
            flat = np.ascontiguousarray(dense).reshape(-1)
            built = pa.Array.from_buffers(atype, n_slots, [None, pa.py_buffer(flat)])
        else:
            it = iter(dense)
            rows = [bytes(next(it)) if ok else None for ok in valid]
            built = pa.array(rows, atype)
        return retype_leaf(pa, leaf, built)

    dense = np_vals[k0 : k0 + nv]
    if mask is None:
        return retype_leaf(pa, leaf, pa.array(dense))
    out = np.zeros(n_slots, dtype=np_vals.dtype)
    out[valid] = dense
    return retype_leaf(pa, leaf, pa.array(out, mask=mask))

"""General Dremel-levels -> Arrow assembly for to_arrow's nested shapes.

The flat and canonical-LIST fast paths live in reader.to_arrow; this module
covers everything else — structs, MAPs, multi-level lists, list-of-struct,
struct-of-list, legacy repeated groups/leaves — by walking the schema tree
once and deriving each node's Arrow layout (offsets, validity) from the
repetition/definition level arrays with vectorized numpy, never touching
values row by row (reference semantics: schema.go:216-312,
floor/reader.go:302-409; the row-path analogue here is core/assembly.py).

Per-leaf stream state during the recursion:
  sel      int64[k]  indices into the leaf's full level arrays that belong to
                     the current node's element stream (always ascending)
  slot_of  int64[k]  which slot of the current node each entry belongs to
                     (non-decreasing; every slot has >= 1 entry until a list
                     node with zero elements drops its placeholder)

Two invariants make the leaf step cheap:
  * a value-bearing entry (def == leaf.max_def) survives every list filter
    above it, so the selected values are one CONTIGUOUS dense slice;
  * every slot at struct granularity keeps exactly one entry per leaf, so
    struct validity reads one level per slot.
"""

from __future__ import annotations

import numpy as np

from ..meta.file_meta import ParquetFileError
from ..meta.parquet_types import ConvertedType, FieldRepetitionType, Type

__all__ = ["build_top_field", "nested_arrow_type", "retype_leaf"]


class _LeafState:
    __slots__ = ("leaf", "chunk", "rl", "dl", "present", "nvals_before")

    def __init__(self, leaf, chunk):
        self.leaf = leaf
        self.chunk = chunk
        n = chunk.num_values
        self.rl = (
            np.asarray(chunk.rep_levels, dtype=np.int64)
            if chunk.rep_levels is not None
            else np.zeros(n, dtype=np.int64)
        )
        self.dl = (
            np.asarray(chunk.def_levels, dtype=np.int64)
            if chunk.def_levels is not None
            else np.full(n, leaf.max_def, dtype=np.int64)
        )
        # number of value-bearing entries before each position (for locating
        # the dense slice start of any selection)
        self.present = self.dl == leaf.max_def
        self.nvals_before = np.concatenate(
            [[0], np.cumsum(self.present[:-1])]
        ) if n else np.zeros(0, dtype=np.int64)


def _is_list_annotated(node) -> bool:
    return (
        node.converted_type == ConvertedType.LIST
        and not node.is_leaf
        and len(node.children) == 1
        and node.children[0].repetition == FieldRepetitionType.REPEATED
        and not node.children[0].is_leaf
    )


def _is_map_annotated(node) -> bool:
    if node.converted_type not in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE):
        return False
    if node.is_leaf or len(node.children) != 1:
        return False
    kv = node.children[0]
    return (
        kv.repetition == FieldRepetitionType.REPEATED
        and not kv.is_leaf
        and len(kv.children) == 2
    )


def _leaf_storage_type(pa, leaf):
    """The Arrow type of the STORAGE array the builders produce (physical
    parquet layout, before logical-type conversion)."""
    if leaf.type == Type.BYTE_ARRAY:
        return pa.large_string() if leaf.is_string() else pa.large_binary()
    if leaf.type in (Type.FIXED_LEN_BYTE_ARRAY, Type.INT96):
        return pa.binary(12 if leaf.type == Type.INT96 else leaf.type_length)
    return {
        Type.INT32: pa.int32(),
        Type.INT64: pa.int64(),
        Type.FLOAT: pa.float32(),
        Type.DOUBLE: pa.float64(),
        Type.BOOLEAN: pa.bool_(),
    }[leaf.type]


def _logical_target(pa, leaf):
    """The FINAL Arrow type the leaf's logical/converted annotation maps to
    (pyarrow.parquet.read_table's convention), or None when the storage
    type IS the final type (strings, plain numerics, unannotated binary)."""
    t = leaf.type
    if t == Type.INT96:
        return pa.timestamp("ns")  # Impala/Hive timestamps; pyarrow: ns
    lt = leaf.logical_type
    ct = leaf.converted_type
    if lt is not None:
        if lt.TIMESTAMP is not None and t == Type.INT64:
            u = lt.TIMESTAMP.unit
            unit = (
                "ms" if u and u.MILLIS is not None
                else "ns" if u and u.NANOS is not None
                else "us"
            )
            tz = "UTC" if lt.TIMESTAMP.isAdjustedToUTC else None
            return pa.timestamp(unit, tz=tz)
        if lt.TIME is not None:
            # Spec-pinned unit/physical pairs only: MILLIS stores INT32,
            # MICROS/NANOS store INT64. Any other combination (a foreign
            # writer annotating TIME(MILLIS) on INT64, a missing unit) is
            # spec-invalid: keep raw storage rather than silently misreading
            # the values in a wrong unit.
            u = lt.TIME.unit
            if u is not None and u.MILLIS is not None:
                return pa.time32("ms") if t == Type.INT32 else None
            if u is not None and u.MICROS is not None:
                return pa.time64("us") if t == Type.INT64 else None
            if u is not None and u.NANOS is not None:
                return pa.time64("ns") if t == Type.INT64 else None
            return None
        if lt.DATE is not None and t == Type.INT32:
            return pa.date32()
        if lt.DECIMAL is not None:
            return _decimal_type(pa, leaf, lt.DECIMAL.precision, lt.DECIMAL.scale)
        if lt.INTEGER is not None:
            return _int_arrow_type(pa, lt.INTEGER.bitWidth, bool(lt.INTEGER.isSigned))
        if (
            lt.FLOAT16 is not None
            and t == Type.FIXED_LEN_BYTE_ARRAY
            and leaf.type_length == 2  # spec-invalid widths stay raw binary
        ):
            return pa.float16()
        # UUID/JSON extension types deliberately NOT mapped: pyarrow's
        # arrow.uuid/arrow.json extensions cannot ride every lane here
        # (zero-group empty arrays, nested structs, dictionary-preserved
        # columns all reject extension types), and JSON would force a
        # UTF-8-validating cast that crashes on foreign non-UTF-8 payloads
        # our raw-binary convention reads fine. write_column still accepts
        # extension ARRAYS (storage unwrap in column_store._from_arrow).
        return None
    if ct is None:
        return None
    if ct == ConvertedType.DATE and t == Type.INT32:
        return pa.date32()
    if ct == ConvertedType.TIME_MILLIS and t == Type.INT32:
        return pa.time32("ms")
    if ct == ConvertedType.TIME_MICROS and t == Type.INT64:
        return pa.time64("us")
    if ct == ConvertedType.TIMESTAMP_MILLIS and t == Type.INT64:
        return pa.timestamp("ms")
    if ct == ConvertedType.TIMESTAMP_MICROS and t == Type.INT64:
        return pa.timestamp("us")
    if ct == ConvertedType.DECIMAL:
        el = leaf.element
        return _decimal_type(pa, leaf, el.precision, el.scale)
    ints = {
        # INT_32/INT_64 omitted: identity with the storage type
        ConvertedType.UINT_8: (8, False), ConvertedType.UINT_16: (16, False),
        ConvertedType.UINT_32: (32, False), ConvertedType.UINT_64: (64, False),
        ConvertedType.INT_8: (8, True), ConvertedType.INT_16: (16, True),
    }
    if ct in ints:
        return _int_arrow_type(pa, *ints[ct])
    return None


def _int_arrow_type(pa, bit_width, signed: bool):
    m = {
        (8, True): pa.int8, (16, True): pa.int16,
        (32, True): pa.int32, (64, True): pa.int64,
        (8, False): pa.uint8, (16, False): pa.uint16,
        (32, False): pa.uint32, (64, False): pa.uint64,
    }
    f = m.get((bit_width, signed))
    return f() if f is not None else None


def _decimal_type(pa, leaf, precision, scale):
    if precision is None or not 1 <= precision <= 38:
        return None  # >38 needs decimal256; malformed: keep storage
    if leaf.type in (Type.INT32, Type.INT64):
        return pa.decimal128(precision, scale or 0)
    if leaf.type == Type.FIXED_LEN_BYTE_ARRAY and 1 <= (leaf.type_length or 0) <= 16:
        # pyarrow's own bound: FromBigEndian accepts 1..16 bytes; wider
        # FLBA decimals error in pyarrow, so they stay raw binary here
        return pa.decimal128(precision, scale or 0)
    return None  # BYTE_ARRAY-backed decimals: keep raw bytes


def _leaf_arrow_type(pa, leaf):
    """The FINAL Arrow type for a leaf (logical conversion applied)."""
    return _logical_target(pa, leaf) or _leaf_storage_type(pa, leaf)


def retype_leaf(pa, leaf, arr):
    """Convert a STORAGE array to the leaf's final Arrow type: zero-copy
    view() where widths agree (timestamps, date32, time, uint32/64,
    float16), compute cast for narrowing ints, and buffer rebuilds for
    decimal128 and INT96->timestamp[ns]. Mirrors pyarrow.read_table's
    logical-type handling so a pyarrow user sees the same schema."""
    ft = _logical_target(pa, leaf)
    if ft is None or arr.type == ft:
        return arr
    if arr.offset != 0:  # rebase so raw-buffer math below is position 0
        arr = pa.concat_arrays([arr])
    if pa.types.is_decimal(ft):
        return _to_decimal128(pa, leaf, arr, ft)
    if leaf.type == Type.INT96:
        return _int96_to_timestamp(pa, arr, ft)
    bw = {pa.int8(): 8, pa.int16(): 16, pa.uint8(): 8, pa.uint16(): 16}
    if ft in bw:
        try:
            # narrowing: our own writer's values fit by construction, but a
            # malformed FOREIGN file can annotate INT_8/UINT_16/... on stored
            # values outside the annotated range — fail through the
            # documented error surface, not a raw pyarrow exception
            return arr.cast(ft)
        except pa.lib.ArrowInvalid as e:
            raise ParquetFileError(
                f"parquet: stored values overflow annotated type {ft}: {e}"
            ) from e
    return arr.view(ft)  # same-width reinterpretation, zero-copy


def _validity(arr):
    bufs = arr.buffers()
    return bufs[0] if bufs else None


def _to_decimal128(pa, leaf, arr, ft):
    n = len(arr)
    out = np.zeros((n, 16), dtype=np.uint8)
    if leaf.type in (Type.INT32, Type.INT64):
        npdt = np.int32 if leaf.type == Type.INT32 else np.int64
        v = np.frombuffer(arr.buffers()[1], dtype=npdt, count=n).astype(np.int64)
        lohi = out.view(np.int64).reshape(n, 2)
        lohi[:, 0] = v
        lohi[:, 1] = v >> 63  # sign extension
    else:  # FLBA big-endian two's complement, width 1..16 (_decimal_type)
        w = leaf.type_length or 0
        m = np.frombuffer(arr.buffers()[1], dtype=np.uint8, count=n * w).reshape(n, w)
        out[:, :w] = m[:, ::-1]  # BE -> LE
        out[m[:, 0] >= 0x80, w:] = 0xFF
    return pa.Array.from_buffers(
        ft, n, [_validity(arr), pa.py_buffer(out)], null_count=arr.null_count
    )


def _int96_to_timestamp(pa, arr, ft):
    n = len(arr)
    m = np.frombuffer(arr.buffers()[1], dtype=np.uint8, count=n * 12).reshape(n, 12)
    nanos = np.ascontiguousarray(m[:, :8]).view("<u8").reshape(n)
    days = np.ascontiguousarray(m[:, 8:12]).view("<u4").reshape(n)
    ns = (days.astype(np.int64) - 2440588) * 86_400_000_000_000 + nanos.astype(
        np.int64
    )
    return pa.Array.from_buffers(
        ft, n, [_validity(arr), pa.py_buffer(ns)], null_count=arr.null_count
    )


def nested_arrow_type(pa, node, selected=None):
    """The Arrow type this builder produces for a schema node.

    ``selected`` (a set of leaf paths, or None for all) prunes struct
    members whose leaves are projected out — mirroring _build_struct's
    data-side skip, so a projected read and its zero-row schema agree."""
    if node.is_leaf:
        base = _leaf_arrow_type(pa, node)
        if node.repetition == FieldRepetitionType.REPEATED:
            return pa.large_list(base)  # legacy bare repeated primitive
        return base
    if _is_map_annotated(node):
        kv = node.children[0]
        if not all(_selects(selected, c) for c in kv.children):
            # key or value projected out: no Arrow MAP without both —
            # degrade to the underlying list-of-struct shape (pruned)
            return pa.large_list(_struct_type(pa, kv, selected))
        return pa.map_(
            nested_arrow_type(pa, kv.children[0], selected),
            nested_arrow_type(pa, kv.children[1], selected),
        )
    if _is_list_annotated(node):
        rep = node.children[0]
        if len(rep.children) == 1:
            elem = rep.children[0]
            return pa.large_list(nested_arrow_type(pa, elem, selected))
        # canonical list whose repeated group holds several fields:
        # list of structs
        return pa.large_list(_struct_type(pa, rep, selected))
    if node.repetition == FieldRepetitionType.REPEATED:
        # legacy repeated group: list of structs, elements non-null
        return pa.large_list(_struct_type(pa, node, selected))
    return _struct_type(pa, node, selected)


def _selects(selected, node) -> bool:
    if selected is None:
        return True
    k = len(node.path)
    return any(p[:k] == node.path for p in selected)


def _struct_type(pa, node, selected=None):
    return pa.struct(
        [
            pa.field(
                c.name,
                nested_arrow_type(pa, c, selected),
                nullable=c.repetition != FieldRepetitionType.REQUIRED,
            )
            for c in node.children
            if _selects(selected, c)
        ]
    )


def build_top_field(pa, schema, top_name: str, chunks: dict) -> "pa.Array":
    """Assemble one top-level field (all its leaf chunks from one row group)
    into a pyarrow Array of length = the group's row count."""
    top = schema.column((top_name,))
    leaves = {
        path: _LeafState(schema.column(path), cd)
        for path, cd in chunks.items()
        if path[0] == top_name
    }
    if not leaves:
        raise ParquetFileError(f"parquet: no leaf chunks for field {top_name}")
    # root slots = records: an entry starts a record iff rep level == 0
    state = {}
    n_slots = None
    for path, ls in leaves.items():
        starts = ls.rl == 0
        slot_of = np.cumsum(starts) - 1
        sel = np.arange(len(ls.rl), dtype=np.int64)
        state[path] = (sel, slot_of)
        count = int(starts.sum())
        if n_slots is None:
            n_slots = count
        elif n_slots != count:
            raise ParquetFileError(
                f"parquet: leaves of {top_name} disagree on row count "
                f"({n_slots} vs {count})"
            )
    return _build(pa, top, leaves, state, n_slots, parent_def=0)


def _first_entry_levels(leaves, state):
    """def level at each slot's first entry (shared above any descendant
    leaf, so any leaf serves)."""
    path = next(iter(state))
    sel, slot_of = state[path]
    ls = leaves[path]
    n_slots = int(slot_of[-1]) + 1 if len(slot_of) else 0
    firsts = np.searchsorted(slot_of, np.arange(n_slots), side="left")
    return ls.dl[sel[firsts]]


def _build(pa, node, leaves, state, n_slots, parent_def):
    if node.is_leaf:
        if node.repetition == FieldRepetitionType.REPEATED:
            # legacy bare repeated primitive: a one-level list of non-null
            # elements, no outer validity (repeated fields cannot be null)
            offsets, elem_state, n_elems = _list_expand(
                node, leaves, state, n_slots
            )
            values = _leaf_array(pa, node, leaves, elem_state, n_elems)
            return pa.LargeListArray.from_arrays(offsets, values)
        return _leaf_array(pa, node, leaves, state, n_slots)

    if _is_map_annotated(node):
        kv = node.children[0]
        valid = None
        if node.repetition == FieldRepetitionType.OPTIONAL:
            valid = _first_entry_levels(leaves, state) >= node.max_def
        offsets, elem_state, n_elems = _list_expand(kv, leaves, state, n_slots)
        have = [
            c
            for c in kv.children
            if any(p[: len(c.path)] == c.path for p in elem_state)
        ]
        if len(have) < 2:
            # key or value projected out: no Arrow MAP without both —
            # assemble the underlying list-of-struct over what's selected
            values = _build_struct(
                pa, kv, leaves, elem_state, n_elems, kv.max_def, force_valid=True
            )
            return _list_with_validity(pa, offsets, values, valid)
        key_node, val_node = kv.children
        keys = _build_child(pa, key_node, leaves, elem_state, n_elems, kv.max_def)
        items = _build_child(pa, val_node, leaves, elem_state, n_elems, kv.max_def)
        off32 = offsets.astype(np.int32)
        if valid is not None and not valid.all():
            # a null offset at i marks map i null; the final offset (the
            # appended False) must stay valid
            moff = pa.array(
                off32, mask=np.append(~valid, False), type=pa.int32()
            )
            return pa.MapArray.from_arrays(moff, keys, items)
        return pa.MapArray.from_arrays(pa.array(off32, type=pa.int32()), keys, items)

    if _is_list_annotated(node):
        rep = node.children[0]
        valid = None
        if node.repetition == FieldRepetitionType.OPTIONAL:
            valid = _first_entry_levels(leaves, state) >= node.max_def
        offsets, elem_state, n_elems = _list_expand(rep, leaves, state, n_slots)
        if len(rep.children) == 1:
            elem = rep.children[0]
            values = _build_child(pa, elem, leaves, elem_state, n_elems, rep.max_def)
        else:
            values = _build_struct(
                pa, rep, leaves, elem_state, n_elems, rep.max_def, force_valid=True
            )
        return _list_with_validity(pa, offsets, values, valid)

    if node.repetition == FieldRepetitionType.REPEATED:
        # legacy repeated group: list of non-null structs
        offsets, elem_state, n_elems = _list_expand(node, leaves, state, n_slots)
        values = _build_struct(
            pa, node, leaves, elem_state, n_elems, node.max_def, force_valid=True
        )
        return pa.LargeListArray.from_arrays(offsets, values)

    return _build_struct(pa, node, leaves, state, n_slots, parent_def)


def _build_child(pa, child, leaves, state, n_slots, parent_def):
    sub = {p: st for p, st in state.items() if p[: len(child.path)] == child.path}
    sub_leaves = {p: leaves[p] for p in sub}
    return _build(pa, child, sub_leaves, sub, n_slots, parent_def)


def _build_struct(pa, node, leaves, state, n_slots, parent_def, force_valid=False):
    valid = None
    if node.repetition == FieldRepetitionType.OPTIONAL and not force_valid:
        valid = _first_entry_levels(leaves, state) >= node.max_def
    children = []
    fields = []
    for c in node.children:
        sub = {p: st for p, st in state.items() if p[: len(c.path)] == c.path}
        if not sub:
            continue  # projected out
        sub_leaves = {p: leaves[p] for p in sub}
        children.append(_build(pa, c, sub_leaves, sub, n_slots, node.max_def))
        fields.append(
            pa.field(
                c.name,
                children[-1].type,
                nullable=c.repetition != FieldRepetitionType.REQUIRED,
            )
        )
    mask = None
    if valid is not None and not valid.all():
        mask = pa.array(~valid)
    return pa.StructArray.from_arrays(children, fields=fields, mask=mask)


def _list_expand(rep_node, leaves, state, n_slots):
    """Expand the current slots through one repeated node: returns
    (int64 offsets [n_slots+1], per-leaf element stream state, n_elements).

    An entry starts an element of this list iff its rep level <= the node's
    rep depth; the element exists iff its def level >= the node's def
    threshold (below that the entry is the placeholder of an empty or null
    or ancestor-null list and is dropped from the child stream)."""
    q = rep_node.max_rep
    d_r = rep_node.max_def
    offsets = None
    elem_state = {}
    n_elems = None
    for path, (sel, slot_of) in state.items():
        ls = leaves[path]
        rl = ls.rl[sel]
        dl = ls.dl[sel]
        is_start = (rl <= q - 1) | (rl == q)  # rl <= q
        exists = dl >= d_r
        elem_start = is_start & exists
        lengths = np.bincount(slot_of[elem_start], minlength=n_slots)
        offs = np.zeros(n_slots + 1, dtype=np.int64)
        np.cumsum(lengths, out=offs[1:])
        if offsets is None:
            offsets = offs
            n_elems = int(offs[-1])
        elif not np.array_equal(offsets, offs):
            raise ParquetFileError(
                f"parquet: leaves under {rep_node.path_str} disagree on "
                "list structure"
            )
        keep = exists
        new_sel = sel[keep]
        new_slot = np.cumsum(elem_start)[keep] - 1
        elem_state[path] = (new_sel, new_slot.astype(np.int64))
    return offsets, elem_state, n_elems


def _list_with_validity(pa, offsets, values, valid):
    if valid is not None and not valid.all():
        # a null offset at i marks list i null; the final offset (the
        # appended False) must stay valid
        return pa.LargeListArray.from_arrays(
            pa.array(
                offsets.astype(np.int64),
                mask=np.append(~valid, False),
                type=pa.int64(),
            ),
            values,
        )
    return pa.LargeListArray.from_arrays(offsets, values)


def _leaf_array(pa, leaf, leaves, state, n_slots):
    """Build the leaf's Arrow array over the current slots (one entry per
    slot). The dense values of the selected entries are one contiguous
    slice: a value-bearing entry (def == max_def) can never be dropped by a
    list filter above it."""
    from .arrays import ByteArrayData

    ls = leaves[leaf.path]
    sel, slot_of = state[leaf.path]
    if len(sel) != n_slots:
        raise ParquetFileError(
            f"parquet: leaf {leaf.path_str} stream does not align with its "
            f"slots ({len(sel)} entries for {n_slots} slots)"
        )
    valid = ls.present[sel]
    nv = int(valid.sum())
    k0 = int(ls.nvals_before[sel[0]]) if len(sel) and nv else 0
    values = ls.chunk.values
    mask = None if bool(valid.all()) else ~valid

    if isinstance(values, ByteArrayData):
        atype = pa.large_string() if leaf.is_string() else pa.large_binary()
        all_offsets = np.asarray(values.offsets, dtype=np.int64)
        dense_off = all_offsets[k0 : k0 + nv + 1]
        lens = np.zeros(n_slots, dtype=np.int64)
        if nv:
            lens[valid] = np.diff(dense_off)
        out_off = np.zeros(n_slots + 1, dtype=np.int64)
        np.cumsum(lens, out=out_off[1:])
        data = values.data[
            int(dense_off[0]) if nv else 0 : int(dense_off[-1]) if nv else 0
        ]
        bufs = [
            None
            if mask is None
            else pa.py_buffer(np.packbits(valid, bitorder="little").tobytes()),
            pa.py_buffer(out_off),
            pa.py_buffer(data),
        ]
        return pa.Array.from_buffers(
            atype, n_slots, bufs, null_count=int(mask.sum()) if mask is not None else 0
        )  # byte-array leaves have no logical retype (BYTE_ARRAY decimals stay raw)

    np_vals = np.asarray(values)
    if np_vals.ndim == 2:  # FLBA / INT96 byte rows
        atype = pa.binary(np_vals.shape[1])
        dense = np_vals[k0 : k0 + nv]
        if mask is None:
            flat = np.ascontiguousarray(dense).reshape(-1)
            built = pa.Array.from_buffers(atype, n_slots, [None, pa.py_buffer(flat)])
        else:
            it = iter(dense)
            rows = [bytes(next(it)) if ok else None for ok in valid]
            built = pa.array(rows, atype)
        return retype_leaf(pa, leaf, built)

    dense = np_vals[k0 : k0 + nv]
    if mask is None:
        return retype_leaf(pa, leaf, pa.array(dense))
    out = np.zeros(n_slots, dtype=np_vals.dtype)
    out[valid] = dense
    return retype_leaf(pa, leaf, pa.array(out, mask=mask))

"""Schema tree: Column nodes with max repetition/definition levels.

The dual-use (reader+writer) schema model of the reference (reference:
schema.go — Column tree, recursiveFix at :667-693, Thrift flattening/parsing at
:893-1015), minus the per-column value stores: in this design decoded data
lives in typed arrays keyed by column path, not inside the tree.

Level rules (Dremel): walking from the root, OPTIONAL or REPEATED increments
max_def; REPEATED also increments max_rep. The root is not counted.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import cached_property

from ..meta.parquet_types import (
    ConvertedType,
    FieldRepetitionType,
    LogicalType,
    SchemaElement,
    Type,
)

__all__ = ["Column", "Schema", "SchemaError"]


class SchemaError(ValueError):
    pass


@dataclass
class Column:
    """A node in the schema tree (group or leaf)."""

    element: SchemaElement
    children: list["Column"] = field(default_factory=list)
    path: tuple[str, ...] = ()
    max_def: int = 0
    max_rep: int = 0
    leaf_index: int = -1  # position among leaves, -1 for groups

    @property
    def name(self) -> str:
        return self.element.name

    @property
    def is_leaf(self) -> bool:
        return not self.children

    # type/repetition/converted_type are cached: enum construction per call
    # was the hottest line of the row-path shredder (1.3M Enum() calls per
    # 200k nested rows). Cache-safety invariant: schema elements are only
    # mutated while a tree is being BUILT — the builder mutates elements on
    # fresh clones (builder._clone_column) before any property is read, and
    # message()/group() share already-final Columns — so a cache never goes
    # stale. A future schema-rewrite pass must clone Columns, not mutate
    # elements in place.
    @cached_property
    def type(self) -> Type | None:
        return Type(self.element.type) if self.element.type is not None else None

    @property
    def type_length(self) -> int | None:
        return self.element.type_length

    @cached_property
    def repetition(self) -> FieldRepetitionType:
        rt = self.element.repetition_type
        return FieldRepetitionType(rt if rt is not None else 0)

    @cached_property
    def converted_type(self) -> ConvertedType | None:
        ct = self.element.converted_type
        return ConvertedType(ct) if ct is not None else None

    @property
    def logical_type(self) -> LogicalType | None:
        return self.element.logicalType

    @property
    def path_str(self) -> str:
        return ".".join(self.path)

    def is_string(self) -> bool:
        """UTF8 annotation (converted or logical)."""
        if self.converted_type == ConvertedType.UTF8:
            return True
        lt = self.logical_type
        return lt is not None and lt.STRING is not None

    def __repr__(self):
        kind = self.type.name if self.is_leaf and self.type is not None else "group"
        return (
            f"Column({self.path_str or '<root>'}: {kind}, "
            f"{self.repetition.name}, maxR={self.max_rep}, maxD={self.max_def})"
        )


def _deep_copy_column(col: Column) -> Column:
    import copy

    # deepcopy the element so nested structs (logicalType) are independent too
    elem = copy.deepcopy(col.element)
    return Column(element=elem, children=[_deep_copy_column(c) for c in col.children])


class Schema:
    """Parsed schema: root group + flat leaf list in file order."""

    def __init__(self, root: Column):
        self.root = root
        self.leaves: list[Column] = []
        self._by_path: dict[tuple[str, ...], Column] = {}
        self._finalize(root, 0, 0)

    def _finalize(self, node: Column, max_def: int, max_rep: int) -> None:
        for child in node.children:
            d, r = max_def, max_rep
            rep = child.repetition
            if rep in (FieldRepetitionType.OPTIONAL, FieldRepetitionType.REPEATED):
                d += 1
            if rep == FieldRepetitionType.REPEATED:
                r += 1
            child.max_def = d
            child.max_rep = r
            child.path = node.path + (child.name,)
            self._by_path[child.path] = child
            if child.is_leaf:
                child.leaf_index = len(self.leaves)
                self.leaves.append(child)
            else:
                self._finalize(child, d, r)

    # -- lookup ----------------------------------------------------------------

    def column(self, path) -> Column:
        """Find a node by tuple path or dotted string."""
        if isinstance(path, str):
            path = tuple(path.split("."))
        node = self._by_path.get(tuple(path))
        if node is None:
            raise SchemaError(f"schema: no column {'.'.join(path)}")
        return node

    def sub_schema(self, path) -> "Schema":
        """A new Schema rooted at the named group — the reference's
        SchemaDefinition.SubSchema (schema_def.go:137-150)."""
        node = self.column(path)
        if node.is_leaf:
            raise SchemaError(
                f"schema: sub_schema root {node.path_str!r} is a leaf, not a group"
            )
        return Schema(_deep_copy_column(node))

    def clone(self) -> "Schema":
        """Independent deep copy — the reference's SchemaDefinition.Clone
        (schema_def.go:106-112, which round-trips through the printer; here a
        structural copy, equivalent and cheaper)."""
        return Schema(_deep_copy_column(self.root))

    def __contains__(self, path) -> bool:
        if isinstance(path, str):
            path = tuple(path.split("."))
        return tuple(path) in self._by_path

    # -- thrift conversion -----------------------------------------------------

    @classmethod
    def from_thrift(cls, elements: list[SchemaElement]) -> "Schema":
        """Parse the depth-first-flattened element list of a footer
        (reference: schema.go:992 readSchema)."""
        if not elements:
            raise SchemaError("schema: empty element list")
        pos = 0

        def read_node(elem: SchemaElement) -> Column:
            nonlocal pos
            node = Column(element=elem)
            n = elem.num_children or 0
            if n < 0:
                raise SchemaError(f"schema: element {elem.name!r} claims {n} children")
            if n == 0 and elem.type is None:
                raise SchemaError(f"schema: group {elem.name!r} has no children and no type")
            for _ in range(n):
                # Re-check per child: earlier siblings' subtrees consume elements.
                if pos >= len(elements):
                    raise SchemaError(
                        f"schema: element {elem.name!r} claims {n} children "
                        "but the element list is exhausted"
                    )
                child_elem = elements[pos]
                pos += 1
                node.children.append(read_node(child_elem))
            return node

        root_elem = elements[0]
        pos = 1
        root = Column(element=root_elem)
        n = root_elem.num_children or 0
        if n <= 0:
            raise SchemaError("schema: root must have children")
        for _ in range(n):
            if pos >= len(elements):
                raise SchemaError("schema: truncated element list")
            child = elements[pos]
            pos += 1
            root.children.append(read_node(child))
        if pos != len(elements):
            raise SchemaError(
                f"schema: {len(elements) - pos} trailing elements after tree"
            )
        return cls(root)

    def to_thrift(self) -> list[SchemaElement]:
        out: list[SchemaElement] = []

        def emit(node: Column) -> None:
            out.append(node.element)
            for c in node.children:
                emit(c)

        root = self.root.element
        root.num_children = len(self.root.children)
        out.append(root)
        for c in self.root.children:
            emit(c)
        return out

    def __repr__(self):
        return f"Schema({len(self.leaves)} leaves)"

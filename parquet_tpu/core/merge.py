"""Row-group-level file merge: concatenate parquet files WITHOUT re-encoding.

The compaction primitive (parquet-mr ships it as `parquet-tools merge`;
the reference has no equivalent — beyond-reference feature): every input
row group's chunk bytes copy verbatim into the output, only the footer's
offsets are rewritten. No decode, no re-compression — merging N files
costs one sequential read + write of their page bytes.

Schemas must match exactly (element-by-element). Statistics, encodings and
sorting_columns carry over untouched (they describe the values, which are
byte-identical); page indexes and bloom filters live OUTSIDE the chunk
byte ranges in their source files and are NOT carried — re-write the file
with `write_page_index=`/`bloom_filters=` if you need them on the merged
output.

Output goes through the ByteSink seam (parquet_tpu.sink): a path gets the
atomic tmp+rename LocalFileSink, so a failed or interrupted merge/split
never leaves a torn output where the inputs' readers (or a compaction
daemon's glob) would pick it up; any ByteSink can be passed directly.
"""

from __future__ import annotations

from ..meta.file_meta import (
    MAGIC,
    ParquetFileError,
    read_file_metadata,
    serialize_footer,
)
from ..meta.parquet_types import FileMetaData, KeyValue
from ..sink.sink import open_sink
from .chunk import chunk_byte_range

__all__ = ["merge_files", "split_row_groups"]

_COPY_BLOCK = 8 << 20

def _copy_group(out, pos: int, f, rg, ordinal: int, src_label: str) -> int:
    """Copy one row group's chunk bytes verbatim from open input `f` to open
    output `out` at byte position `pos`, rewriting the group's footer
    offsets IN PLACE (callers pass a private RowGroup). Returns the new
    position. Shared by merge_files and split_row_groups so the two lanes
    can never diverge on offset handling."""
    first_new = None
    for cc in rg.columns or []:
        if cc.file_path:
            raise ParquetFileError(
                "parquet: merge/split does not support external column "
                f"chunks ({src_label!r})"
            )
        offset, total = chunk_byte_range(cc)
        delta = pos - offset
        f.seek(offset)
        remaining = total
        while remaining:
            block = f.read(min(remaining, _COPY_BLOCK))
            if not block:
                raise ParquetFileError(
                    f"parquet: merge/split input truncated ({src_label!r})"
                )
            out.write(block)
            remaining -= len(block)
        md = cc.meta_data
        for attr in (
            "data_page_offset", "dictionary_page_offset", "index_page_offset"
        ):
            v = getattr(md, attr)
            if v is not None:
                setattr(md, attr, v + delta)
        # regions outside the chunk range are not carried
        md.bloom_filter_offset = None
        md.bloom_filter_length = None
        cc.offset_index_offset = None
        cc.offset_index_length = None
        cc.column_index_offset = None
        cc.column_index_length = None
        if cc.file_offset:  # modern writers set 0: keep it
            cc.file_offset += delta
        if first_new is None:
            first_new = pos
        pos += total
    rg.file_offset = first_new
    rg.ordinal = ordinal
    return pos



def split_row_groups(in_path, out_pattern: str, groups_per_part: int = 1,
                     created_by: str | None = None) -> list:
    """Shard a file into parts of `groups_per_part` row groups each by
    copying chunk bytes VERBATIM (the converse of merge_files — no decode,
    no re-encoding; parquet-tool `split --groups` rides this). Returns the
    written part paths. `out_pattern` must contain %d."""
    if "%d" not in out_pattern:
        raise ParquetFileError("parquet: split pattern must contain %d")
    if groups_per_part < 1:
        raise ParquetFileError("parquet: groups_per_part must be >= 1")
    with open(in_path, "rb") as f:
        meta = read_file_metadata(f)
    n_groups = len(meta.row_groups or [])
    parts = []
    for part, lo in enumerate(range(0, n_groups, groups_per_part)):
        out = out_pattern % part
        _copy_groups(
            out, in_path, meta,
            range(lo, min(lo + groups_per_part, n_groups)),
            created_by or "parquet_tpu split",
        )
        parts.append(out)
    return parts


def _copy_groups(out_path, in_path, meta, group_indices, created_by) -> None:
    """One output file holding verbatim copies of the selected row groups.

    Deep-copies the footer structs it mutates (thrift round-trip) so the
    caller's metadata — shared across parts — stays untouched."""
    from ..meta.parquet_types import RowGroup

    import os

    st_in = os.stat(in_path)
    try:
        st_out = os.stat(out_path)
        if (st_out.st_dev, st_out.st_ino) == (st_in.st_dev, st_in.st_ino):
            raise ParquetFileError(
                f"parquet: split output {out_path!r} is the input"
            )
    except OSError:
        pass
    out_groups = []
    num_rows = 0
    out, owns = open_sink(out_path)
    try:
        with open(in_path, "rb") as f:
            out.write(MAGIC)
            pos = len(MAGIC)
            for gi in group_indices:
                rg = RowGroup.loads((meta.row_groups[gi]).dumps())  # private copy
                pos = _copy_group(out, pos, f, rg, len(out_groups), str(in_path))
                out_groups.append(rg)
                num_rows += rg.num_rows or 0
            out_meta = FileMetaData(
                version=2,
                schema=meta.schema,
                num_rows=num_rows,
                row_groups=out_groups,
                created_by=created_by,
                key_value_metadata=meta.key_value_metadata,
                column_orders=meta.column_orders,
            )
            out.write(serialize_footer(out_meta))
    except BaseException:
        out.abort()  # atomic sinks leave no partial part file
        raise
    if owns:
        out.close()  # commit
    else:
        out.flush()


def merge_files(out_path, in_paths, created_by: str | None = None,
                key_value_metadata: dict | None = None) -> FileMetaData:
    """Merge `in_paths` (order preserved) into `out_path` (a path, committed
    atomically, or any ByteSink) by copying row groups byte-for-byte.
    Returns the written FileMetaData."""
    if not in_paths:
        raise ParquetFileError("parquet: merge needs at least one input")
    import os

    out_key = None
    if isinstance(out_path, (str, os.PathLike)):
        try:
            out_id = os.stat(out_path)
            out_key = (out_id.st_dev, out_id.st_ino)
        except OSError:
            out_key = None  # output doesn't exist yet: cannot collide
    for p in in_paths:
        st = os.stat(p)
        if out_key is not None and (st.st_dev, st.st_ino) == out_key:
            raise ParquetFileError(
                f"parquet: merge output {out_path!r} is also an input "
                f"({p!r}) — opening it for write would destroy the source"
            )
    metas = []
    for p in in_paths:
        with open(p, "rb") as f:
            metas.append(read_file_metadata(f))
    schema = metas[0].schema
    for p, m in zip(in_paths[1:], metas[1:]):
        if m.schema != schema:
            raise ParquetFileError(
                f"parquet: merge schema mismatch: {p!r} does not match "
                f"{in_paths[0]!r}"
            )
        if m.column_orders != metas[0].column_orders:
            # stats interpretation differs: refusing beats silently
            # re-labeling another writer's ordering guarantees
            raise ParquetFileError(
                f"parquet: merge column-order mismatch: {p!r} does not "
                f"match {in_paths[0]!r}"
            )
    out_groups = []
    num_rows = 0
    out, owns = open_sink(out_path)
    try:
        out.write(MAGIC)
        pos = len(MAGIC)
        for path, meta in zip(in_paths, metas):
            with open(path, "rb") as f:
                for rg in meta.row_groups or []:
                    pos = _copy_group(out, pos, f, rg, len(out_groups), path)
                    out_groups.append(rg)
                    num_rows += rg.num_rows or 0
        kv = dict(key_value_metadata or {})
        out_meta = FileMetaData(
            version=2,
            schema=schema,
            num_rows=num_rows,
            row_groups=out_groups,
            created_by=created_by or "parquet_tpu merge",
            key_value_metadata=(
                [KeyValue(key=k, value=v) for k, v in kv.items()] or None
            ),
            # carried from the inputs (verified equal above): the copied
            # statistics keep the ordering their writer declared for them
            column_orders=metas[0].column_orders,
        )
        out.write(serialize_footer(out_meta))
    except BaseException:
        out.abort()  # atomic sinks leave no partial merge output
        raise
    if owns:
        out.close()  # commit
    else:
        out.flush()
    return out_meta

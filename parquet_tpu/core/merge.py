"""Row-group-level file merge: concatenate parquet files WITHOUT re-encoding.

The compaction primitive (parquet-mr ships it as `parquet-tools merge`;
the reference has no equivalent — beyond-reference feature): every input
row group's chunk bytes copy verbatim into the output, only the footer's
offsets are rewritten. No decode, no re-compression — merging N files
costs one sequential read + write of their page bytes.

Schemas must match exactly (element-by-element). Statistics, encodings and
sorting_columns carry over untouched (they describe the values, which are
byte-identical); page indexes and bloom filters live OUTSIDE the chunk
byte ranges in their source files and are NOT carried — re-write the file
with `write_page_index=`/`bloom_filters=` if you need them on the merged
output.
"""

from __future__ import annotations

from ..meta.file_meta import (
    MAGIC,
    ParquetFileError,
    read_file_metadata,
    serialize_footer,
)
from ..meta.parquet_types import FileMetaData, KeyValue
from .chunk import chunk_byte_range

__all__ = ["merge_files"]

_COPY_BLOCK = 8 << 20


def merge_files(out_path, in_paths, created_by: str | None = None,
                key_value_metadata: dict | None = None) -> FileMetaData:
    """Merge `in_paths` (order preserved) into `out_path` by copying row
    groups byte-for-byte. Returns the written FileMetaData."""
    if not in_paths:
        raise ParquetFileError("parquet: merge needs at least one input")
    import os

    try:
        out_id = os.stat(out_path)
        out_key = (out_id.st_dev, out_id.st_ino)
    except OSError:
        out_key = None  # output doesn't exist yet: cannot collide
    for p in in_paths:
        st = os.stat(p)
        if out_key is not None and (st.st_dev, st.st_ino) == out_key:
            raise ParquetFileError(
                f"parquet: merge output {out_path!r} is also an input "
                f"({p!r}) — opening it for write would destroy the source"
            )
    metas = []
    for p in in_paths:
        with open(p, "rb") as f:
            metas.append(read_file_metadata(f))
    schema = metas[0].schema
    for p, m in zip(in_paths[1:], metas[1:]):
        if m.schema != schema:
            raise ParquetFileError(
                f"parquet: merge schema mismatch: {p!r} does not match "
                f"{in_paths[0]!r}"
            )
        if m.column_orders != metas[0].column_orders:
            # stats interpretation differs: refusing beats silently
            # re-labeling another writer's ordering guarantees
            raise ParquetFileError(
                f"parquet: merge column-order mismatch: {p!r} does not "
                f"match {in_paths[0]!r}"
            )
    out_groups = []
    num_rows = 0
    with open(out_path, "wb") as out:
        out.write(MAGIC)
        pos = len(MAGIC)
        for path, meta in zip(in_paths, metas):
            with open(path, "rb") as f:
                for rg in meta.row_groups or []:
                    first_new = None
                    for cc in rg.columns or []:
                        if cc.file_path:
                            raise ParquetFileError(
                                "parquet: merge does not support external "
                                f"column chunks ({path!r})"
                            )
                        offset, total = chunk_byte_range(cc)
                        delta = pos - offset
                        f.seek(offset)
                        remaining = total
                        while remaining:
                            block = f.read(min(remaining, _COPY_BLOCK))
                            if not block:
                                raise ParquetFileError(
                                    f"parquet: merge input truncated ({path!r})"
                                )
                            out.write(block)
                            remaining -= len(block)
                        md = cc.meta_data
                        if md.data_page_offset is not None:
                            md.data_page_offset += delta
                        if md.dictionary_page_offset is not None:
                            md.dictionary_page_offset += delta
                        if md.index_page_offset is not None:
                            md.index_page_offset += delta
                        # regions outside the chunk range are not carried
                        md.bloom_filter_offset = None
                        md.bloom_filter_length = None
                        cc.offset_index_offset = None
                        cc.offset_index_length = None
                        cc.column_index_offset = None
                        cc.column_index_length = None
                        if cc.file_offset:  # modern writers set 0: keep it
                            cc.file_offset += delta
                        if first_new is None:
                            first_new = pos
                        pos += total
                    rg.file_offset = first_new
                    rg.ordinal = len(out_groups)
                    out_groups.append(rg)
                    num_rows += rg.num_rows or 0
        kv = dict(key_value_metadata or {})
        out_meta = FileMetaData(
            version=2,
            schema=schema,
            num_rows=num_rows,
            row_groups=out_groups,
            created_by=created_by or "parquet_tpu merge",
            key_value_metadata=(
                [KeyValue(key=k, value=v) for k, v in kv.items()] or None
            ),
            # carried from the inputs (verified equal above): the copied
            # statistics keep the ordering their writer declared for them
            column_orders=metas[0].column_orders,
        )
        out.write(serialize_footer(out_meta))
    return out_meta

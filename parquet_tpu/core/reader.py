"""FileReader: the low-level public read API.

Equivalent of the reference's FileReader (reference: file_reader.go:15-27
type, :32-63 ctor, :186-207 row-group seek/skip, :258-272 NextRow), redesigned
column-first: the primary read unit is a row group's worth of decoded column
arrays (`read_row_group`), which is what the TPU pipeline consumes; row
iteration (`iter_rows`) is record assembly layered on top.

Options mirror the reference's functional options (file_reader.go:89-149):
column projection, CRC validation, memory ceiling, pre-parsed metadata, and —
new here — decoder backend selection (host NumPy vs TPU kernels), the
WithDecoderBackend(TPU) of the north star.
"""

from __future__ import annotations

import gc
import itertools
import os
import threading
from contextlib import contextmanager, nullcontext
from functools import partial
from concurrent.futures import ThreadPoolExecutor

import numpy as np
from pathlib import Path
from typing import NamedTuple

from ..io.planner import DEFAULT_COALESCE_GAP, fetch_ranges
from ..io.source import SourceFile, open_source
from ..meta.file_meta import ParquetFileError, read_file_metadata
from ..meta.parquet_types import FileMetaData, RowGroup
from .alloc import AllocTracker
from .assembly import RecordAssembler
from .assembly_vec import (
    _zip_dict_rows,
    assemble_row_columns,
    slice_column,
    vec_enabled,
)
from .chunk import ChunkData, ChunkError, read_chunk
from .page import PageError
from .schema import Schema
from ..meta.thrift import ThriftError
from ..obs.log import log_event as _log_event
from ..utils import metrics as _metrics
from ..utils.trace import bump, span, stage, timed_stage, traced_submit

__all__ = ["FileReader", "PARQUET_ERRORS", "resolve_column_prefixes"]

# The typed malformed-file error family: everything a corrupt or lying file
# can legally raise out of a read. Anything else escaping a decode is a bug
# the fault-injection harness (parquet_tpu.testing.faults) hunts for.
PARQUET_ERRORS = (ParquetFileError, ChunkError, PageError, ThriftError)


def resolve_column_prefixes(schema: Schema, columns):
    """Resolve a column projection against a parsed schema: each entry is a
    dotted (or tuple) path prefix selecting every leaf under it — the
    reference's SetSelectedColumns convention. Returns the selected leaf
    path set (None = all), raising the typed error for unknown prefixes.
    Module-level so metadata-only callers (serve planning) validate with
    the exact semantics FileReader applies, without opening the file."""
    if columns is None:
        return None
    selected = set()
    for c in columns:
        path = tuple(c.split(".")) if isinstance(c, str) else tuple(c)
        hits = [
            leaf.path
            for leaf in schema.leaves
            if leaf.path[: len(path)] == path
        ]
        if not hits:
            raise ParquetFileError(f"parquet: selected column {c!r} not in schema")
        selected.update(hits)
    return selected


class _GroupQuarantined(Exception):
    """Internal control flow for on_error != 'raise': the current row group
    cannot be delivered (a required column was corrupt, or the policy is
    'skip'). Never escapes FileReader."""

_pool: ThreadPoolExecutor | None = None
_pool_lock = threading.Lock()


def _host_pool() -> ThreadPoolExecutor | None:
    """Shared worker pool for the host-side chunk prepare phase.

    Sized by PQT_HOST_THREADS (default: cpu count, capped at 16). The cap is
    real parallelism, not oversubscription insurance: the fused native
    chunk-prepare walk (decompress + level decode + prescan + repack) runs
    the whole chunk in one GIL-free C call, so N workers deliver ~N cores of
    prepare throughput until memory bandwidth saturates. Returns None when
    threading cannot help (single worker): single-core hosts, or the knob
    set to 0/1.
    """
    global _pool
    env = os.environ.get("PQT_HOST_THREADS")
    workers = int(env) if env else min(os.cpu_count() or 1, 16)
    if workers <= 1:
        return None
    with _pool_lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="pqt-host"
            )
        return _pool


def _with_device(fn, device):
    """Run `fn` under jax.default_device(device) (plain call when None).

    Device placement must travel WITH the callable onto whatever thread runs
    it: jax.default_device is thread-local, so a context entered on the
    caller's thread never reaches the `pqt-dispatch` worker. Every dispatch
    submission routes through this so an explicit `device=` is honored by
    every jnp.asarray the plan issues."""
    if device is None:
        return fn()
    import jax

    with jax.default_device(device):
        return fn()


def _dispatch_traced(fn, device):
    """Dispatch-thread task wrapper: device pinning plus a 'dispatch' stage
    so traces attribute transfer/launch wall time to the pqt-dispatch lane
    (the trace itself arrives via traced_submit's context carry)."""
    with stage("dispatch"):
        return _with_device(fn, device)


def _dispatch_pool() -> ThreadPoolExecutor:
    """The process-wide single-thread device-dispatch executor. Lives in
    kernels/pipeline.py (next to the device pipeline it feeds, shared with
    the dataset layer's batch uploads); imported lazily so pure host reads
    never pull jax in."""
    from ..kernels.pipeline import dispatch_pool

    return dispatch_pool()


def _timed_rows(assembler):
    """Stream rows from the scalar cursor walk, billing per-row time to the
    'assembly.rows' stage without materializing the row group.
    record_span=False: one sub-microsecond span PER ROW would flood the
    trace's event budget and crowd out the chunk/page hierarchy — the
    aggregate stays exact. Row count and wall time also feed the always-on
    assembly_rows_total{engine="scalar"} / assembly_seconds families."""
    it = iter(assembler)
    n = 0
    seconds = 0.0
    try:
        while True:
            with timed_stage("assembly.rows", record_span=False) as el:
                try:
                    row = next(it)
                except StopIteration:
                    break
            n += 1
            seconds += el.seconds
            yield row
    finally:
        # also runs when the consumer abandons the generator: delivered
        # rows still count
        _metrics.inc("assembly_rows_total", n, engine="scalar")
        _metrics.observe("assembly_seconds", seconds)


def _scatter_byte_offsets(valid: np.ndarray, offsets) -> np.ndarray:
    """Dense byte-array offsets (non-null cells only) -> offsets positioned
    at every slot, int64[len(valid) + 1], null slots zero-length. Shared by
    the flat and list to_arrow paths."""
    idx = np.clip(np.cumsum(valid) - 1, 0, None)
    ends = np.asarray(offsets[1:], dtype=np.int64)
    picked = ends[idx] if len(ends) else np.zeros(len(valid), dtype=np.int64)
    out = np.concatenate(
        [np.zeros(1, dtype=np.int64), np.where(valid, picked, 0)]
    )
    np.maximum.accumulate(out, out=out)
    return out


def _concat_group_tables(pa, parts):
    """Concatenate per-row-group pyarrow tables of the SAME selection,
    normalizing dictionary-vs-plain per column exactly like to_arrow's
    cross-group chunk assembly (a group with PLAIN fallback pages decodes
    plain while its siblings stay dictionary-typed). None for no parts."""
    if not parts:
        return None
    if len(parts) == 1:
        return parts[0]
    names = parts[0].column_names
    arrays = []
    for name in names:
        cols = [p.column(name) for p in parts]
        is_dict = [pa.types.is_dictionary(c.type) for c in cols]
        if any(is_dict) and not all(is_dict):
            cols = [
                c.cast(c.type.value_type) if pa.types.is_dictionary(c.type) else c
                for c in cols
            ]
        arrays.append(
            pa.chunked_array(
                [ch for c in cols for ch in c.chunks], type=cols[0].type
            )
        )
    return pa.table(dict(zip(names, arrays)))


class RaggedColumn(NamedTuple):
    """A LIST column in device-batch form: `values` is row-padded to a
    static [rows, max_list_len] matrix (unused slots zero-filled on device)
    and `lengths` is the int32 element count per row — the TPU-native
    ragged representation (a NamedTuple = a jax pytree node, so a jitted
    step takes the pair and masks with
    `jnp.arange(K) < col.lengths[:, None]`). Null and empty lists both have
    length 0."""

    values: object  # jax.Array[rows, max_list_len]
    lengths: object  # jax.Array[rows] int32


_pad_ragged_jit = None


def _pad_ragged_device(values, lengths, max_len: int) -> RaggedColumn:
    """Scatter a flat element vector into [rows, max_len] ON DEVICE: row
    offsets come from a cumsum of lengths, each row gathers its slice, and
    slots past the row's length zero-fill. Static shapes — one compile per
    (rows, max_len, dtype) bucket."""
    global _pad_ragged_jit
    import jax
    import jax.numpy as jnp

    if _pad_ragged_jit is None:

        @partial(jax.jit, static_argnames=("max_len",))
        def pad(v, ln, max_len):
            offs = jnp.concatenate(
                [jnp.zeros(1, jnp.int32), jnp.cumsum(ln, dtype=jnp.int32)]
            )
            idx = offs[:-1, None] + jnp.arange(max_len, dtype=jnp.int32)[None, :]
            nv = v.shape[0]
            mask = jnp.arange(max_len, dtype=jnp.int32)[None, :] < ln[:, None]
            safe = jnp.clip(idx, 0, max(nv - 1, 0))
            vals = v[safe] if nv else jnp.zeros(idx.shape, v.dtype)
            zero = jnp.zeros((), v.dtype)
            return jnp.where(mask, vals, zero)

        _pad_ragged_jit = pad
    return RaggedColumn(
        values=_pad_ragged_jit(values, lengths, max_len), lengths=lengths
    )


class MaskedColumn(NamedTuple):
    """A nullable column in device-batch form: `values` are row-aligned with
    null rows zero-filled on device; `mask` is True where the row is
    non-null — the TPU-native validity representation (NamedTuple = a jax
    pytree node, so a jitted step takes the pair directly and computes e.g.
    `jnp.where(col.mask, col.values, fill)` with no host trip)."""

    values: object  # jax.Array[n] of the column dtype
    mask: object    # jax.Array[n] bool


_expand_nullable_jit = None


def _expand_nullable_device(values, mask) -> MaskedColumn:
    """Scatter the dense non-null values into row positions ON DEVICE (nulls
    zero-filled): prefix-sum the validity mask into a gather index — the same
    levels-to-rows math as host null expansion, but no host round-trip. The
    jitted kernel is module-cached so repeated groups hit the compile cache."""
    global _expand_nullable_jit
    import jax
    import jax.numpy as jnp

    if _expand_nullable_jit is None:

        @jax.jit
        def expand(v, m):
            idx = jnp.cumsum(m) - 1
            idx = jnp.clip(idx, 0, jnp.maximum(v.shape[0] - 1, 0))
            dense = v[idx] if v.shape[0] else jnp.zeros(m.shape, v.dtype)
            zero = jnp.zeros((), v.dtype)
            return jnp.where(m, dense, zero)

        _expand_nullable_jit = expand
    return MaskedColumn(values=_expand_nullable_jit(values, mask), mask=mask)


# Rows materialize in windows this size: cyclic GC cost scales with LIVE
# tracked containers, so bounded windows keep collections cheap while
# consumers that drop rows as they go (aggregations, filters) never hold a
# whole 1M-row group of dicts.
_ASSEMBLE_WINDOW = 1 << 16


@contextmanager
def _gc_paused():
    """Pause cyclic GC around a bulk container build: each incremental
    collection re-scans the still-growing result (~25% of assembly wall
    time) and nothing in row assembly creates reference cycles."""
    was_enabled = gc.isenabled()
    if was_enabled:
        gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()


class FileReader:
    """Reads Parquet files: footer metadata, row groups, records.

    Usage:
        with FileReader("file.parquet") as r:
            cols = r.read_row_group(0)          # columnar (dict path -> ChunkData)
            for row in r.iter_rows():           # assembled records
                ...
    """

    def __init__(
        self,
        source,
        columns=None,
        *,
        validate_crc: bool = False,
        max_memory: int | None = None,
        metadata: FileMetaData | None = None,
        schema: Schema | None = None,
        backend: str = "host",
        compact_levels: bool = False,
        device=None,
        on_error: str = "raise",
        block_cache=None,
        footer_cache=None,
        coalesce_gap: int | None = None,
    ):
        # Every byte this reader touches flows through a ByteSource
        # (parquet_tpu.io.source): str/Path opens a lock-free pread-backed
        # LocalFileSource, a ByteSource (e.g. a RetryingSource over a remote
        # store) passes through, bytes/BytesIO/file-likes adapt. self._f is
        # a per-reader SourceFile cursor for the stream-shaped page walks.
        self._source, self._owns_file = open_source(source)
        self._f = SourceFile(self._source)
        # block_cache: a shared io.cache.BlockCache (or io.tiercache
        # TieredCache — same contract) chunk/range reads check before
        # touching the source (the dataset layer passes one so readahead
        # and repeated epochs hit memory). footer_cache: an
        # io.cache.FooterCache consulted/filled for path sources, so a
        # re-opened file parses its footer zero times. coalesce_gap:
        # an explicit byte gap, None (the 64 KiB local default) or
        # "auto" — resolve per fetch through the io.autotune profile of
        # this source's transport (remote stores coalesce MiB-scale).
        self._block_cache = block_cache
        if coalesce_gap is None:
            self._coalesce_gap = DEFAULT_COALESCE_GAP
        elif coalesce_gap == "auto":
            self._coalesce_gap = "auto"
        else:
            self._coalesce_gap = int(coalesce_gap)
        try:
            if metadata is not None:
                self.metadata = metadata
            else:
                path_key = (
                    str(source) if isinstance(source, (str, Path)) else None
                )
                # URL keys can't os.stat: validate against the remote
                # source's generation (size, ETag) instead
                gen = (
                    self._source.generation() if path_key is not None else None
                )
                cached = (
                    footer_cache.get(path_key, sig=gen)
                    if footer_cache is not None and path_key is not None
                    else None
                )
                if cached is not None:
                    self.metadata = cached
                else:
                    self.metadata = read_file_metadata(self._f)
                    if footer_cache is not None and path_key is not None:
                        footer_cache.put(path_key, self.metadata, sig=gen)
            # schema=: a pre-built Schema for this metadata (high-churn
            # callers like the dataset layer open one reader per row group;
            # rebuilding the schema tree from thrift every open is waste)
            self.schema = (
                schema
                if schema is not None
                else Schema.from_thrift(self.metadata.schema)
            )
            self.validate_crc = validate_crc
            self.alloc = AllocTracker(max_memory) if max_memory else None
            if backend not in ("host", "tpu", "tpu_roundtrip"):
                raise ValueError(
                    f"unknown backend {backend!r}: expected 'host', 'tpu', "
                    "or 'tpu_roundtrip'"
                )
            self.backend = backend
            # on_error: corruption-isolation policy for host-delivery reads
            # (read_row_group / iter_rows / to_arrow).
            #   "raise" (default)  the first typed Parquet error aborts the read
            #   "skip"             a corrupt column chunk quarantines its whole
            #                      row group (dropped; counters:
            #                      chunks_quarantined / row_groups_quarantined)
            #   "null"             the corrupt chunk delivers as all-null when
            #                      its column is optional; required columns
            #                      degrade to "skip" for that group
            # Device-resident delivery (read_row_group_device, device batches)
            # always raises: a training loop silently missing rows is worse
            # than a crash.
            if on_error not in ("raise", "skip", "null"):
                raise ValueError(
                    f"unknown on_error {on_error!r}: expected 'raise', "
                    "'skip', or 'null'"
                )
            self.on_error = on_error
            # compact_levels: R/D levels of delivered columns are stored
            # bit-packed (PackedLevels, width = bits(max_level)) instead of
            # uint16 arrays — the reference's packed_array memory layout
            # (packed_array.go:13-101), ~16x smaller at rest. Consumers widen
            # windows on demand; NumPy comparisons work transparently.
            self.compact_levels = compact_levels
            # device: an explicit jax.Device every delivered array is pinned
            # to — including work issued from the internal dispatch thread,
            # which a caller-side jax.default_device context (thread-local)
            # can never reach. None = the process default device.
            self.device = device
            self._selected = self._resolve_columns(columns)
        except BaseException:
            if self._owns_file:
                self._source.close()
            raise

    # -- properties ------------------------------------------------------------

    @property
    def num_rows(self) -> int:
        return self.metadata.num_rows or 0

    @property
    def num_row_groups(self) -> int:
        return len(self.metadata.row_groups or [])

    @property
    def created_by(self) -> str | None:
        return self.metadata.created_by

    @property
    def key_value_metadata(self) -> dict[str, str | None]:
        return {
            kv.key: kv.value for kv in (self.metadata.key_value_metadata or [])
        }

    def row_group(self, i: int) -> RowGroup:
        groups = self.metadata.row_groups or []
        if not 0 <= i < len(groups):
            raise IndexError(f"row group {i} out of range (file has {len(groups)})")
        return groups[i]

    # -- column selection (reference: file_reader.go SetSelectedColumns, schema.go:347-367)

    def _resolve_columns(self, columns):
        return resolve_column_prefixes(self.schema, columns)

    def set_selected_columns(self, *columns) -> None:
        self._selected = self._resolve_columns(columns if columns else None)

    # -- columnar reads --------------------------------------------------------

    def _pack_chunk_levels(self, path, delivered):
        """Swap a delivered ChunkData/DeviceColumn's level arrays for their
        bit-packed form (compact_levels contract). Widened arrays existed
        transiently during decode; this bounds the at-rest footprint."""
        if not self.compact_levels or delivered is None:
            return delivered
        from ..ops.packed_levels import PackedLevels

        col = self.schema.column(path)
        dl, rl = delivered.def_levels, delivered.rep_levels
        if dl is not None and not isinstance(dl, PackedLevels):
            delivered.def_levels = PackedLevels.from_array(dl, col.max_def)
        if rl is not None and not isinstance(rl, PackedLevels):
            delivered.rep_levels = PackedLevels.from_array(rl, col.max_rep)
        return delivered

    def read_row_group(self, i: int, columns=None) -> dict[tuple, ChunkData]:
        """Decode one row group into {leaf path: ChunkData}.

        Host-bound delivery always decodes on the host, even on the TPU
        backend: round-tripping every value through the device for a host
        destination is a measured net loss (fetching decoded columns back
        over the transfer link costs more than decoding them locally). The
        device path pays off when values *stay* in HBM — that's
        read_row_group_device. backend="tpu_roundtrip" forces the device
        decode + fetch anyway: it is the byte-identical parity oracle used
        by tests/test_tpu_backend.py.

        On the roundtrip backend all selected chunks are *planned* first
        (host prescan + async device dispatch), then finalized — every
        chunk's device work is in flight before the first fetch blocks."""
        return self._read_row_group(i, columns, pack=True)

    def _read_row_group(
        self, i: int, columns, pack: bool, dict_paths=frozenset()
    ) -> dict[tuple, ChunkData]:
        """pack=False is the internal iteration path: rows/batches consume
        the levels immediately, so bit-packing them (compact_levels) would be
        a pure pack+widen round trip with no at-rest benefit. `dict_paths`
        keeps those columns' dictionary indices unmaterialized when their
        chunk allows it (to_arrow read_dictionary=; both backends — the
        roundtrip path passes its decoded indices through finalize).

        Under on_error != 'raise' a corrupt chunk is quarantined instead of
        aborting: 'null' substitutes an all-null chunk (optional columns
        only), otherwise the WHOLE row group is dropped — columns of a group
        must stay row-aligned, so a single undeliverable chunk poisons the
        group. A dropped group returns {}."""
        with span("row_group", {"group": i}):
            return self._read_row_group_impl(i, columns, pack, dict_paths)

    def _read_row_group_impl(
        self, i: int, columns, pack: bool, dict_paths=frozenset()
    ) -> dict[tuple, ChunkData]:
        try:
            if self.backend == "tpu_roundtrip":
                try:
                    plans = self._plan_row_group(i, columns)
                    out = {
                        path: plan.finalize(keep_dict_indices=path in dict_paths)
                        for path, plan in plans.items()
                    }
                except PARQUET_ERRORS as e:
                    # chunks plan/finalize as a batch here, so isolation is
                    # group-granular on this backend
                    if self.on_error == "raise":
                        raise
                    bump("chunks_quarantined")
                    _log_event(
                        "chunk_quarantined", level="warning",
                        source=self._source.source_id, group=i,
                        error=f"{type(e).__name__}: {e}",
                    )
                    raise _GroupQuarantined() from e
            else:
                out = {}
                selected = list(self._selected_chunks(i, columns))
                # batched range fetch (coalesced, cache-aware); None falls
                # back to streaming page-by-page through the shared cursor
                windows = self._chunk_windows(selected)
                for path, cc, column in selected:
                    f = windows[path] if windows is not None else self._f
                    try:
                        out[path] = read_chunk(
                            f,
                            cc,
                            column,
                            validate_crc=self.validate_crc,
                            alloc=self.alloc,
                            keep_dict_indices=path in dict_paths,
                        )
                    except PARQUET_ERRORS as e:
                        if self.on_error == "raise":
                            raise
                        bump("chunks_quarantined")
                        _log_event(
                            "chunk_quarantined", level="warning",
                            source=self._source.source_id, group=i,
                            column=".".join(path),
                            error=f"{type(e).__name__}: {e}",
                        )
                        if self.on_error == "null":
                            nc = self._null_chunk(i, column)
                            if nc is not None:
                                bump("chunks_nulled")
                                out[path] = nc
                                continue
                        raise _GroupQuarantined() from e
        except _GroupQuarantined:
            bump("row_groups_quarantined")
            _log_event(
                "row_group_quarantined", level="warning",
                source=self._source.source_id, group=i,
            )
            return {}
        if pack and self.compact_levels:
            for path, cd in out.items():
                self._pack_chunk_levels(path, cd)
        return out

    def _null_chunk(self, i: int, column) -> "ChunkData | None":
        """An all-null stand-in for a quarantined chunk (on_error='null'):
        one level entry per row at definition 0. Only possible when the
        column is optional somewhere along its path (max_def > 0) — a
        REQUIRED column has no null representation, so the caller degrades
        to quarantining the group."""
        if column.max_def <= 0:
            return None
        rows = self.row_group(i).num_rows or 0
        from ..meta.parquet_types import Type
        from .arrays import ByteArrayData
        from .chunk import _empty_dtype

        if column.type == Type.BYTE_ARRAY:
            values = ByteArrayData(offsets=np.zeros(1, dtype=np.int64), data=b"")
        elif column.type == Type.FIXED_LEN_BYTE_ARRAY:
            # fixed-width values decode as (n, width) uint8 rows; a 1-D empty
            # here would type the Arrow chunk uint8 and crash concatenation
            # against clean groups' fixed_size_binary chunks
            values = np.empty((0, column.type_length or 0), dtype=np.uint8)
        elif column.type == Type.INT96:
            values = np.empty((0, 12), dtype=np.uint8)
        else:
            values = np.empty(0, dtype=_empty_dtype(column))
        return ChunkData(
            column=column,
            num_values=rows,
            values=values,
            def_levels=np.zeros(rows, dtype=np.uint16),
            rep_levels=(
                np.zeros(rows, dtype=np.uint16) if column.max_rep > 0 else None
            ),
        )

    def _effective_device(self, device=None):
        """Precedence rule, in one place: per-call override > reader default
        > process default (None)."""
        return device if device is not None else self.device

    def _devctx(self, device=None):
        """Context manager that pins caller-thread jax work to the effective
        device."""
        dev = self._effective_device(device)
        if dev is None:
            return nullcontext()
        import jax

        return jax.default_device(dev)

    def read_row_group_device(
        self, i: int, columns=None, device=None, *, filters=None
    ):
        """Decode one row group straight into device memory (HBM).

        The TPU-native delivery point: returns {leaf path: DeviceColumn} whose
        value arrays are jax arrays resident on the accelerator — encoded
        bytes go up, decoded columns never come back down. Works regardless
        of the reader's configured backend. `device` pins this call's arrays
        to one jax.Device (overriding the reader-level `device=`); unlike a
        caller-side jax.default_device context it also reaches the internal
        dispatch thread.

        `filters` (same spec as iter_rows) additionally evaluates the
        predicate over the DELIVERED columns and returns ({leaf path:
        DeviceColumn}, mask) — the mask a device bool[num_rows] row array
        computed IN HBM (core/filter_device; the host vec engine takes over,
        typed and counted, for any shape the device engine declines). Any
        filter column missing from `columns` is read and delivered too (the
        mask needs it resident). The columns are NOT compacted: feed the
        mask to kernels.device_ops.mask_take_device for the gather, or carry
        it into masked reductions unsliced — that is the
        predicate -> mask -> gather pipeline with one jit cache entry per
        (schema, pad-bucket)."""
        if filters is None:
            return self._read_row_group_device(i, columns, pack=True, device=device)
        from .filter import normalize_dnf

        normalized = normalize_dnf(self.schema, filters)
        read_columns = self._columns_with_filters(columns, normalized)
        cols = self._read_row_group_device(
            i, read_columns, pack=True, device=device
        )
        n = int(self.row_group(i).num_rows or 0)
        with self._devctx(device):
            mask = self._device_group_mask(i, cols, normalized, n)
        return cols, mask

    def _columns_with_filters(self, columns, normalized):
        """The read set a row-filtered device read needs: the caller's
        projection plus any filter-referenced leaf it misses (None = all
        columns, which already covers every filter leaf)."""
        if columns is None:
            return None
        proj = self._resolve_columns(columns)
        if proj is None:
            return None
        fpaths = {e[0] for conj in normalized for e in conj}
        return sorted(proj) + sorted(p for p in fpaths if p not in proj)

    def _device_group_mask(self, i, group, normalized, n, *, null_mode="row"):
        """bool[n] DEVICE row mask for group i's delivered columns — the
        engine ladder: device kernels (filter_device.device_dnf_mask) first;
        any typed decline counts device_filter_declined and re-derives the
        mask with the host vec engine (exact for everything the zoo holds;
        a shape even IT declines raises its typed error)."""
        import jax.numpy as jnp

        from ..utils.trace import bump as trace_bump
        from .filter_device import DeviceFilterError, device_dnf_mask

        with span("query.mask", {"group": i, "terms": len(normalized)}):
            try:
                mask = device_dnf_mask(group, normalized, n, null_mode=null_mode)
            except DeviceFilterError:
                trace_bump("device_filter_declined")
                return jnp.asarray(
                    self._host_row_mask(i, normalized, n, null_mode)
                )
            trace_bump("device_filter_engaged")
            return mask

    def _host_row_mask(self, i, normalized, n, null_mode="row"):
        """Host-engine fallback mask: decode the filter columns on host and
        run the vec mask pipeline (np bool[n])."""
        from .filter_vec import dnf_mask

        cols = sorted({e[0] for conj in normalized for e in conj})
        chunks = self._read_row_group(i, cols, pack=False)
        if not chunks:
            # quarantined under an on_error policy: no rows to admit
            return np.zeros(n, dtype=bool)
        return dnf_mask(chunks, normalized, n, null_mode=null_mode)

    def _device_filter_rows(self, i, group, normalized, arrs, n):
        """Row-level compaction for one staged group (iter_device_batches
        filter_rows=True): DNF -> resident mask (_device_group_mask, with
        its typed + counted host fallback) -> ONE mask_take_device index
        shared by every delivered leaf — each pytree leaf compacts with a
        single padded gather, so the jit cache stays bounded by the
        (schema, pad-bucket) pair. Returns (filtered arrs, kept rows)."""
        import jax
        import jax.numpy as jnp

        from ..kernels.device_ops import mask_take_device
        from ..kernels.pipeline import _bucket

        mask = self._device_group_mask(i, group, normalized, n)
        with span("query.take", {"group": i, "rows": n}):
            sel, cnt = mask_take_device(
                jnp.arange(n, dtype=jnp.int32), mask, _bucket(n)
            )
            kept = int(cnt)
            if kept == n:
                return arrs, n
            if kept == 0:
                return arrs, 0
            arrs = jax.tree_util.tree_map(lambda a: a[sel][:kept], arrs)
            return arrs, kept

    def _read_row_group_device(self, i: int, columns, pack: bool, device=None):
        """pack=False mirrors _read_row_group: the batch iterator consumes
        levels immediately (mask build), so packing them would be overhead."""
        with span("row_group.device", {"group": i}):
            plans = self._plan_row_group(i, columns, device=device)
            with self._devctx(device):
                out = {path: plan.device_column() for path, plan in plans.items()}
            if pack and self.compact_levels:
                for path, dc in out.items():
                    self._pack_chunk_levels(path, dc)
            return out

    def read_row_groups_device(self, row_groups=None, columns=None, device=None):
        """Decode row groups into device memory with full pipelining.

        Unlike per-group read_row_group_device calls — which resolve each
        group's dispatch futures before the next group's host prepare starts
        — this plans EVERY chunk of every requested group first (prepare on
        worker threads / dispatch on the dispatch thread, all overlapped) and
        only then materializes results. Returns [{leaf path: DeviceColumn}]
        in row-group order."""
        indices = list(
            range(self.num_row_groups) if row_groups is None else row_groups
        )
        if self.alloc is not None:
            # A memory ceiling is per-row-group (released between groups on
            # the host path); cross-group pipelining would account all
            # groups' decoded buffers at once and spuriously trip it, so
            # ceiling-capped readers stage one group at a time.
            return [
                self.read_row_group_device(i, columns, device=device)
                for i in indices
            ]
        staged = self._plan_row_groups_async(indices, columns, device=device)
        out = []
        for group in staged:
            with self._devctx(device):
                cols = {
                    path: fut.result().device_column() for path, fut in group
                }
            out.append(
                {p: self._pack_chunk_levels(p, dc) for p, dc in cols.items()}
            )
        return out

    def _plan_row_group_async(self, i: int, columns=None, device=None):
        """Stage one row group: prepare (pool or inline) + enqueue dispatch.
        Returns [(path, future-of-dispatched-plan)] without resolving."""
        return self._plan_row_groups_async([i], columns, device=device)[0]

    def iter_device_batches(
        self,
        batch_size: int,
        columns=None,
        drop_remainder: bool = True,
        sharding=None,
        nullable: str = "error",
        filters=None,
        filter_rows: bool = False,
        lists: str = "error",
        max_list_len: int | None = None,
        device=None,
    ):
        """Stream the file as fixed-size device-resident batches.

        The TPU-native consumption pattern: each yielded batch is
        {leaf path: jax.Array} with exactly `batch_size` rows (static shape —
        a jitted train step compiles once), values already decoded in HBM.
        Dictionary-encoded byte-array columns yield their int32 indices
        (embedding-lookup style). Unsupported shapes raise: raw byte-array
        columns (no device form), repeated/LIST columns (leaf slots are not
        rows) — project them out with `columns=` or transform upstream.

        `nullable` picks the policy for columns with nulls:
          "error" (default)  raise — non-null cells would silently shift rows
          "mask"             yield MaskedColumn(values, mask): values are
                             row-aligned with nulls zero-filled ON DEVICE and
                             mask is a bool row validity array — the
                             TPU-native null representation (a jit step takes
                             the pair as a pytree: jnp.where(m, v, ...)).

        While the consumer runs on group i's batches, group i+1 is already
        preparing and dispatching (one-group lookahead); memory stays
        bounded by two row groups plus the carry. With drop_remainder=False
        the final short batch is yielded as-is (dynamic shape: callers pad
        or accept a recompile).

        `sharding` (a jax.sharding.Sharding, e.g. NamedSharding(mesh,
        P("data"))) lays every batch out across a device mesh — the
        data-parallel input pipeline: decode once, shard over ICI. The
        batch size must divide evenly over the sharded axis.

        `lists` picks the policy for single-level LIST columns:
          "error" (default)  raise — leaf slots are not rows
          "pad"              yield RaggedColumn(values, lengths): values
                             row-padded ON DEVICE to a static
                             [rows, max_list_len] matrix (zero-filled past
                             each row's length), lengths the per-row element
                             count — the TPU-native ragged representation
                             for sequence data. Requires max_list_len; a row
                             exceeding it raises. Null and empty lists both
                             have length 0.

        `filters` pushes a predicate (a (column, op, value) conjunction, or
        a list of lists — the OR-of-ANDs DNF convention) down to ROW-GROUP
        granularity: groups whose statistics/bloom filters exclude the
        predicate are never prepared, uploaded, or decoded. Surviving groups
        stream whole (batches keep their static shape; rows are NOT
        individually filtered — filter columns may admit non-matching rows,
        exact per-row masking is the consumer's jnp.where).

        `filter_rows=True` (requires `filters`) extends the push-down to ROW
        granularity IN HBM: each surviving group's predicate evaluates as a
        device mask over the resident columns (core/filter_device) and one
        mask_take_device compaction gathers only matching rows into the
        batch stream — predicate -> mask -> gather, never round-tripping the
        host. Batches keep their static shape (matching rows pack densely
        across group boundaries); a predicate shape the device engine
        cannot run falls back, typed and counted
        (device_filter_engaged/declined), to the host vec engine's mask
        with the same compaction. Filter columns missing from `columns=`
        are read for the mask but not delivered in batches.

        `device` pins every batch's arrays to one jax.Device (overriding the
        reader-level `device=`); unlike a caller-side jax.default_device
        context it also reaches the internal dispatch thread. Mutually
        useful with `sharding`: decode lands on `device`, device_put lays
        each batch out over the mesh.
        """
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if nullable not in ("error", "mask"):
            raise ValueError('nullable must be "error" or "mask"')
        if lists not in ("error", "pad"):
            raise ValueError('lists must be "error" or "pad"')
        if lists == "pad":
            if max_list_len is None or max_list_len <= 0:
                raise ValueError('lists="pad" requires a positive max_list_len')
            # eager, like every other argument: nested lists fail at the
            # call, not at the first next() deep in a train loop
            sel = self._resolve_columns(columns) if columns else self._selected
            for leaf in self.schema.leaves:
                if (sel is None or leaf.path in sel) and leaf.max_rep > 1:
                    raise ParquetFileError(
                        f"parquet: column {leaf.path_str} has {leaf.max_rep} "
                        "repetition levels; ragged batching covers "
                        "single-level LIST columns only"
                    )
        normalized = None
        if filters is not None:
            # eager validation, like batch_size/nullable: a bad column or op
            # should fail HERE, not at the first next() deep in a train loop
            from .filter import normalize_dnf

            normalized = normalize_dnf(self.schema, filters)
        if filter_rows and normalized is None:
            raise ValueError("filter_rows=True requires filters")
        return self._iter_device_batches(
            batch_size, columns, drop_remainder, sharding, nullable,
            normalized, lists, max_list_len, device, filter_rows,
        )

    def _iter_device_batches(
        self, batch_size: int, columns, drop_remainder: bool, sharding=None,
        nullable: str = "error", normalized=None, lists: str = "error",
        max_list_len=None, device=None, filter_rows: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        def _ragged(path, dc, arr):
            from ..meta.parquet_types import FieldRepetitionType

            leaf = self.schema.column(path)
            if leaf.max_rep != 1:
                raise ParquetFileError(
                    f"parquet: column {'.'.join(path)} has {leaf.max_rep} "
                    "repetition levels; ragged batching covers single-level "
                    "LIST columns only"
                )
            rl = np.asarray(dc.rep_levels)
            starts = np.nonzero(rl == 0)[0]
            if dc.def_levels is not None:
                dl = np.asarray(dc.def_levels)
                present = dl == leaf.max_def
                # a null ELEMENT (optional leaf, def one below max) would
                # silently left-shift its row's survivors — corruption for
                # position-sensitive sequences, so refuse
                if leaf.repetition == FieldRepetitionType.OPTIONAL and bool(
                    (dl == leaf.max_def - 1).any()
                ):
                    raise ParquetFileError(
                        f"parquet: column {'.'.join(path)} has null elements "
                        "inside lists; ragged batching would shift positions "
                        "(fill nulls upstream)"
                    )
            else:
                present = np.ones(len(rl), dtype=bool)
            # every row owns >= 1 level entry (null/empty lists carry one
            # below-max entry), so reduceat over row starts counts elements
            lengths = (
                np.add.reduceat(present.astype(np.int32), starts)
                if len(starts)
                else np.zeros(0, dtype=np.int32)
            )
            if arr.shape[0] != int(present.sum()):
                raise ParquetFileError(
                    f"parquet: column {'.'.join(path)} level/value mismatch"
                )
            if len(lengths) and int(lengths.max()) > max_list_len:
                raise ParquetFileError(
                    f"parquet: column {'.'.join(path)} has a row with "
                    f"{int(lengths.max())} elements > max_list_len="
                    f"{max_list_len} (raise it, or filter upstream)"
                )
            return _pad_ragged_device(
                arr, jnp.asarray(lengths), int(max_list_len)
            )

        def _array_of(path, dc):
            arr = dc.values if dc.values is not None else dc.indices
            if arr is None:
                raise ParquetFileError(
                    f"parquet: column {'.'.join(path)} has no device array form "
                    "(raw byte-array columns cannot batch; project them out)"
                )
            if dc.rep_levels is not None:
                if lists == "pad":
                    return _ragged(path, dc, arr)
                raise ParquetFileError(
                    f"parquet: column {'.'.join(path)} is repeated; its leaf "
                    "slots are not rows, so it cannot batch (project it "
                    'out, or pass lists="pad" with max_list_len)'
                )
            has_nulls = arr.shape[0] != dc.num_values
            if nullable == "mask" and dc.def_levels is not None:
                max_def = self.schema.column(path).max_def
                if max_def > 0:
                    mask = jnp.asarray(dc.def_levels == max_def)
                    if has_nulls:
                        return _expand_nullable_device(arr, mask)
                    # no nulls in THIS group, but the column is declared
                    # optional: keep the pytree structure stable across
                    # groups/batches
                    return MaskedColumn(values=arr, mask=mask)
            if has_nulls:
                raise ParquetFileError(
                    f"parquet: column {'.'.join(path)} contains nulls; "
                    "device batches need null-free columns (filter or fill "
                    'upstream, project the column out, or pass nullable="mask")'
                )
            return arr

        if normalized is not None:
            # group-level pushdown: excluded groups never touch the device
            groups = self._prune_groups_normalized(normalized)
        else:
            groups = list(range(self.num_row_groups))
        # row-level pushdown reads filter-referenced leaves too (the mask
        # needs them resident), but only the caller's projection batches
        proj = None
        read_columns = columns
        if filter_rows:
            proj = self._resolve_columns(columns) if columns else self._selected
            read_columns = self._columns_with_filters(
                columns if columns else (sorted(proj) if proj else None),
                normalized,
            )
        # a memory ceiling forbids the lookahead's two-groups residency
        lookahead = self.alloc is None

        def stage(i):
            if lookahead:
                return self._plan_row_group_async(i, read_columns, device=device)
            return None

        staged_next = stage(groups[0]) if groups and lookahead else None
        carry: dict = {}
        carry_n = 0
        for gi, i in enumerate(groups):
            # device work scoped so the pin never leaks across a yield into
            # the consumer's frame (jax.default_device is thread-local and
            # the consumer runs on this thread between batches)
            with self._devctx(device):
                if lookahead:
                    staged = staged_next
                    staged_next = (
                        stage(groups[gi + 1]) if gi + 1 < len(groups) else None
                    )
                    # no level packing here: _array_of consumes the levels
                    # (mask build) within this iteration, so they never rest
                    group = {
                        path: fut.result().device_column() for path, fut in staged
                    }
                else:
                    group = self._read_row_group_device(
                        i, read_columns, pack=False, device=device
                    )
                arrs = {
                    path: _array_of(path, dc)
                    for path, dc in group.items()
                    if proj is None or path in proj
                }
                if not arrs:
                    continue
                lengths = {a.shape[0] for a in jax.tree_util.tree_leaves(arrs)}
                if len(lengths) != 1:
                    raise ParquetFileError(
                        f"parquet: columns disagree on row count in group {i}: "
                        f"{sorted(lengths)}"
                    )
                n = lengths.pop()
                if filter_rows:
                    arrs, n = self._device_filter_rows(i, group, normalized, arrs, n)
                    if not n:
                        continue
                if carry_n:
                    cat = jax.tree_util.tree_map(
                        lambda c, a: jnp.concatenate([c, a]), carry, arrs
                    )
                else:
                    cat = arrs
            total = carry_n + n
            # cursor slicing: each batch is one static-shape slice; the tail
            # is sliced once per row group, not once per batch
            off = 0
            while total - off >= batch_size:
                lo = off
                with self._devctx(device):
                    batch = jax.tree_util.tree_map(
                        lambda a, lo=lo: a[lo : lo + batch_size], cat
                    )
                    if sharding is not None:
                        batch = jax.device_put(batch, sharding)
                yield batch
                off += batch_size
            carry_n = total - off
            with self._devctx(device):
                carry = (
                    jax.tree_util.tree_map(lambda a: a[off:], cat) if carry_n else {}
                )
        if carry_n and not drop_remainder:
            if sharding is not None:
                try:
                    carry = jax.device_put(carry, sharding)
                except ValueError:
                    # tail not divisible over the mesh axis: deliver it
                    # unsharded rather than dying on the last batch (callers
                    # already handle the tail's dynamic shape)
                    pass
            yield carry

    def _plan_row_groups_async(self, indices, columns=None, device=None):
        """Stage chunks of several row groups at once.

        Every chunk's prepare is submitted to the worker pool up front (no
        per-group barrier — the pool never drains between groups); device
        dispatch is enqueued per chunk in deterministic (group, column) order
        as its prepare resolves. Returns [[(path, future-of-dispatched-plan)]]
        per group, unresolved."""
        from ..kernels.pipeline import prepare_chunk_plan
        from ..utils.native import get_native
        from .chunk import ChunkWindow, chunk_byte_range

        groups = [list(self._selected_chunks(i, columns)) for i in indices]

        def prep(path, cc, column):
            with span("chunk.prepare", {"column": ".".join(path)}):
                offset, total = chunk_byte_range(cc)
                win = ChunkWindow(self._fetch_chunk(offset, total), offset)
                return prepare_chunk_plan(
                    win, cc, column, validate_crc=self.validate_crc, alloc=self.alloc
                )

        dev = self._effective_device(device)
        dispatcher = _dispatch_pool()
        pool = _host_pool()
        # Both pool hops use traced_submit: an active decode_trace is a
        # contextvar, which ThreadPoolExecutor does NOT carry into workers
        # by itself — without the explicit copy_context() carry a traced
        # device read would lose every prepare/dispatch stage to the void
        # (and two concurrent traced readers sharing the pools would have no
        # way to attribute worker time to the right trace).
        staged = []
        if pool is None or sum(len(g) for g in groups) <= 1:
            # Single-core host: prepare serially; device dispatch (transfer
            # RPCs, which release the GIL) still overlaps the next prepare.
            for chunks in groups:
                out = []
                for path, cc, column in chunks:
                    plan = prep(path, cc, column)
                    out.append(
                        (
                            path,
                            traced_submit(
                                dispatcher, _dispatch_traced, plan.dispatch_device, dev
                            ),
                        )
                    )
                staged.append(out)
            return staged
        get_native()  # thread-safe lazy init before fan-out
        prep_futs = [
            [
                (path, traced_submit(pool, prep, path, cc, column))
                for path, cc, column in chunks
            ]
            for chunks in groups
        ]
        for group in prep_futs:
            out = []
            for path, fut in group:
                plan = fut.result()
                out.append(
                    (
                        path,
                        traced_submit(
                            dispatcher, _dispatch_traced, plan.dispatch_device, dev
                        ),
                    )
                )
            staged.append(out)
        return staged

    def _plan_row_group(self, i: int, columns=None, device=None):
        """Plan every selected chunk of a row group for device decode.

        The host-only prepare phase (one pread per chunk, page walk,
        decompress, level decode, prescan) fans out over worker threads —
        decompression and the native prescans release the GIL — while device
        dispatch runs on the dispatch thread, in deterministic column order,
        overlapped with the next chunk's prepare.
        """
        return {
            path: fut.result()
            for path, fut in self._plan_row_group_async(i, columns, device=device)
        }

    def _pread(self, offset: int, size: int) -> bytes:
        """Positional read through the reader's ByteSource — os.pread on
        local files, so there is no shared cursor, no lock, and no position
        save/restore. Clamps at EOF (short return, like a plain handle):
        truncated files surface as the decode ladder's typed errors, not a
        raw source exception."""
        end = self._source.size()
        if offset >= end or offset < 0 or size <= 0:
            return b""
        return self._source.read_at(offset, min(size, end - offset))

    def _fetch_chunk(self, offset: int, size: int):
        """One chunk's page bytes, through the block cache when attached.
        Out-of-bounds or degenerate ranges (truncated/lying files) bypass
        the cache and return short via _pread so corruption keeps its typed
        decode error."""
        if size <= 0 or offset < 0 or offset + size > self._source.size():
            return self._pread(offset, size)
        if self._block_cache is None:
            return self._source.read_at(offset, size)
        return fetch_ranges(
            self._source,
            [(offset, size)],
            cache=self._block_cache,
            gap=0,
        )[(offset, size)]

    def _chunk_windows(self, selected) -> "dict | None":
        """Planner-driven batched fetch of the selected chunks' byte ranges:
        exact extents from the footer, neighbors coalesced (io.coalesce)
        into batched source reads (io.read), each chunk handed back as a
        preloaded ChunkWindow. Returns None when the planner path does not
        apply — memory-ceiling readers (preloading a whole group would
        charge every page at once) and chunks whose metadata ranges are
        unusable or out of bounds (the streaming walk raises the precise
        typed error there)."""
        if self.alloc is not None or not selected:
            return None
        from .chunk import ChunkWindow, chunk_byte_range

        ranges = {}
        end = self._source.size()
        for path, cc, _col in selected:
            try:
                off, total = chunk_byte_range(cc)
            except ChunkError:
                return None
            # total == 0 included: coalesce() drops empty ranges, so the
            # fetch would come back without the key — the streaming walk
            # instead raises the exact typed value-count error
            if off < 0 or total <= 0 or off + total > end:
                return None
            ranges[path] = (off, total)
        fetched = fetch_ranges(
            self._source,
            list(ranges.values()),
            cache=self._block_cache,
            gap=self._coalesce_gap,
        )
        return {
            path: ChunkWindow(fetched[r], r[0]) for path, r in ranges.items()
        }

    def _selected_chunks(self, i: int, columns=None):
        """Yield (path, ColumnChunk, Column) for the selected leaves of group i."""
        rg = self.row_group(i)
        selected = self._resolve_columns(columns) if columns else self._selected
        if self.alloc is not None:
            self.alloc.release()
        for cc in rg.columns or []:
            md = cc.meta_data
            if md is None:
                raise ParquetFileError("parquet: column chunk without metadata")
            path = tuple(md.path_in_schema or [])
            if selected is not None and path not in selected:
                continue  # skipChunk (reference: chunk_reader.go:271)
            yield path, cc, self.schema.column(path)

    # -- record iteration ------------------------------------------------------

    def prune_row_groups(self, filters) -> list[int]:
        """Row-group indices whose chunk statistics admit the filters —
        groups provably excluded by written min/max/null-count never load
        (statistics-driven pruning; the reference writes stats but never
        consumes them, README.md:47)."""
        return self.prune_row_groups_counted(filters)[0]

    def prune_row_groups_counted(self, filters) -> tuple:
        """`(admitted_indices, stats_pruned, bloom_pruned)` — the same
        pruning walk as prune_row_groups, attributing each excluded group
        to the rung that excluded it (statistics first, then bloom). The
        plan layer's pruning summary (`ScanPlan.pruning_summary()`) is fed
        from here so the semantics live in ONE place."""
        from .filter import normalize_dnf, row_group_may_match

        dnf = normalize_dnf(self.schema, filters)
        admitted: list[int] = []
        stats_pruned = bloom_pruned = 0
        for i in range(self.num_row_groups):
            # one walk per (group, conjunction): dnf_group_may_match's OR
            # semantics, unrolled so each stats evaluation happens once and
            # the excluding rung is known without a second pass
            rg = self.row_group(i)
            stats_ok = survives = False
            for conj in dnf:
                if not row_group_may_match(rg, conj):
                    continue
                stats_ok = True
                if self._bloom_excludes(i, conj):
                    continue
                survives = True
                break
            if survives:
                admitted.append(i)
            elif stats_ok:
                bloom_pruned += 1
            else:
                stats_pruned += 1
        return admitted, stats_pruned, bloom_pruned

    def _prune_groups_normalized(self, dnf) -> list[int]:
        from .filter import dnf_group_may_match

        return [
            i
            for i in range(self.num_row_groups)
            if dnf_group_may_match(self.row_group(i), dnf, self._bloom_excludes, i)
        ]

    def read_page_index(self, i: int, columns=None) -> dict:
        """The Parquet page index of row group i: {leaf path: (ColumnIndex,
        OffsetIndex)}; columns whose chunk carries no index map to
        (None, None). Beyond the reference (no page-index support there);
        parity oracle is pyarrow's write_page_index=True output."""
        from ..meta.parquet_types import ColumnIndex, OffsetIndex
        from ..meta.thrift import ThriftError

        out = {}
        for path, cc, _col in self._selected_chunks(i, columns):
            ci = oi = None
            try:
                # _fetch_chunk, not _pread: with a block cache attached the
                # index ranges persist across readers, so warm re-planning
                # (the serve daemon's repeat requests) reads zero bytes
                if cc.column_index_offset and cc.column_index_length:
                    ci = ColumnIndex.loads(
                        self._fetch_chunk(
                            cc.column_index_offset, cc.column_index_length
                        )
                    )
                if cc.offset_index_offset and cc.offset_index_length:
                    oi = OffsetIndex.loads(
                        self._fetch_chunk(
                            cc.offset_index_offset, cc.offset_index_length
                        )
                    )
            except ThriftError as e:
                raise ParquetFileError(
                    f"parquet: corrupt page index for {'.'.join(path)}: {e}"
                ) from e
            out[path] = (ci, oi)
        return out

    def read_bloom_filter(self, i: int, column):
        """The split-block bloom filter of one column chunk, or None when
        the chunk carries none. Beyond the reference; pyarrow's
        bloom_filter_options output is the cross-implementation oracle."""
        from .bloom import BloomFilter

        path = tuple(column.split(".")) if isinstance(column, str) else tuple(column)
        cache = getattr(self, "_bloom_cache", None)
        if cache is None:
            cache = self._bloom_cache = {}
        if (i, path) in cache:
            return cache[(i, path)]
        rg = self.row_group(i)
        for cc in rg.columns or []:
            md = cc.meta_data
            if md is None or tuple(md.path_in_schema or []) != path:
                continue
            off = md.bloom_filter_offset
            if not off or off <= 0:
                cache[(i, path)] = None
                return None
            length = md.bloom_filter_length
            if not length or length <= 0:
                # header precedes the bitset; peek enough for the header,
                # parse numBytes, then take exactly header+bitset
                # (cache-routed so warm re-pruning repeats it from memory)
                peek = self._fetch_chunk(off, 64)
                from ..meta.parquet_types import BloomFilterHeader
                from ..meta.thrift import CompactReader, ThriftError

                try:
                    r = CompactReader(peek)
                    h = BloomFilterHeader.read(r)
                except ThriftError as e:
                    raise ParquetFileError(
                        f"parquet: corrupt bloom header for {'.'.join(path)}: {e}"
                    ) from e
                length = r.pos + (h.numBytes or 0)
            try:
                bf = BloomFilter.from_buffer(self._fetch_chunk(off, length))
            except ValueError as e:
                raise ParquetFileError(
                    f"parquet: corrupt bloom filter for {'.'.join(path)}: {e}"
                ) from e
            cache[(i, path)] = bf
            return bf
        raise ParquetFileError(f"parquet: column {'.'.join(path)} not in row group")

    def _bloom_excludes(self, i: int, normalized) -> bool:
        """True when some equality predicate's value is PROVABLY absent from
        row group i per its bloom filter (false-positive-only structure:
        never excludes a group that contains the value)."""
        from .filter import chunks_by_path
        from .stats import column_is_unsigned

        by_path = chunks_by_path(self.row_group(i))
        for path, leaf, op, _rv, vlo, vhi in normalized:
            if op == "==":
                if vlo is None or vlo != vhi:
                    continue
                probes = [vlo]
            elif op == "in":
                # exclusion needs EVERY member provably absent, so every
                # bracket must be exact ([] is handled by stats pruning)
                if not vlo or any(a != b for a, b in vlo):
                    continue
                probes = [a for a, _ in vlo]
            else:
                continue
            cc = by_path.get(path)
            if cc is None or not cc.meta_data.bloom_filter_offset:
                continue
            try:
                bf = self.read_bloom_filter(i, path)
            except ParquetFileError:
                continue  # corrupt filter: never exclude on it
            if bf is not None and all(
                not bf.might_contain(leaf.type, p, column_is_unsigned(leaf))
                for p in probes
            ):
                return True
        return False

    def prune_pages(self, i: int, filters) -> list[tuple[int, int]]:
        """Row ranges of row group i that may contain rows matching
        `filters`, proven by the page index — sorted disjoint [(start,
        stop)); [(0, num_rows)] when the file has no page index or nothing
        can be pruned, [] when the whole group is provably empty of
        matches."""
        from .filter import dnf_page_ranges, normalize_dnf

        dnf = normalize_dnf(self.schema, filters)
        num_rows = self.row_group(i).num_rows or 0
        paths = [p for conj in dnf for p, *_ in conj]
        indexes = self.read_page_index(i, columns=paths) if paths else {}
        return dnf_page_ranges(dnf, indexes, num_rows)

    def iter_rows(self, row_groups=None, raw: bool = False, filters=None):
        """Yield rows as dicts (returns an iterator). `raw=True` gives
        reference-style nested maps (no LIST/MAP unwrapping, bytes not
        decoded). `filters` is a flat list of (column, op, value) triples (a
        conjunction) or a list of LISTS of triples (an OR of conjunctions —
        pyarrow's DNF convention): row groups whose statistics/bloom/
        page-index exclude the predicate are skipped wholesale and the
        surviving rows are predicate-checked exactly."""
        if filters is None and row_groups is None and self.num_row_groups == 1:
            # single-group scan: hand back the group's list/generator with
            # no extra per-row generator hop (~10% of assembled-rows time)
            rows = self._iter_group_rows(0, raw)
            return iter(rows) if isinstance(rows, list) else rows
        return self._iter_rows_gen(row_groups, raw, filters)

    def _iter_rows_gen(self, row_groups, raw: bool, filters):
        dnf = None
        if filters is not None:
            from .filter import (
                FilterError,
                dnf_group_may_match,
                dnf_page_ranges,
                dnf_row_matches,
                normalize_dnf,
            )

            if raw:
                # row_matches compares in the converted domain (datetime,
                # Decimal, str); raw rows are wire-shaped (ints, undecoded
                # bytes, nested wrappers), so the predicate would silently
                # mismatch — mirror floor.Reader, which only prunes for the
                # unmarshal path
                raise FilterError("filters cannot be combined with raw=True")
            dnf = normalize_dnf(self.schema, filters)
        # Filter columns OUTSIDE the projection still evaluate: decode them
        # alongside the selection, predicate-check, then strip them from the
        # yielded rows (silently returning zero rows because the predicate
        # column was projected out is a correctness trap). Stripping is
        # LEAF-granular: each missing leaf is deleted at the shallowest
        # path component no selected leaf shares, so g.c vanishes from a
        # row that keeps g.b, and a whole unselected root vanishes outright.
        read_cols = None
        strips: list = []  # (parent path parts, key to pop)
        if dnf is not None and self._selected is not None:
            fpaths = {p for conj in dnf for p, *_ in conj}
            missing = fpaths - self._selected
            if missing:
                read_cols = list(self._selected | fpaths)
                for path in missing:
                    cut = 1
                    while cut < len(path) and any(
                        sel[:cut] == path[:cut] for sel in self._selected
                    ):
                        cut += 1
                    strips.append((path[: cut - 1], path[cut - 1]))
        indices = range(self.num_row_groups) if row_groups is None else row_groups
        for i in indices:
            if dnf is None:
                # no predicate: delegate the whole group (C-level yield from
                # the assembled list — no per-row Python frame)
                yield from self._iter_group_rows(i, raw)
                continue
            if not dnf_group_may_match(
                self.row_group(i), dnf, self._bloom_excludes, i
            ):
                continue
            # page index (when written): restrict row materialization to the
            # ranges whose pages may match — row assembly is the dominant
            # cost of a filtered scan, so pruned ranges never build rows
            ranges = None
            indexes = None
            try:
                # one parse covers both uses: range computation here and
                # selective page decode in _read_group_ranges. Filter columns
                # outside the projection still prune, so their index is
                # fetched alongside the selected columns'.
                indexes = self.read_page_index(i, columns=read_cols)
                if any(ci is not None for ci, _ in indexes.values()):
                    num_rows = self.row_group(i).num_rows or 0
                    ranges = dnf_page_ranges(dnf, indexes, num_rows)
                    if ranges == [(0, num_rows)]:
                        # nothing pruned: keep the unpruned fast paths
                        # (direct list / plain windows, no extra slicing)
                        ranges = None
            except ParquetFileError:
                ranges = None  # corrupt index: scan everything, stay correct
                indexes = None
            if ranges is not None and not ranges:
                continue
            yield from self._filtered_group_rows(
                i, raw, dnf, ranges, indexes, read_cols, strips
            )

    def _filtered_group_rows(
        self, i: int, raw: bool, dnf, ranges, indexes, read_cols, strips
    ):
        """One row group's rows surviving the residual predicate.

        The vectorized path: the decoded chunks compile into ONE boolean
        row mask (core/filter_vec.dnf_mask — per-leaf masks over the
        columnar buffers, AND within conjunctions, OR across them) and only
        matching rows ever materialize, windowed over the mask's True-runs.
        Shapes or value domains the mask pipeline cannot prove raise the
        typed VecFilterError and this falls back to the scalar per-row
        `row_matches` walk — identical output, the engine-ladder contract
        of assembly_vec (PQT_VEC_FILTER=0 forces the scalar oracle)."""
        from .filter import dnf_row_matches
        from .filter_vec import (
            VecFilterError,
            dnf_mask,
            group_row_count,
            masked_flat_columns,
            vec_filter_enabled,
        )

        chunks, sliced = self._decode_group_chunks(i, ranges, indexes, read_cols)
        if not chunks:
            return  # quarantined group (on_error='skip'), or empty selection
        mask = None
        if vec_filter_enabled() and vec_enabled():
            try:
                with timed_stage("assembly.filter") as el:
                    mask = dnf_mask(chunks, dnf, group_row_count(chunks))
                _metrics.observe("filter_mask_seconds", el.seconds)
            except VecFilterError:
                mask = None
        if mask is not None:
            kept = int(mask.sum())
            if kept:
                # rows assemble from the PROJECTION only: filter-only leaf
                # chunks never build row values, so the strip pass the
                # scalar path needs does not exist here
                row_chunks = (
                    chunks
                    if self._selected is None
                    else {p: cd for p, cd in chunks.items() if p in self._selected}
                )
                # flat schemas gather ONLY the kept rows (value boxing and
                # logical conversion scale with matches, not group size)
                flat = None
                try:
                    with stage("assemble"):
                        flat = masked_flat_columns(row_chunks, raw, mask)
                except VecFilterError:
                    flat = None
                if flat is not None:
                    bump("assemble_vec")
                    _metrics.inc(
                        "query_rows_filtered_total",
                        len(mask) - kept,
                        engine="vec",
                    )
                    names, columns, k = flat
                    if names and k:
                        yield from self._column_rows(names, columns, k)
                    return
                rc = None
                with stage("assemble"):
                    with _gc_paused():
                        rc = assemble_row_columns(self.schema, row_chunks, raw)
                if rc is not None and rc[2] == len(mask):
                    bump("assemble_vec")
                    _metrics.inc(
                        "query_rows_filtered_total",
                        len(mask) - kept,
                        engine="vec",
                    )
                    names, columns, _n = rc
                    if names:
                        yield from self._masked_rows(names, columns, mask)
                    return
            else:
                # the mask alone proved the group empty of matches: no rows
                # assemble under either engine, the filtering was vec's
                _metrics.inc(
                    "query_rows_filtered_total", len(mask), engine="vec"
                )
                return
            # row assembly couldn't prove the shape: the scalar walk below
            # decides (and raises its precise error on real inconsistency) —
            # the metric is counted THERE, never here too (one engine, one
            # count)
            mask = None
        evaluated = kept = 0
        try:
            for row in self._rows_from_chunks(chunks, raw, ranges, sliced):
                evaluated += 1
                if not dnf_row_matches(row, dnf):
                    continue
                kept += 1
                for parents, key in strips:
                    d = row
                    for part in parents:
                        d = d.get(part) if isinstance(d, dict) else None
                        if d is None:
                            break
                    if isinstance(d, dict):
                        d.pop(key, None)
                yield row
        finally:
            _metrics.inc(
                "query_rows_filtered_total", evaluated - kept, engine="scalar"
            )

    def _decode_group_chunks(self, i: int, ranges, indexes, columns):
        """(chunks, sliced) for one row group: selective page decode when
        the page index proves `ranges` (sorted disjoint row windows) cover
        few enough rows, else the full decode. sliced=True means the chunks
        hold exactly the ranges' rows."""
        chunks = None
        sliced = False
        if ranges is not None:
            try:
                chunks = self._read_group_ranges(i, ranges, indexes, columns)
            except ValueError:
                # inconsistent index, or a page shape the range decoder
                # doesn't cover (ChunkError/PageError/...): full decode
                # below stays correct and raises the precise error if the
                # file is genuinely corrupt
                chunks = None
            sliced = chunks is not None
            if sliced:
                bump("selective_page_decode")
        if chunks is None:
            chunks = self._read_row_group(i, columns, pack=False)
        return chunks, sliced

    def _iter_group_rows(
        self, i: int, raw: bool, ranges=None, indexes=None, columns=None
    ):
        """One row group's rows: a LIST for small vectorized shapes (callers
        iterate without an extra generator frame per row), a window-batched
        generator for large ones (bounds the live tracked-object count so
        cyclic GC passes stay cheap), or the streaming Dremel fallback.
        `ranges` (sorted disjoint [(start, stop)), from the page index)
        limits which rows materialize; when every selected column is flat
        and indexed, only the pages covering the ranges are even READ and
        decoded (selective page decode). The Dremel fallback ignores ranges
        (the caller's exact predicate check keeps the result correct)."""
        chunks, sliced = self._decode_group_chunks(i, ranges, indexes, columns)
        if not chunks:
            return []  # quarantined group (on_error='skip'), or empty selection
        return self._rows_from_chunks(chunks, raw, ranges, sliced)

    def _rows_from_chunks(self, chunks: dict, raw: bool, ranges=None, sliced=False):
        rc = None
        if vec_enabled():
            # the vectorized engine: level prefix scans -> offsets/validity
            # columns (core/assembly_vec.py). None when the scans cannot
            # prove the shape — or always when PQT_VEC_ASSEMBLY=0.
            with stage("assemble"):
                with _gc_paused():
                    rc = assemble_row_columns(self.schema, chunks, raw)
            if rc is not None:
                bump("assemble_vec")
        if rc is None:
            # per-row Dremel fallback: streams one row at a time (constant
            # memory) and raises precise errors on inconsistent level data
            bump("assemble_cursor")
            return _timed_rows(
                RecordAssembler(self.schema, chunks, raw=raw, engine="scalar")
            )
        names, columns, n = rc
        if not names or n == 0:
            return []
        if ranges is not None and not sliced:
            # full decode happened: restrict materialization to the ranges
            return self._ranged_rows(names, columns, ranges)
        if n <= _ASSEMBLE_WINDOW:
            with timed_stage("assembly.rows") as el, _gc_paused():
                rows = _zip_dict_rows(names, columns)
            _metrics.inc("assembly_rows_total", n, engine="vec")
            _metrics.observe("assembly_seconds", el.seconds)
            return rows
        return self._ranged_rows(names, columns, [(0, n)])

    def _read_group_ranges(
        self, i: int, ranges, indexes=None, columns=None
    ) -> dict | None:
        """Selective page decode of row group i restricted to `ranges`, or
        None when it doesn't apply (no/partial offset index, repeated
        columns, or ranges covering most rows — whole-chunk decode wins
        then). All returned chunks hold exactly the ranges' rows, aligned.
        `indexes` reuses an already-parsed page index for this group."""
        from .chunk import read_chunk_row_ranges

        rg = self.row_group(i)
        num_rows = rg.num_rows or 0
        covered = sum(e - s for s, e in ranges)
        if num_rows == 0 or covered * 4 > num_rows * 3:
            return None
        selected = list(self._selected_chunks(i, columns))
        if any(col.max_rep > 0 for _, _, col in selected):
            return None
        if indexes is None:
            indexes = self.read_page_index(i, columns=columns)
        out = {}
        for path, cc, col in selected:
            oi = indexes.get(path, (None, None))[1]
            if oi is None or not oi.page_locations:
                return None
            firsts = [loc.first_row_index for loc in oi.page_locations]
            if (
                any(not isinstance(x, int) for x in firsts)
                or firsts[0] != 0
                or any(b <= a for a, b in zip(firsts, firsts[1:]))
                or any(
                    not isinstance(loc.offset, int) or loc.offset <= 0
                    for loc in oi.page_locations
                )
            ):
                return None  # foreign/corrupt index: full decode
            out[path] = read_chunk_row_ranges(
                self._f,
                cc,
                col,
                oi,
                ranges,
                num_rows,
                validate_crc=self.validate_crc,
                alloc=self.alloc,
            )
        return out

    @staticmethod
    def _column_rows(names, columns, n):
        """Row dicts from already-gathered column value lists, windowed to
        bound live tracked objects like every other materialization path."""

        def windows():
            for s in range(0, n, _ASSEMBLE_WINDOW):
                e = min(s + _ASSEMBLE_WINDOW, n)
                with timed_stage("assembly.rows") as el, _gc_paused():
                    rows = _zip_dict_rows(names, [c[s:e] for c in columns])
                _metrics.inc("assembly_rows_total", e - s, engine="vec")
                _metrics.observe("assembly_seconds", el.seconds)
                yield rows

        return itertools.chain.from_iterable(windows())

    @staticmethod
    def _masked_rows(names, columns, mask):
        """Materialize only the rows a boolean mask keeps, windowed like
        _ranged_rows. One itertools.compress pass per window gathers
        arbitrary (even per-row fragmented) masks at C speed — a run-list
        gather would pay a Python window round trip PER RUN, which for a
        selective predicate over random data is one per kept row."""
        from itertools import compress

        from .assembly_vec import _materialize_spec

        n = len(mask)

        def windows():
            for s in range(0, n, _ASSEMBLE_WINDOW):
                e = min(s + _ASSEMBLE_WINDOW, n)
                wm = mask[s:e]
                k = int(wm.sum())
                if not k:
                    continue
                with timed_stage("assembly.rows") as el, _gc_paused():
                    if k == e - s:
                        cols = [slice_column(c, s, e) for c in columns]
                    else:
                        wml = wm.tolist()
                        cols = []
                        for c in columns:
                            wc = slice_column(c, s, e)
                            if isinstance(wc, tuple):
                                wc = _materialize_spec(wc)
                            cols.append(list(compress(wc, wml)))
                    rows = _zip_dict_rows(names, cols)
                _metrics.inc("assembly_rows_total", k, engine="vec")
                _metrics.observe("assembly_seconds", el.seconds)
                yield rows

        return itertools.chain.from_iterable(windows())

    @staticmethod
    def _ranged_rows(names, columns, ranges):
        # chain.from_iterable over window LISTS: the per-row next() is pure
        # C (no Python generator frame resumes per row — those cost more
        # than the dict build itself at multi-M rows/s); the Python frame
        # below only wakes once per 64Ki-row window
        def windows():
            for start, stop in ranges:
                for s in range(start, stop, _ASSEMBLE_WINDOW):
                    e = min(s + _ASSEMBLE_WINDOW, stop)
                    # build INSIDE the contexts, yield OUTSIDE them: the
                    # consumer must run with GC enabled and off the stage
                    # timer (a yield inside `with` would hold both open
                    # across arbitrary consumer code)
                    with timed_stage("assembly.rows") as el, _gc_paused():
                        rows = _zip_dict_rows(
                            names, [slice_column(c, s, e) for c in columns]
                        )
                    _metrics.inc("assembly_rows_total", e - s, engine="vec")
                    _metrics.observe("assembly_seconds", el.seconds)
                    yield rows

        return itertools.chain.from_iterable(windows())

    def to_arrow(
        self, row_groups=None, columns=None, filters=None, read_dictionary=None
    ):
        """Decoded columns as a pyarrow.Table. Flat leaves (numerics,
        booleans, strings/binary, FLBA) and canonical single-level LIST
        columns take zero-copy fast paths; every deeper shape — structs,
        MAPs, multi-level lists, list-of-struct, struct-of-list, legacy
        repeated groups/leaves — assembles through the vectorized
        Dremel-levels builder (core/arrow_nested.py), matching the
        reference's full nested read surface (reference schema.go:216-312,
        floor/reader.go:302-409). The reverse of write_column's arrow
        ingest: a pyarrow user can hand columns either way without a
        rewrite.

        `filters` mirrors pyarrow.parquet.read_table's: a flat list of
        (column, op, value) triples (a conjunction) or a list of lists
        (an OR of conjunctions). Row groups that statistics/bloom exclude
        are never decoded; surviving rows are filtered EXACTLY. Filter
        columns outside the projection still apply, then drop.

        `read_dictionary` (list of flat string/binary column names, like
        pyarrow's) returns those columns DICTIONARY-ENCODED
        (dictionary<int32, large_string>) — indices and the (small)
        dictionary pass through without materializing the strings. Chunks
        with PLAIN fallback pages decode plain; a column mixing both
        normalizes to plain across groups so the type stays uniform."""
        if filters is not None:
            return self._to_arrow_filtered(
                row_groups, columns, filters, read_dictionary
            )
        import pyarrow as pa

        from .arrow_nested import nested_arrow_type

        dict_paths = self._dict_paths(read_dictionary)
        indices = list(
            range(self.num_row_groups) if row_groups is None else row_groups
        )
        if not indices:
            # zero groups selected: a zero-ROW table with the selected
            # schema, so cross-file concatenation never hits a mismatch
            # (nested_arrow_type derives the same type every data branch
            # produces, fast paths included)
            sel = self._resolve_columns(columns) if columns else self._selected
            by_top: dict[str, list] = {}
            for leaf in self.schema.leaves:
                if sel is None or leaf.path in sel:
                    by_top.setdefault(leaf.path[0], []).append(leaf.path)
            def _empty_type(top_name):
                t = nested_arrow_type(pa, self.schema.column((top_name,)), sel)
                if (top_name,) in dict_paths:
                    return pa.dictionary(pa.int32(), t)
                return t
            return pa.table({
                top_name: pa.array([], type=_empty_type(top_name))
                for top_name in by_top
            })
        per_group: list[dict] = []
        names: list[str] | None = None
        for i in indices:
            chunks = self._read_row_group(
                i, columns, pack=False, dict_paths=dict_paths
            )
            if not chunks:
                continue  # quarantined group (on_error != 'raise')
            cols = self._arrow_group_cols(pa, chunks, dict_paths)
            if names is None:
                names = list(cols)
            per_group.append(cols)
        if names is None:
            names = []
        if not per_group:
            if indices:
                # every selected group was quarantined (on_error != 'raise'):
                # deliver the zero-row table WITH the selected schema, like
                # an empty row-group selection would
                return self.to_arrow(
                    row_groups=[], columns=columns, read_dictionary=read_dictionary
                )
            return pa.table({})
        arrays = []
        for name in names:
            parts = [g[name] for g in per_group]
            is_dict = [pa.types.is_dictionary(a.type) for a in parts]
            if any(is_dict) and not all(is_dict):
                # a group with PLAIN fallback pages decoded plain: the
                # column normalizes to plain so the chunked type is uniform
                parts = [
                    a.dictionary_decode() if pa.types.is_dictionary(a.type) else a
                    for a in parts
                ]
            arrays.append(pa.chunked_array(parts))
        return pa.table(dict(zip(names, arrays)))

    def _dict_paths(self, read_dictionary) -> frozenset:
        """The dictionary-preserving projection (read_dictionary=): flat
        BYTE_ARRAY tops only."""
        from ..meta.parquet_types import Type

        if not read_dictionary:
            return frozenset()
        wanted = set()
        for name in read_dictionary:
            path = (
                tuple(name.split(".")) if isinstance(name, str) else tuple(name)
            )
            try:
                leaf = self.schema.column(path)
            except Exception as e:
                raise ParquetFileError(
                    f"parquet: read_dictionary column {name!r} not in schema"
                ) from e
            if (
                len(path) == 1
                and leaf.is_leaf
                and leaf.max_rep == 0
                and leaf.type == Type.BYTE_ARRAY
            ):
                wanted.add(path)
        return frozenset(wanted)

    def _arrow_group_cols(self, pa, chunks: dict, dict_paths) -> dict:
        """{top-level name: pyarrow array} for one decoded row group — the
        per-group body of to_arrow, shared with the filtered fast path so
        a group's chunks decode exactly once however they were read."""
        from ..meta.parquet_types import Type
        from .arrow_nested import build_top_field, retype_leaf
        from .arrays import ByteArrayData

        def _fast_kind(paths):
            """'flat' | 'list' | 'nested' for one top-level field's leaves."""
            if len(paths) != 1:
                return "nested"
            path = paths[0]
            leaf = self.schema.column(path)
            if leaf.max_rep == 0 and len(path) == 1:
                return "flat"
            if self._is_canonical_list(path, leaf) and leaf.type not in (
                Type.FIXED_LEN_BYTE_ARRAY, Type.INT96,
            ):
                return "list"
            return "nested"

        by_top: dict[str, dict] = {}
        for path, cd in chunks.items():
            by_top.setdefault(path[0], {})[path] = cd
        cols = {}
        for top_name, sub in by_top.items():
            kind = _fast_kind(list(sub))
            if kind == "nested":
                cols[top_name] = build_top_field(pa, self.schema, top_name, sub)
                continue
            (path, cd), = sub.items()
            leaf = self.schema.column(path)
            if kind == "list":
                cols[top_name] = self._arrow_list_column(pa, path, leaf, cd)
                continue
            if cd.indices is not None and isinstance(
                cd.dictionary, ByteArrayData
            ):
                cols[top_name] = self._arrow_dictionary_column(pa, leaf, cd)
                continue
            mask = None
            if cd.def_levels is not None and leaf.max_def > 0:
                valid = np.asarray(cd.def_levels) == leaf.max_def
                if not valid.all():
                    mask = ~valid
            values = cd.values
            if isinstance(values, ByteArrayData):
                atype = (
                    pa.large_string() if leaf.is_string() else pa.large_binary()
                )
                offsets = np.ascontiguousarray(values.offsets, dtype=np.int64)
                data = values.data
                if mask is not None:
                    # expand offsets to row positions: null rows repeat
                    # the running offset (zero-length slot)
                    offsets = _scatter_byte_offsets(valid, offsets)
                n = len(offsets) - 1
                bufs = [
                    None
                    if mask is None
                    else pa.py_buffer(
                        np.packbits(valid, bitorder="little").tobytes()
                    ),
                    pa.py_buffer(offsets),
                    pa.py_buffer(data),
                ]
                arr = pa.Array.from_buffers(
                    atype, n, bufs,
                    null_count=int(mask.sum()) if mask is not None else 0,
                )
            else:
                np_vals = np.asarray(values)
                if np_vals.ndim == 2:  # FLBA / INT96 rows
                    atype = pa.binary(np_vals.shape[1])
                    if mask is None:
                        flat = np.ascontiguousarray(np_vals).reshape(-1)
                        arr = pa.Array.from_buffers(
                            atype, len(np_vals), [None, pa.py_buffer(flat)]
                        )
                    else:
                        # values are DENSE (non-null cells only):
                        # scatter them to their row positions
                        it = iter(np_vals)
                        rows = [
                            bytes(next(it)) if ok else None for ok in valid
                        ]
                        arr = pa.array(rows, atype)
                elif mask is not None:
                    # dense non-null cells scatter to row positions
                    expanded = np.zeros(len(valid), np_vals.dtype)
                    expanded[valid] = np_vals
                    arr = pa.array(expanded, mask=mask)
                else:
                    arr = pa.array(np_vals)
            cols[path[0]] = retype_leaf(pa, leaf, arr)
        return cols

    def _arrow_dictionary_column(self, pa, leaf, cd):
        """A dictionary-preserved chunk -> pyarrow DictionaryArray: the
        (small) dictionary transfers zero-copy into large_string/
        large_binary, indices scatter to row positions with validity from
        the definition levels (read_dictionary= lane)."""
        d = cd.dictionary
        offs = np.ascontiguousarray(d.offsets, dtype=np.int64)
        dict_arr = pa.Array.from_buffers(
            pa.large_string() if leaf.is_string() else pa.large_binary(),
            len(d),
            [None, pa.py_buffer(offs), pa.py_buffer(d.data)],
        )
        n = cd.num_values
        idx = np.asarray(cd.indices, dtype=np.int32)
        valid = None
        if cd.def_levels is not None and leaf.max_def > 0:
            v = np.asarray(cd.def_levels) == leaf.max_def
            if not v.all():
                valid = v
        if valid is None:
            ind = pa.array(idx)
        else:
            expanded = np.zeros(n, dtype=np.int32)
            expanded[valid] = idx
            ind = pa.array(expanded, mask=~valid)
        return pa.DictionaryArray.from_arrays(ind, dict_arr)

    def _to_arrow_filtered(self, row_groups, columns, filters, read_dictionary=None):
        """Pruned + exactly-filtered columnar read (to_arrow's filters=).

        The row mask evaluates over a SEPARATE read of just the filter
        leaves, so a predicate on a projected-out column — even a nested
        sibling leaf — filters without leaking into the output schema
        (leaf-granular, like iter_rows' strips).

        Fast path: when the vectorized mask pipeline covers every predicate
        (core/filter_vec, arrow null semantics), each group's mask compiles
        straight off the decoded filter-leaf chunks and applies as ONE
        buffer-level take (`table.filter`) — no combine_chunks copies, no
        per-row work, record batches stream zero-copy into the IPC writer.
        VecFilterError falls back to the pyarrow-compute path below."""
        import pyarrow as pa
        import pyarrow.compute as pc

        from .filter import FilterError, dnf_group_may_match, normalize_dnf

        dnf = normalize_dnf(self.schema, filters)
        indices = [
            i
            for i in (
                range(self.num_row_groups) if row_groups is None else row_groups
            )
            if dnf_group_may_match(self.row_group(i), dnf, self._bloom_excludes, i)
        ]
        vacuous = not dnf or any(not conj for conj in dnf)
        if indices and not vacuous and self.on_error == "raise":
            out = self._to_arrow_vec_filtered(
                pa, dnf, indices, columns, read_dictionary
            )
            if out is not None:
                return out
        # flat top-level filter columns already in the projection evaluate
        # straight off `table`; only projected-out or nested paths pay a
        # second (filter-leaves-only) read
        sel = self._resolve_columns(columns) if columns else self._selected
        fpaths = sorted({p for conj in dnf for p, *_ in conj})
        extra = [
            p
            for p in fpaths
            if len(p) > 1 or (sel is not None and p not in sel)
        ]
        ftab = None
        if extra and not vacuous and self.on_error != "raise":
            # Quarantine decisions depend on which columns a read touches,
            # so the projection read and the filter-leaves read can drop
            # DIFFERENT groups (a corrupt chunk outside one projection) —
            # misaligned row masks below would escape as a raw pyarrow
            # length error. Read both sides group-by-group, keep only groups
            # BOTH deliver in full, and concatenate the kept per-group
            # tables directly (each group decodes exactly once, same as the
            # bulk read — to_arrow iterates per group internally anyway).
            kept_t, kept_f = [], []
            for i in indices:
                expect = self.row_group(i).num_rows or 0
                t_i = self.to_arrow(
                    row_groups=[i], columns=columns,
                    read_dictionary=read_dictionary,
                )
                if t_i.num_rows != expect:
                    continue  # group already dropped: skip the filter read
                f_i = self.to_arrow(row_groups=[i], columns=extra)
                if f_i.num_rows == expect:
                    kept_t.append(t_i)
                    kept_f.append(f_i)
            table = _concat_group_tables(pa, kept_t)
            if table is None:
                table = self.to_arrow(
                    row_groups=[], columns=columns,
                    read_dictionary=read_dictionary,
                )
            ftab = _concat_group_tables(pa, kept_f)
        else:
            table = self.to_arrow(
                row_groups=indices, columns=columns,
                read_dictionary=read_dictionary,
            )
        if vacuous or table.num_rows == 0:
            return table  # an empty conjunction is vacuously true
        if ftab is None and extra:
            ftab = self.to_arrow(row_groups=indices, columns=extra)

        # A column referenced in N DNF conjunctions must combine its chunks
        # once, not N times (combine_chunks copies the whole column); the
        # filter_combine_chunks counter pins the memoization in tests.
        combined: dict = {}
        leaf_cache: dict = {}

        def base_col(path):
            key = (path in extra or len(path) > 1, path[0])
            base = combined.get(key)
            if base is None:
                src = ftab if key[0] else table
                base = combined[key] = src.column(path[0]).combine_chunks()
                bump("filter_combine_chunks")
            return base

        def leaf_col(path):
            arr = leaf_cache.get(path)
            if arr is not None:
                return arr
            arr = base_col(path)
            if len(path) > 1:
                arr = pc.struct_field(arr, list(path[1:]))
            leaf_cache[path] = arr
            return arr

        try:
            mask = None
            for conj in dnf:
                m = None
                for path, _leaf, op, rv, _lo, _hi in conj:
                    if op == "contains":
                        # the LIST wrapper itself carries the predicate: its
                        # leaf path addresses the element for stats, but the
                        # arrow column is the top-level list
                        p = self._arrow_contains_mask(pa, pc, base_col(path), rv)
                        m = p if m is None else pc.and_kleene(m, p)
                        continue
                    arr = leaf_col(path)
                    if op == "is_null":
                        p = pc.is_null(arr)
                    elif op == "not_null":
                        p = pc.is_valid(arr)
                    elif op == "in":
                        p = pc.is_in(arr, value_set=pa.array(list(rv)))
                    elif op == "not_in":
                        p = pc.invert(
                            pc.is_in(arr, value_set=pa.array(list(rv)))
                        )
                    else:
                        p = {
                            "==": pc.equal, "!=": pc.not_equal,
                            "<": pc.less, "<=": pc.less_equal,
                            ">": pc.greater, ">=": pc.greater_equal,
                        }[op](arr, rv)
                    m = p if m is None else pc.and_kleene(m, p)
                mask = m if mask is None else pc.or_kleene(mask, m)
        except (pa.lib.ArrowInvalid, pa.lib.ArrowNotImplementedError,
                TypeError) as err:  # literal pyarrow cannot compare
            raise FilterError(
                f"filter: cannot evaluate over arrow columns: {err}"
            ) from err
        # Null handling mirrors pyarrow.parquet.read_table exactly: a null
        # comparison yields a null mask entry (dropped), EXCEPT not_in —
        # pc.is_in maps null to false, so invert KEEPS null rows (pyarrow's
        # convention). iter_rows' row predicate instead fails every op on
        # null (SQL-ish); the difference is pinned by tests.
        out = table.filter(mask)
        _metrics.inc(
            "query_rows_filtered_total",
            table.num_rows - out.num_rows,
            engine="arrow",
        )
        return out

    def _arrow_contains_mask(self, pa, pc, col, rv):
        """Row mask for a ('tags', 'contains', x) predicate over an arrow
        LIST column: one vectorized equality over the FLATTENED elements,
        lifted to rows through list_parent_indices — null lists contribute
        no elements and null elements compare null, so neither matches
        (identical to the scalar walk and the chunk-level mask)."""
        value = rv
        t = col.type
        if isinstance(rv, (bytes, bytearray)) and (
            pa.types.is_list(t) or pa.types.is_large_list(t)
        ) and (
            pa.types.is_string(t.value_type)
            or pa.types.is_large_string(t.value_type)
        ):
            # string element leaves coerce to bytes in the filter domain;
            # the arrow column compares in str space
            value = bytes(rv).decode("utf-8", errors="replace")
        flat = pc.list_flatten(col)
        parents = pc.list_parent_indices(col)
        em = pc.fill_null(pc.equal(flat, value), False)
        if isinstance(em, pa.ChunkedArray):
            em = em.combine_chunks()
        if isinstance(parents, pa.ChunkedArray):
            parents = parents.combine_chunks()
        hits = np.asarray(parents)[np.asarray(em)]
        m = np.zeros(len(col), dtype=bool)
        m[hits] = True
        return pa.array(m)

    def _to_arrow_vec_filtered(self, pa, dnf, indices, columns, read_dictionary):
        """The zero-copy filtered-read fast path: per group, the residual
        mask compiles off the decoded filter-leaf chunks (core/filter_vec,
        arrow null semantics so both paths stay value-identical) and
        applies as ONE buffer-level take (`Table.filter`) — no
        combine_chunks copies, no per-row predicate work. Returns None when
        the mask pipeline declines any predicate (VecFilterError), letting
        the pyarrow-compute path decide."""
        from .filter_vec import (
            VecFilterError,
            dnf_mask,
            group_row_count,
            vec_filter_enabled,
        )

        if not vec_filter_enabled() or not vec_enabled():
            return None
        fcols = {p for conj in dnf for p, *_ in conj}
        sel = self._resolve_columns(columns) if columns else self._selected
        # ONE decode per group covers projection AND filter leaves; the
        # mask compiles off the same chunks the table is built from
        read_cols = None if sel is None else sorted(sel | fcols)
        dict_paths = self._dict_paths(read_dictionary)
        parts = []
        filtered = 0
        try:
            for i in indices:
                chunks = self._read_row_group(
                    i, read_cols, pack=False, dict_paths=dict_paths
                )
                if not chunks:
                    raise VecFilterError("filter_vec: group undecodable")
                n_rows = group_row_count(chunks)
                with timed_stage("assembly.filter") as el:
                    mask = dnf_mask(chunks, dnf, n_rows, null_mode="arrow")
                _metrics.observe("filter_mask_seconds", el.seconds)
                kept = int(mask.sum())
                filtered += n_rows - kept
                if not kept:
                    continue  # the whole group drops: never build its table
                proj = (
                    chunks
                    if sel is None
                    else {p: cd for p, cd in chunks.items() if p in sel}
                )
                t_i = pa.table(self._arrow_group_cols(pa, proj, dict_paths))
                if t_i.num_rows != n_rows:
                    raise VecFilterError("filter_vec: projection row drift")
                parts.append(
                    t_i if kept == n_rows else t_i.filter(pa.array(mask))
                )
        except VecFilterError:
            return None
        _metrics.inc("query_rows_filtered_total", filtered, engine="vec")
        table = _concat_group_tables(pa, parts)
        if table is None:
            return self.to_arrow(
                row_groups=[], columns=columns, read_dictionary=read_dictionary
            )
        return table

    def _is_canonical_list(self, path, leaf) -> bool:
        """True for the one list shape _arrow_list_column's level math
        covers: top group > repeated mid group > element leaf, with no other
        optional layer (anything else — e.g. an optional group whose child
        is a bare repeated leaf — has different level semantics and must
        take the nested-deeper error, not silently corrupt)."""
        from ..meta.parquet_types import FieldRepetitionType

        if len(path) != 3 or leaf.max_rep != 1:
            return False
        top = self.schema.column((path[0],))
        mid = next((c for c in top.children if c.name == path[1]), None)
        if (
            mid is None
            or mid.repetition != FieldRepetitionType.REPEATED
            # exactly ONE element leaf: a legacy list-of-STRUCT repeated
            # group has several, and collapsing them to one column would
            # silently drop fields
            or len(mid.children) != 1
            or mid.children[0].path != leaf.path
        ):
            return False
        t = 1 if top.repetition == FieldRepetitionType.OPTIONAL else 0
        e = 1 if leaf.repetition == FieldRepetitionType.OPTIONAL else 0
        return leaf.max_def == t + 1 + e

    def _arrow_list_column(self, pa, path, leaf, cd):
        """One canonical LIST column chunk -> pyarrow LargeListArray: row
        lengths and validity from the levels (the same derivation as ragged
        device batches), element array from the dense non-null cells."""
        from ..meta.parquet_types import FieldRepetitionType, Type
        from .arrow_nested import retype_leaf
        from .arrays import ByteArrayData

        top = self.schema.column((path[0],))
        t = 1 if top.repetition == FieldRepetitionType.OPTIONAL else 0
        n = cd.num_values
        rl = (
            np.asarray(cd.rep_levels)
            if cd.rep_levels is not None
            else np.zeros(n, dtype=np.uint16)
        )
        dl = (
            np.asarray(cd.def_levels)
            if cd.def_levels is not None
            else np.full(n, leaf.max_def, dtype=np.uint16)
        )
        starts = np.nonzero(rl == 0)[0]
        slot = dl >= t + 1  # level entries that denote a list ELEMENT
        elem_valid = (dl == leaf.max_def)[slot]
        lengths = (
            np.add.reduceat(slot.astype(np.int64), starts)
            if len(starts)
            else np.zeros(0, dtype=np.int64)
        )
        row_null = (dl[starts] < t) if t else np.zeros(len(starts), dtype=bool)
        n_slots = int(slot.sum())
        values = cd.values
        if isinstance(values, ByteArrayData):
            etype = pa.large_string() if leaf.is_string() else pa.large_binary()
            if elem_valid.all():
                offs = np.ascontiguousarray(values.offsets, dtype=np.int64)
                elem = pa.Array.from_buffers(
                    etype, n_slots,
                    [None, pa.py_buffer(offs), pa.py_buffer(values.data)],
                )
            else:
                offs = _scatter_byte_offsets(elem_valid, values.offsets)
                elem = pa.Array.from_buffers(
                    etype, n_slots,
                    [
                        pa.py_buffer(
                            np.packbits(elem_valid, bitorder="little").tobytes()
                        ),
                        pa.py_buffer(offs),
                        pa.py_buffer(values.data),
                    ],
                    null_count=int((~elem_valid).sum()),
                )
        else:
            npv = np.asarray(values)
            if npv.ndim != 1 or leaf.type in (
                Type.FIXED_LEN_BYTE_ARRAY, Type.INT96,
            ):
                raise ParquetFileError(
                    f"parquet: to_arrow does not cover fixed-width elements "
                    f"inside lists ({'.'.join(path)}); use iter_rows"
                )
            if elem_valid.all():
                elem = pa.array(npv)
            else:
                expanded = np.zeros(n_slots, dtype=npv.dtype)
                expanded[elem_valid] = npv
                elem = pa.array(expanded, mask=~elem_valid)
        elem = retype_leaf(pa, leaf, elem)
        offsets = np.zeros(len(lengths) + 1, dtype=np.int64)
        np.cumsum(lengths, out=offsets[1:])
        if row_null.any():
            # a null offset at i marks list i null; the final offset (the
            # appended False) must stay valid
            offsets_pa = pa.array(
                offsets, pa.int64(), mask=np.append(row_null, False)
            )
        else:
            offsets_pa = pa.array(offsets, pa.int64())
        return pa.LargeListArray.from_arrays(offsets_pa, elem)

    def iter_row_groups(self, columns=None):
        for i in range(self.num_row_groups):
            yield self.read_row_group(i, columns=columns)

    def __iter__(self):
        """Iterating the reader yields rows — the `for reader.NextRow()` loop
        of the reference (file_reader.go:258) as a Python iterator."""
        return self.iter_rows()

    # -- lifecycle -------------------------------------------------------------

    @classmethod
    def open_metadata(cls, path, footer_cache=None) -> FileMetaData:
        """Parse ONLY the footer of `path` — no data pages are touched and
        no reader object (or open handle) survives the call. The cheap
        multi-file planning primitive: a dataset scanning a thousand-file
        glob footers every file once here, then opens per-unit readers
        with `metadata=` so the footer never re-parses. `footer_cache` (an
        io.cache.FooterCache) makes the parse once-per-file-GENERATION: a
        warm hit performs zero source reads; staleness is checked against
        the file's (size, mtime).

        `path` may be an http(s):// URL (io.remote.HttpSource under the
        installed resilience policy): the footer cache then validates
        against the object's (size, ETag) generation — a warm remote
        re-plan costs one HEAD and zero body bytes per file."""
        if isinstance(path, str) and path.startswith(("http://", "https://")):
            from ..io.source import open_source

            src, owns = open_source(path)
            try:
                gen = src.generation()
                if footer_cache is not None:
                    meta = footer_cache.get(path, sig=gen)
                    if meta is not None:
                        return meta
                meta = read_file_metadata(SourceFile(src))
                if footer_cache is not None:
                    footer_cache.put(path, meta, sig=gen)
                return meta
            finally:
                if owns:
                    src.close()
        if footer_cache is not None:
            meta = footer_cache.get(path)
            if meta is not None:
                return meta
        from ..io.source import LocalFileSource

        with LocalFileSource(path) as src:
            meta = read_file_metadata(SourceFile(src))
        if footer_cache is not None:
            footer_cache.put(path, meta)
        return meta

    @classmethod
    def open_many(cls, paths, columns=None, **options) -> "list[FileReader]":
        """Open several files at once (footer parse only — FileReader's
        constructor never touches data pages). All-or-nothing: if any open
        fails, the already-opened readers are closed before the error
        propagates, so no handles leak. Every option forwards to each
        reader (`on_error=`, `validate_crc=`, ...)."""
        readers: list[FileReader] = []
        try:
            for p in paths:
                readers.append(cls(p, columns=columns, **options))
        except BaseException:
            for r in readers:
                r.close()
            raise
        return readers

    def close(self) -> None:
        """Release the underlying source when this reader owns it (paths,
        bytes). Idempotent: the dataset layer's lazy open/close churn (and
        `with` blocks wrapped in error paths) may close the same reader
        more than once. Caller-provided sources/file objects stay open —
        their lifetime belongs to the caller."""
        if self._owns_file:
            self._source.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

"""FileReader: the low-level public read API.

Equivalent of the reference's FileReader (reference: file_reader.go:15-27
type, :32-63 ctor, :186-207 row-group seek/skip, :258-272 NextRow), redesigned
column-first: the primary read unit is a row group's worth of decoded column
arrays (`read_row_group`), which is what the TPU pipeline consumes; row
iteration (`iter_rows`) is record assembly layered on top.

Options mirror the reference's functional options (file_reader.go:89-149):
column projection, CRC validation, memory ceiling, pre-parsed metadata, and —
new here — decoder backend selection (host NumPy vs TPU kernels), the
WithDecoderBackend(TPU) of the north star.
"""

from __future__ import annotations

import io
from pathlib import Path

from ..meta.file_meta import ParquetFileError, read_file_metadata
from ..meta.parquet_types import FileMetaData, RowGroup
from .alloc import AllocTracker
from .assembly import RecordAssembler, fast_flat_rows
from .chunk import ChunkData, read_chunk
from .schema import Schema
from ..utils.trace import stage

__all__ = ["FileReader"]


def _timed_rows(assembler):
    """Stream rows from the recursive assembler, billing per-row time to the
    'assemble' stage without materializing the row group."""
    it = iter(assembler)
    while True:
        with stage("assemble"):
            try:
                row = next(it)
            except StopIteration:
                return
        yield row


class FileReader:
    """Reads Parquet files: footer metadata, row groups, records.

    Usage:
        with FileReader("file.parquet") as r:
            cols = r.read_row_group(0)          # columnar (dict path -> ChunkData)
            for row in r.iter_rows():           # assembled records
                ...
    """

    def __init__(
        self,
        source,
        columns=None,
        *,
        validate_crc: bool = False,
        max_memory: int | None = None,
        metadata: FileMetaData | None = None,
        backend: str = "host",
    ):
        if isinstance(source, (str, Path)):
            self._f = open(source, "rb")
            self._owns_file = True
        else:
            self._f = source
            self._owns_file = False
        try:
            self.metadata = (
                metadata if metadata is not None else read_file_metadata(self._f)
            )
            self.schema = Schema.from_thrift(self.metadata.schema)
            self.validate_crc = validate_crc
            self.alloc = AllocTracker(max_memory) if max_memory else None
            self.backend = backend
            self._selected = self._resolve_columns(columns)
        except BaseException:
            if self._owns_file:
                self._f.close()
            raise

    # -- properties ------------------------------------------------------------

    @property
    def num_rows(self) -> int:
        return self.metadata.num_rows or 0

    @property
    def num_row_groups(self) -> int:
        return len(self.metadata.row_groups or [])

    @property
    def created_by(self) -> str | None:
        return self.metadata.created_by

    @property
    def key_value_metadata(self) -> dict[str, str | None]:
        return {
            kv.key: kv.value for kv in (self.metadata.key_value_metadata or [])
        }

    def row_group(self, i: int) -> RowGroup:
        groups = self.metadata.row_groups or []
        if not 0 <= i < len(groups):
            raise IndexError(f"row group {i} out of range (file has {len(groups)})")
        return groups[i]

    # -- column selection (reference: file_reader.go SetSelectedColumns, schema.go:347-367)

    def _resolve_columns(self, columns):
        if columns is None:
            return None
        selected = set()
        for c in columns:
            path = tuple(c.split(".")) if isinstance(c, str) else tuple(c)
            # select all leaves under the prefix
            hits = [
                leaf.path
                for leaf in self.schema.leaves
                if leaf.path[: len(path)] == path
            ]
            if not hits:
                raise ParquetFileError(f"parquet: selected column {c!r} not in schema")
            selected.update(hits)
        return selected

    def set_selected_columns(self, *columns) -> None:
        self._selected = self._resolve_columns(columns if columns else None)

    # -- columnar reads --------------------------------------------------------

    def read_row_group(self, i: int, columns=None) -> dict[tuple, ChunkData]:
        """Decode one row group into {leaf path: ChunkData}.

        On the TPU backend all selected chunks are *planned* first (host
        prescan + async device dispatch), then finalized — every chunk's
        device work is in flight before the first fetch blocks (JAX async
        dispatch over the host<->device link)."""
        if self.backend == "tpu":
            plans = self._plan_row_group(i, columns)
            return {path: plan.finalize() for path, plan in plans.items()}
        out: dict[tuple, ChunkData] = {}
        for path, cc, column in self._selected_chunks(i, columns):
            out[path] = read_chunk(
                self._f, cc, column, validate_crc=self.validate_crc, alloc=self.alloc
            )
        return out

    def read_row_group_device(self, i: int, columns=None):
        """Decode one row group straight into device memory (HBM).

        The TPU-native delivery point: returns {leaf path: DeviceColumn} whose
        value arrays are jax arrays resident on the accelerator — encoded
        bytes go up, decoded columns never come back down. Works regardless
        of the reader's configured backend."""
        plans = self._plan_row_group(i, columns)
        return {path: plan.device_column() for path, plan in plans.items()}

    def _plan_row_group(self, i: int, columns=None):
        from ..kernels.pipeline import plan_chunk_tpu

        plans = {}
        for path, cc, column in self._selected_chunks(i, columns):
            plans[path] = plan_chunk_tpu(
                self._f, cc, column, validate_crc=self.validate_crc, alloc=self.alloc
            )
        return plans

    def _selected_chunks(self, i: int, columns=None):
        """Yield (path, ColumnChunk, Column) for the selected leaves of group i."""
        rg = self.row_group(i)
        selected = self._resolve_columns(columns) if columns else self._selected
        if self.alloc is not None:
            self.alloc.release()
        for cc in rg.columns or []:
            md = cc.meta_data
            if md is None:
                raise ParquetFileError("parquet: column chunk without metadata")
            path = tuple(md.path_in_schema or [])
            if selected is not None and path not in selected:
                continue  # skipChunk (reference: chunk_reader.go:271)
            yield path, cc, self.schema.column(path)

    # -- record iteration ------------------------------------------------------

    def iter_rows(self, row_groups=None, raw: bool = False):
        """Yield rows as dicts. `raw=True` gives reference-style nested maps
        (no LIST/MAP unwrapping, bytes not decoded)."""
        indices = range(self.num_row_groups) if row_groups is None else row_groups
        for i in indices:
            chunks = self.read_row_group(i)
            with stage("assemble"):
                rows = fast_flat_rows(chunks, raw)
            if rows is not None:
                yield from rows
            else:
                # Nested fallback streams one row at a time (constant memory);
                # the timing wrapper keeps the 'assemble' stage accurate.
                yield from _timed_rows(RecordAssembler(self.schema, chunks, raw=raw))

    def iter_row_groups(self, columns=None):
        for i in range(self.num_row_groups):
            yield self.read_row_group(i, columns=columns)

    def __iter__(self):
        """Iterating the reader yields rows — the `for reader.NextRow()` loop
        of the reference (file_reader.go:258) as a Python iterator."""
        return self.iter_rows()

    # -- lifecycle -------------------------------------------------------------

    def close(self) -> None:
        if self._owns_file:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

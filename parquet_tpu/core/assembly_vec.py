"""Vectorized Dremel record assembly: level prefix scans -> offsets/validity
-> rows by batched slicing.

The scalar walk in core/assembly.py (RecordAssembler) rebuilds nested rows
one cursor step per level entry — ~10 us per element of pure interpreter
dispatch. This module is the data-parallel formulation the rep/def-level
model admits (PAPER.md; reference schema.go:88-312): whole-column prefix
scans over each leaf's level streams — record boundaries via rep == 0
(ops/levels.rows_from_rep), per-depth element offsets via one prefix sum
over the element-start mask gathered at slot boundaries, null masks from
each slot's first def level (ops/levels.validity_from_def) — compute, in
bulk numpy, the offset and null-mask arrays of every LIST/MAP/struct
nesting depth: an Arrow-style offsets+validity intermediate. Rows then
materialize from it by batched slicing (_native_ext.rows_from_slices /
dict_rows), never touching values row by row. ops/levels.slot_ids and
ops/levels.list_layout are the same scans as standalone primitives (and
the contract the device kernel mirrors).

The intermediate representation (IR) is a small tree mirroring the schema:

  LeafVec    one entry per slot: dense chunk values + a per-slot valid mask
  ListVec    offsets int64[n+1] + null mask over slots + element child
             (kind: "list" = annotated LIST to unwrap, "map" = annotated
             MAP -> dict, "repeated" = wire-shape repeated field)
  StructVec  named children at shared slot granularity + null mask

Three build modes share one recursion (the sel/slot_of stream filtering of
core/arrow_nested.py, which now consumes this IR for to_arrow — the same
scan feeds rows and Arrow, and the Arrow handoff is zero-copy at the
buffer level):

  "rows"   ergonomic dispatch: LIST -> list, MAP -> dict, logical
           conversions — matches pyarrow to_pylist
  "raw"    wire shape: no unwrapping, bytes stay bytes — matches the
           reference's NextRow
  "arrow"  arrow_nested's dispatch (2-level legacy lists stay structs,
           MAP needs both key and value selected)

Engine selection: the reader (and RecordAssembler's iterator facade) uses
this engine by default; PQT_VEC_ASSEMBLY=0 forces the scalar walk — the
fallback for shapes the scans cannot prove, and the differential-test
oracle. Structural inconsistencies the scans detect cheaply raise the same
typed AssemblyError as the scalar walk; anything unprovable falls back to
the walk, which raises the precise per-row error (or proves the data fine).

kernels/device_ops.list_layout_device is the same per-depth scan as a
jittable XLA program, so device-decoded level streams can assemble into
offsets/validity without a host round-trip.
"""

from __future__ import annotations

import os

import numpy as np

from ..meta.parquet_types import ConvertedType, FieldRepetitionType
from ..ops.levels import rows_from_rep, validity_from_def
from .arrays import ByteArrayData, _ext
from .assembly import AssemblyError, _leaf_python_values, logical_kind

__all__ = [
    "vec_enabled",
    "build_field_vec",
    "assemble_row_columns",
    "assemble_rows",
    "LeafVec",
    "ListVec",
    "StructVec",
    "VecStructureError",
    "slice_column",
]


def vec_enabled() -> bool:
    """The engine-selection knob: PQT_VEC_ASSEMBLY=0 forces the scalar
    cursor walk (the differential oracle) everywhere the vectorized engine
    would otherwise run."""
    return os.environ.get("PQT_VEC_ASSEMBLY", "1") != "0"


class VecStructureError(Exception):
    """Internal: the level streams describe a structure the vectorized scans
    cannot prove (leaves disagree, stream opens mid-slot, missing level
    arrays). Row callers fall back to the scalar walk — which raises the
    precise typed error if the data really is inconsistent; to_arrow wraps
    it into ParquetFileError."""

    pass


# dtype chars the C dict_rows array-elems path accepts, with the itemsize it
# assumes for each (mirrors pyext.c's format check so ineligible arrays fall
# back to the tolist path instead of raising)
_ARR_ELEM_SIZES = {
    "b": 1, "B": 1, "?": 1, "h": 2, "H": 2, "i": 4, "I": 4, "f": 4,
    "l": 8, "L": 8, "q": 8, "Q": 8, "d": 8,
}


# -- the offsets/validity IR ----------------------------------------------------


class LeafVec:
    """One leaf at some slot granularity: slot i holds dense value
    k0 + (number of valid slots before i) when valid, else None."""

    __slots__ = ("node", "chunk", "valid", "k0", "nv", "n")

    def __init__(self, node, chunk, valid, k0: int, nv: int, n: int):
        self.node = node
        self.chunk = chunk
        self.valid = valid  # bool[n] | None (None = every slot present)
        self.k0 = k0  # first dense value index in chunk.values
        self.nv = nv  # dense value count over these slots
        self.n = n

    def null_count(self) -> int:
        return 0 if self.valid is None else self.n - self.nv


class ListVec:
    """A repeated depth: slot i's elements are child slots
    [offsets[i], offsets[i+1]); null_mask marks slots that are None (null
    wrapper) rather than empty."""

    __slots__ = ("node", "rep_node", "offsets", "null_mask", "child", "kind", "n")

    def __init__(self, node, rep_node, offsets, null_mask, child, kind: str):
        self.node = node  # the field this materializes as (wrapper or rep node)
        self.rep_node = rep_node  # the REPEATED schema node that was expanded
        self.offsets = offsets  # int64[n + 1]
        self.null_mask = null_mask  # uint8[n] | None (1 = slot is None)
        self.child = child
        self.kind = kind  # "list" | "map" | "repeated"
        self.n = len(offsets) - 1


class StructVec:
    """A group at some slot granularity: children share the slot space."""

    __slots__ = ("node", "names", "children", "null_mask", "n")

    def __init__(self, node, names, children, null_mask, n: int):
        self.node = node
        self.names = names
        self.children = children
        self.null_mask = null_mask  # uint8[n] | None
        self.n = n


# -- per-leaf stream state ------------------------------------------------------


class _Stream:
    __slots__ = ("leaf", "chunk", "rl", "dl", "n")

    def __init__(self, leaf, chunk):
        self.leaf = leaf
        self.chunk = chunk
        self.n = chunk.num_values
        rl = chunk.rep_levels
        dl = chunk.def_levels
        # widen PackedLevels / uint16 once; all scans below are comparisons
        self.rl = None if rl is None else np.asarray(rl)
        self.dl = None if dl is None else np.asarray(dl)


def _is_list_node(node, mode: str) -> bool:
    ct = node.converted_type
    if mode == "arrow":
        # must match arrow_nested.nested_arrow_type's dispatch exactly
        # (converted type only), or the built array and the declared type
        # would disagree
        return ct == ConvertedType.LIST
    lt = node.logical_type
    return ct == ConvertedType.LIST or (lt is not None and lt.LIST is not None)


def _is_map_node(node, mode: str) -> bool:
    ct = node.converted_type
    if mode == "arrow":
        return ct in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE)
    lt = node.logical_type
    return ct in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE) or (
        lt is not None and lt.MAP is not None
    )


def _covered(node, streams) -> bool:
    if node.is_leaf:
        return node.path in streams
    return any(_covered(c, streams) for c in node.children)


# -- the builder ----------------------------------------------------------------
#
# State per leaf during the recursion: a _View of the leaf's level arrays
# restricted to the current node's element stream, plus the positions where
# each slot begins. Three invariants keep every step O(n) ndarray math with
# no searchsorted/bincount (shared with the former arrow_nested recursion):
#   * a value-bearing entry (def == leaf.max_def) survives every list
#     filter, so the selected dense values are one contiguous slice from 0;
#   * every slot at struct granularity keeps exactly one entry per leaf;
#   * a no-element placeholder (def below the depth's threshold) is always
#     its slot's SINGLE entry, so per-slot counts are segment lengths minus
#     the placeholder marker — one diff, no scatter.


class _View:
    """One leaf's level streams at the current node's granularity."""

    __slots__ = ("rl", "dl", "starts", "n")

    def __init__(self, rl, dl, starts, n: int):
        self.rl = rl  # ndarray | None (None = no repetition: all zeros)
        self.dl = dl  # ndarray | None (None = every entry at leaf max_def)
        self.starts = starts  # int64 slot-start positions | None (identity)
        self.n = n


def build_field_vec(schema, top, chunks: dict, mode: str):
    """The IR of one top-level field over one row group's leaf chunks.
    `top` is the schema node or its name; mode is "rows" | "raw" | "arrow".
    Returns (vec, n_rows). Raises VecStructureError on structures the scans
    cannot prove and AssemblyError on provable value-count corruption."""
    if isinstance(top, str):
        top = schema.column((top,))
    streams = {
        path: _Stream(schema.column(path), cd)
        for path, cd in chunks.items()
        if path[0] == top.name
    }
    if not streams:
        raise VecStructureError(f"no leaf chunks for field {top.name}")
    state = {}
    n_rows = None
    for path, ls in streams.items():
        if ls.rl is None:
            starts = None  # identity: entry i is record i
            count = ls.n
        else:
            if ls.n and int(ls.rl[0]) != 0:
                raise VecStructureError(f"{top.path_str}: stream opens mid-record")
            starts = rows_from_rep(ls.rl)
            count = len(starts)
        state[path] = _View(ls.rl, ls.dl, starts, ls.n)
        if n_rows is None:
            n_rows = count
        elif n_rows != count:
            raise VecStructureError(
                f"leaves of {top.name} disagree on row count "
                f"({n_rows} vs {count})"
            )
    if top.repetition == FieldRepetitionType.REPEATED:
        vec = _build_repeated(top, streams, state, n_rows, mode)
    else:
        vec = _build(top, streams, state, n_rows, mode)
    return vec, n_rows


def _sub_state(node, streams, state):
    sub = {p: st for p, st in state.items() if p[: len(node.path)] == node.path}
    if not sub:
        return None, None
    return sub, {p: streams[p] for p in sub}


def _build(node, streams, state, n_slots, mode):
    """IR of `node` over the current slots (node known present per slot
    except where its own null mask says otherwise)."""
    if node.repetition == FieldRepetitionType.REPEATED:
        # wire-shape repeated field (incl. spec-violating annotated repeated
        # groups: the annotation describes the node's content, but a
        # REPEATED node's slot granularity is its parent's)
        return _build_repeated(node, streams, state, n_slots, mode)

    if node.is_leaf:
        return _leaf_vec(node, streams, state, n_slots)

    if mode != "raw" and _is_map_node(node, mode) and len(node.children) == 1:
        kv = node.children[0]
        if (
            kv.repetition == FieldRepetitionType.REPEATED
            and not kv.is_leaf
            and len(kv.children) == 2
        ):
            null_mask = (
                _node_null_mask(node, state, n_slots)
                if node.repetition == FieldRepetitionType.OPTIONAL
                else None
            )
            offsets, elem_state = _expand(kv, state, n_slots)
            child = _build_struct(
                kv, streams, elem_state, int(offsets[-1]), mode, force_valid=True
            )
            # arrow needs both key and value selected for a MapArray; with
            # one projected out it assembles the underlying list-of-struct
            both = all(_covered(c, streams) for c in kv.children)
            kind = "list" if (mode == "arrow" and not both) else "map"
            return ListVec(node, kv, offsets, null_mask, child, kind)

    if mode != "raw" and _is_list_node(node, mode) and len(node.children) == 1:
        rep = node.children[0]
        if rep.repetition == FieldRepetitionType.REPEATED and (
            mode != "arrow" or not rep.is_leaf
        ):
            null_mask = (
                _node_null_mask(node, state, n_slots)
                if node.repetition == FieldRepetitionType.OPTIONAL
                else None
            )
            offsets, elem_state = _expand(rep, state, n_slots)
            n_elems = int(offsets[-1])
            if rep.is_leaf:
                # 2-level legacy list: the repeated leaf IS the element
                child = _leaf_vec(rep, streams, elem_state, n_elems)
            elif len(rep.children) == 1:
                sub_state, sub_streams = _sub_state(
                    rep.children[0], streams, elem_state
                )
                if sub_state is None:
                    raise VecStructureError(f"{node.path_str}: element projected out")
                child = _build(
                    rep.children[0], sub_streams, sub_state, n_elems, mode
                )
            else:
                child = _build_struct(
                    rep, streams, elem_state, n_elems, mode, force_valid=True
                )
            return ListVec(node, rep, offsets, null_mask, child, "list")

    return _build_struct(node, streams, state, n_slots, mode)


def _build_repeated(node, streams, state, n_slots, mode):
    """A wire-shape REPEATED field (legacy repeated leaf or group, or any
    repeated node in raw mode): a list of non-null instances per slot."""
    offsets, elem_state = _expand(node, state, n_slots)
    n_elems = int(offsets[-1])
    if node.is_leaf:
        child = _leaf_vec(node, streams, elem_state, n_elems)
    else:
        child = _build_struct(
            node, streams, elem_state, n_elems, mode, force_valid=True
        )
    return ListVec(node, node, offsets, None, child, "repeated")


def _build_struct(node, streams, state, n_slots, mode, force_valid=False):
    null_mask = None
    if not force_valid and node.repetition == FieldRepetitionType.OPTIONAL:
        null_mask = _node_null_mask(node, state, n_slots)
    names = []
    children = []
    for c in node.children:
        sub_state, sub_streams = _sub_state(c, streams, state)
        if sub_state is None:
            continue  # projected out
        names.append(c.name)
        children.append(_build(c, sub_streams, sub_state, n_slots, mode))
    if not names:
        raise VecStructureError(f"{node.path_str}: no selected leaf")
    return StructVec(node, names, children, null_mask, n_slots)


def _node_null_mask(node, state, n_slots):
    """Null mask over the current slots for an OPTIONAL node, from each
    slot's first entry's def level (shared above any descendant leaf, so
    any leaf serves). O(n_slots): slot starts are carried by the state."""
    if node.max_def <= 0:
        return None
    view = next(iter(state.values()))
    if view.dl is None:
        return None  # every entry fully defined: nothing can be null
    first_def = view.dl if view.starts is None else view.dl[view.starts]
    if len(first_def) != n_slots:
        raise VecStructureError(f"{node.path_str}: slot starts out of step")
    return validity_from_def(first_def, node.max_def)


def _expand(rep_node, state, n_slots):
    """Expand the current slots through one REPEATED node: (int64 offsets
    [n_slots+1], per-leaf element stream state). Every leaf under the node
    must describe the same list structure.

    An entry STARTS an element of this depth iff rep <= this depth AND
    def >= the element threshold (below it the entry is the placeholder of
    an empty/null list); per-slot counts are one prefix sum over that mask
    gathered at the slot boundaries — no searchsorted, no bincount. The
    element stream keeps the entries of the elements' subtrees
    (def >= threshold); its slot starts are the element-opening entries."""
    q = rep_node.max_rep
    d_r = rep_node.max_def
    offsets = None
    elem_state = {}
    for path, view in state.items():
        n = view.n
        # a missing rep stream widens to zeros: every entry its own
        # single-element list (the scalar walk's peek_rep() == 0)
        rl = view.rl if view.rl is not None else np.zeros(n, dtype=np.uint16)
        starts = view.starts
        if starts is None:
            starts = np.arange(n, dtype=np.int64)
        if view.dl is None:
            exists = None  # every entry fully defined: no placeholders
            m = rl <= q
        else:
            exists = view.dl >= d_r
            m = (rl <= q) & exists
        cs = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(m, out=cs[1:])
        if len(starts):
            counts = cs[np.append(starts[1:], n)] - cs[starts]
        else:
            counts = np.zeros(0, dtype=np.int64)
        offs = np.zeros(n_slots + 1, dtype=np.int64)
        np.cumsum(counts, out=offs[1:])
        if offsets is None:
            offsets = offs
        elif not np.array_equal(offsets, offs):
            raise VecStructureError(
                f"leaves under {rep_node.path_str} disagree on list structure"
            )
        if exists is None or bool(exists.all()):
            new_view = _View(rl, view.dl, None, n)
            kept_m = m
        else:
            new_view = _View(
                rl[exists], view.dl[exists], None, int(exists.sum())
            )
            kept_m = m[exists]
        elem_starts = np.flatnonzero(kept_m)
        if new_view.n and (not len(elem_starts) or elem_starts[0] != 0):
            # an entry extends an element before any opens: corrupt levels
            # (the scalar walk raises the precise error)
            raise VecStructureError(
                f"{rep_node.path_str}: inconsistent repetition levels"
            )
        new_view.starts = elem_starts
        elem_state[path] = new_view
    if offsets is None:
        raise VecStructureError(f"{rep_node.path_str}: no selected leaf")
    return offsets, elem_state


def _leaf_vec(leaf, streams, state, n_slots):
    ls = streams.get(leaf.path)
    if ls is None:
        raise VecStructureError(f"{leaf.path_str}: leaf not selected")
    view = state[leaf.path]
    if view.n != n_slots:
        raise VecStructureError(
            f"leaf {leaf.path_str} stream does not align with its slots "
            f"({view.n} entries for {n_slots} slots)"
        )
    if view.dl is None or leaf.max_def == 0:
        return LeafVec(leaf, ls.chunk, None, 0, n_slots, n_slots)
    valid = view.dl == leaf.max_def
    nv = int(valid.sum())
    if nv == n_slots:
        valid = None
    # k0 = 0 by the dense-slice invariant: entries dropped by list filters
    # above are never value-bearing, so the first kept value is value 0
    return LeafVec(leaf, ls.chunk, valid, 0, nv, n_slots)


# -- row materialization --------------------------------------------------------


def _leaf_column(vec: LeafVec, raw: bool):
    """Python value list (one per slot, None where null) for a LeafVec, or
    a contiguous numeric ndarray when the C dict_rows path can slice element
    lists straight from the buffer (plain numeric leaf, no nulls, no
    logical conversion)."""
    leaf, chunk = vec.node, vec.chunk
    tp = streams_present_count(chunk, leaf)
    total_present = chunk.num_values if tp is None else tp
    if (
        _ext is not None
        and vec.valid is None
        and not isinstance(chunk.values, ByteArrayData)
        and (raw or logical_kind(leaf) is None)
    ):
        a = np.asarray(chunk.values)
        if (
            a.ndim == 1
            and a.dtype.isnative
            and _ARR_ELEM_SIZES.get(a.dtype.char) == a.dtype.itemsize
        ):
            if len(a) != total_present:
                raise AssemblyError(
                    f"assembly: {leaf.path_str}: {len(a)} values for "
                    f"{total_present} present entries"
                )
            arr = np.ascontiguousarray(a)
            if vec.k0 or vec.nv != len(arr):
                arr = arr[vec.k0 : vec.k0 + vec.nv]
            return arr
    vals = _leaf_python_values(leaf, chunk, raw)
    if len(vals) != total_present:
        raise AssemblyError(
            f"assembly: {leaf.path_str}: {len(vals)} values for "
            f"{total_present} present entries"
        )
    if vec.k0 or vec.nv != len(vals):
        vals = vals[vec.k0 : vec.k0 + vec.nv]
    if vec.valid is None:
        return vals
    full = np.empty(vec.n, dtype=object)  # initialized to None
    full[vec.valid] = vals
    return full.tolist()


def streams_present_count(chunk, leaf):
    """Non-null cell count the chunk's def levels promise, or None when the
    leaf has no def dimension (count = num_values)."""
    if leaf.max_def > 0 and chunk.def_levels is not None:
        return int((np.asarray(chunk.def_levels) == leaf.max_def).sum())
    return None


def _apply_null_mask(values: list, null_mask) -> list:
    if null_mask is not None:
        for i in np.flatnonzero(null_mask).tolist():
            values[i] = None
    return values


def _column_from_vec(vec, raw: bool, top: bool = False):
    """Materialize one IR node into a per-slot Python value list — or, for
    a top-level ListVec of a leaf, a deferred ("slices", elems, offsets,
    mask) spec that _zip_dict_rows slices straight into row dicts (callers
    window-slice specs to bound live row objects)."""
    if isinstance(vec, LeafVec):
        col = _leaf_column(vec, raw)
        if isinstance(col, np.ndarray):  # only reachable under a ListVec
            return col.tolist()
        return col

    if isinstance(vec, ListVec):
        if vec.kind == "map" and not raw:
            return _map_column(vec, raw)
        if isinstance(vec.child, LeafVec):
            elems = _leaf_column(vec.child, raw)
        else:
            elems = _column_from_vec(vec.child, raw)
        if top and _ext is not None:
            # defer the per-row slicing: dict_rows slices elements straight
            # into each row dict (one pass, and numeric ndarrays never take
            # a whole-column tolist at all)
            return ("slices", elems, vec.offsets, vec.null_mask)
        if isinstance(elems, np.ndarray):
            elems = elems.tolist()
        return _rows_from_offsets(elems, vec.offsets, vec.null_mask)

    if isinstance(vec, StructVec):
        cols = [_column_from_vec(c, raw) for c in vec.children]
        cols = [c.tolist() if isinstance(c, np.ndarray) else c for c in cols]
        rows = _zip_dict_rows(list(vec.names), cols)
        return _apply_null_mask(rows, vec.null_mask)

    raise TypeError(f"unknown vec node {type(vec).__name__}")


def _map_column(vec: ListVec, raw: bool):
    """MAP materialization: per-slot dicts from the kv struct's key/value
    columns (REQUIRED keys within a present entry; values may be null or
    projected out — p.get semantics, matching the scalar walk)."""
    kv = vec.rep_node
    n_elems = int(vec.offsets[-1])
    child = vec.child  # StructVec over the covered kv children
    by_name = dict(zip(child.names, child.children))
    cols = []
    for c in kv.children:
        sub = by_name.get(c.name)
        if sub is None:
            cols.append([None] * n_elems)
        else:
            col = _column_from_vec(sub, raw)
            cols.append(col.tolist() if isinstance(col, np.ndarray) else col)
    keys, vals = cols[0], cols[1]
    off = vec.offsets.tolist()
    mask = vec.null_mask.tolist() if vec.null_mask is not None else None
    kname, vname = kv.children[0].name, kv.children[1].name
    out = []
    for i, (a, b) in enumerate(zip(off[:-1], off[1:])):
        if mask is not None and mask[i]:
            out.append(None)
            continue
        try:
            out.append(dict(zip(keys[a:b], vals[a:b])))
        except TypeError:  # unhashable key: keep the pair list
            out.append(
                [{kname: k, vname: v} for k, v in zip(keys[a:b], vals[a:b])]
            )
    return out


def _rows_from_offsets(elems: list, offsets, null_mask) -> list:
    if _ext is not None:
        return _ext.rows_from_slices(
            elems, np.ascontiguousarray(offsets), null_mask
        )
    off = offsets.tolist()
    if null_mask is None:
        return [elems[a:b] for a, b in zip(off[:-1], off[1:])]
    return [
        None if m else elems[a:b]
        for m, a, b in zip(null_mask.tolist(), off[:-1], off[1:])
    ]


# -- flat fast path -------------------------------------------------------------


def _flat_column_values(node, chunk, raw: bool) -> list:
    """One flat leaf column as a row-aligned Python list (nulls expanded)."""
    vals = _leaf_python_values(node, chunk, raw)
    if node.max_def == 1 and chunk.def_levels is not None:
        mask = np.asarray(chunk.def_levels) == 1
        full = [None] * chunk.num_values
        it = iter(vals)
        for idx in np.nonzero(mask)[0]:
            full[idx] = next(it)
        vals = full
    return vals


def _flat_columns(chunks: dict, raw: bool):
    """(names, column value lists, n_rows) for flat schemas (no groups, no
    repetition) — per-column null-expansion at C speed via ndarray.tolist().
    None when the shape needs more than that."""
    cols = []
    for path, chunk in chunks.items():
        node = chunk.column
        if len(path) != 1 or not node.is_leaf or node.max_rep > 0 or node.max_def > 1:
            return None
        cols.append((node, chunk))
    n = None
    for _node, chunk in cols:
        if n is None:
            n = chunk.num_values
        elif n != chunk.num_values:
            return None
    if n is None:
        return [], [], 0
    names = [node.name for node, _ in cols]
    return names, [_flat_column_values(node, chunk, raw) for node, chunk in cols], n


# -- the engine entry points ----------------------------------------------------


def assemble_row_columns(schema, chunks: dict, raw: bool):
    """Column-oriented vectorized assembly: (names, columns, n_rows) where
    each column is a row-aligned value list or a deferred ("slices", ...)
    spec that _zip_dict_rows materializes — callers may window-slice columns
    to bound live row objects. None when the level streams describe a
    structure the scans cannot prove (the scalar RecordAssembler then
    decides — and raises its precise error if the data really is
    inconsistent)."""
    flat = _flat_columns(chunks, raw)
    if flat is not None:
        return flat
    by_top: dict[str, list] = {}
    for path in chunks:
        by_top.setdefault(path[0], []).append(path)
    mode = "raw" if raw else "rows"
    names = []
    columns = []
    n_rows = None
    try:
        for top in schema.root.children:
            paths = by_top.get(top.name)
            if not paths:
                continue  # not selected
            sub = {p: chunks[p] for p in paths}
            if top.is_leaf and top.max_rep == 0 and top.max_def <= 1:
                col = _flat_column_values(top, sub[paths[0]], raw)
                n = len(col)
            else:
                vec, n = build_field_vec(schema, top, sub, mode)
                col = _column_from_vec(vec, raw, top=True)
            if n_rows is None:
                n_rows = n
            elif n_rows != n:
                return None  # inconsistent; let the scalar walk raise precisely
            names.append(top.name)
            columns.append(col)
    except VecStructureError:
        return None
    if n_rows is None:
        return [], [], 0
    return names, columns, n_rows


def assemble_rows(schema, chunks: dict, raw: bool):
    """Row-list form of assemble_row_columns (None on unprovable shapes)."""
    rc = assemble_row_columns(schema, chunks, raw)
    if rc is None:
        return None
    names, columns, n = rc
    if not names or n == 0:
        return []
    return _zip_dict_rows(names, columns)


# -- shared row-zip machinery (consumed by the reader's windowed path) ----------


def _col_len(col) -> int:
    """Row count of a column value list or a deferred slices spec."""
    if isinstance(col, tuple):
        return len(col[2]) - 1
    return len(col)


def _zip_dict_rows(names: list, columns: list) -> list:
    """Zip column value lists (or deferred slices specs, see
    _column_from_vec) into row dicts — C fast path when built; specs are
    only produced when it is. Very wide tables (>256 columns, past the C
    helper's stack table) take the Python zip."""
    if _ext is not None and len(names) <= 256:
        return _ext.dict_rows(tuple(names), tuple(columns))
    columns = [
        _materialize_spec(c) if isinstance(c, tuple) else c for c in columns
    ]
    return [dict(zip(names, row)) for row in zip(*columns)]


def _materialize_spec(spec) -> list:
    """Materialize a deferred ("slices", elems, offsets, mask) column."""
    _tag, elems, offsets, mask = spec
    if isinstance(elems, np.ndarray):  # array-backed spec (C path skipped)
        # convert only this window's element range (a window-sliced spec
        # keeps the FULL elems array with absolute offsets — a whole-column
        # tolist here would repeat per window)
        base = int(offsets[0]) if len(offsets) else 0
        elems = elems[base : int(offsets[-1]) if len(offsets) else 0].tolist()
        offsets = offsets - base
    off = offsets.tolist()
    if mask is None:
        return [elems[a:b] for a, b in zip(off[:-1], off[1:])]
    return [
        None if m else elems[a:b]
        for m, a, b in zip(mask.tolist(), off[:-1], off[1:])
    ]


def slice_column(col, start: int, end: int):
    """Row-window of an assemble_row_columns column (list or slices spec)."""
    if isinstance(col, tuple):
        tag, elems, offsets, mask = col
        return (tag, elems, offsets[start : end + 1],
                None if mask is None else mask[start:end])
    return col[start:end]

"""Block compression registry.

Pluggable codec registry mirroring the reference's BlockCompressor model
(reference: compress.go:16-157): UNCOMPRESSED/GZIP/SNAPPY built in, others
registered at import or by the user via register_codec (the reference's public
RegisterBlockCompressor, compress.go:131-136). Decompressed output is validated
against the expected size before use (reference: compress.go:102-123).

SNAPPY and LZ4/LZ4_RAW resolve to the native C++ codecs (native/, loaded via
ctypes) when built, else pyarrow's bundled implementations. The legacy LZ4
codec (id 5) reads both Hadoop-framed and bare raw blocks and writes the
framed form (parquet-cpp's contract). ZSTD comes from the zstandard module,
BROTLI from pyarrow; LZO raises a clear 'codec not registered' error unless
the user registers an implementation.
"""

from __future__ import annotations

import zlib

from ..meta.file_meta import ParquetFileError
from ..meta.parquet_types import CompressionCodec
from ..utils import metrics as _metrics
from ..utils.trace import add_bytes as _trace_add_bytes

__all__ = [
    "compress_block",
    "decompress_block",
    "register_codec",
    "codec_supported",
    "CompressionError",
]


class CompressionError(ParquetFileError):
    """Corrupt or unsupported compressed block. A ParquetFileError so the
    API boundary's documented catch-all covers codec-level corruption the
    same as every other malformed-file path."""


class _Codec:
    name = "?"

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        raise NotImplementedError


class _Uncompressed(_Codec):
    name = "UNCOMPRESSED"

    def compress(self, data):
        return bytes(data)

    def decompress(self, data, uncompressed_size):
        return bytes(data)


class _Gzip(_Codec):
    name = "GZIP"

    def compress(self, data):
        c = zlib.compressobj(wbits=31)  # gzip container
        # no bytes() round-trip: zlib takes any buffer, and the GIL-held
        # copy of a ~1 MiB page was measurable under the parallel encoder
        return c.compress(data) + c.flush()

    def decompress(self, data, uncompressed_size):
        # wbits=47: auto-detect gzip or zlib headers. Decompression stops at
        # the advertised size: a bomb that inflates past it raises without
        # ever materializing the excess (validation-before-allocation).
        # d.eof also guards integrity: it only turns true once the stream's
        # trailer (gzip CRC32/ISIZE) has been read and verified, so a
        # truncated stream that happens to yield the advertised size still
        # fails here.
        d = zlib.decompressobj(wbits=47)
        out = d.decompress(bytes(data), max(uncompressed_size, 1))
        if d.unconsumed_tail or not d.eof:
            raise CompressionError(
                "gzip stream truncated or inflates past advertised size "
                f"{uncompressed_size}"
            )
        return out


class _PyArrowCodec(_Codec):
    """Stock wrapper over a pyarrow-bundled codec (snappy/lz4_raw/brotli)."""

    def __init__(self, name: str, arrow_name: str):
        import pyarrow as pa

        self.name = name
        self._codec = pa.Codec(arrow_name)

    def compress(self, data):
        return self._codec.compress(bytes(data)).to_pybytes()

    def decompress(self, data, uncompressed_size):
        # memoryview over the pa.Buffer: zero-copy, buffer kept alive by the view
        return memoryview(
            self._codec.decompress(bytes(data), decompressed_size=uncompressed_size)
        )


class _NativeSnappy(_Codec):
    name = "SNAPPY"

    def __init__(self):
        from ..utils.native import get_native

        self._lib = get_native()
        if self._lib is None or not self._lib.has_snappy:
            raise ImportError("native snappy not built")

    def compress(self, data):
        return self._lib.snappy_compress(data)  # _ptr takes any buffer

    def decompress(self, data, uncompressed_size):
        return self._lib.snappy_decompress(data, uncompressed_size)


class _Zstd(_Codec):
    name = "ZSTD"

    def __init__(self):
        import zstandard

        self._c = zstandard.ZstdCompressor()
        self._d = zstandard.ZstdDecompressor()

    def compress(self, data):
        return self._c.compress(bytes(data))

    def decompress(self, data, uncompressed_size):
        return self._d.decompress(bytes(data), max_output_size=max(uncompressed_size, 1))


class _NativeLz4Raw(_Codec):
    """LZ4_RAW (codec 7): one raw LZ4 block per page."""

    name = "LZ4_RAW"

    def __init__(self):
        from ..utils.native import get_native

        self._lib = get_native()
        if self._lib is None or not self._lib.has_lz4:
            raise ImportError("native lz4 not built")

    def compress(self, data):
        return self._lib.lz4_compress(bytes(data))

    def decompress(self, data, uncompressed_size):
        return self._lib.lz4_decompress(data, uncompressed_size)


class _Lz4Hadoop(_Codec):
    """Legacy LZ4 (codec 5): Hadoop framing on disk — repeated
    [4B BE uncompressed size][4B BE compressed size][raw block] — with a
    bare-raw-block fallback on read (parquet-cpp's contract; pyarrow and
    parquet-mr both write the framed form)."""

    name = "LZ4"

    def __init__(self, raw: _Codec):
        self._raw = raw
        from ..utils.native import get_native

        lib = get_native()
        self._lib = lib if lib is not None and lib.has_lz4 else None

    # Hadoop's BlockCompressorStream splits writes at the codec buffer size
    # (io.compression.codec.lz4.buffersize, default 256KB): pages past that
    # emit MULTIPLE [sizes][block] frames, which is what parquet-mr files
    # actually contain — matching it keeps our large pages byte-compatible
    # with Hadoop-stack readers
    _BLOCK = 256 << 10

    def compress(self, data):
        import struct

        data = bytes(data)
        if len(data) <= self._BLOCK:
            block = self._raw.compress(data)
            return struct.pack(">II", len(data), len(block)) + block
        out = bytearray()
        for lo in range(0, len(data), self._BLOCK):
            piece = data[lo : lo + self._BLOCK]
            block = self._raw.compress(piece)
            out += struct.pack(">II", len(piece), len(block)) + block
        return bytes(out)

    def decompress(self, data, uncompressed_size):
        if self._lib is not None:
            return self._lib.lz4_decompress(data, uncompressed_size, hadoop=True)
        import struct

        buf = bytes(data)
        out = bytearray()
        pos = 0
        ok = True
        while pos < len(buf):
            if pos + 8 > len(buf):
                ok = False
                break
            usz, csz = struct.unpack_from(">II", buf, pos)
            if pos + 8 + csz > len(buf) or len(out) + usz > uncompressed_size:
                ok = False
                break
            try:
                out += self._raw.decompress(buf[pos + 8 : pos + 8 + csz], usz)
            except Exception:
                ok = False
                break
            pos += 8 + csz
        if ok and len(out) == uncompressed_size:
            return bytes(out)
        return self._raw.decompress(buf, uncompressed_size)


_REGISTRY: dict[int, _Codec] = {}


def register_codec(codec: CompressionCodec, impl) -> None:
    """Register/override a codec implementation (objects with .compress(bytes)
    and .decompress(bytes, uncompressed_size))."""
    _REGISTRY[int(codec)] = impl


def codec_supported(codec: CompressionCodec) -> bool:
    return int(codec) in _REGISTRY


_GZIP_FUSED_OK: bool | None = None


def fused_gzip_identical() -> bool:
    """One-time probe: the native deflate (ptq_gzip_compress) must produce a
    gzip stream byte-identical to zlib.compressobj(wbits=31) — true when the
    extension and CPython link the same zlib build. A CPython bundling a
    different zlib keeps GZIP chunks on the staged encoder (the fused walk's
    byte-identity contract is absolute)."""
    global _GZIP_FUSED_OK
    if _GZIP_FUSED_OK is None:
        from ..utils.native import get_native

        lib = get_native()
        ok = lib is not None and getattr(lib, "has_gzip_encode", False)
        if ok:
            probe = bytes(range(256)) * 16 + b"parquet_tpu gzip probe " * 64
            try:
                ok = lib.gzip_compress(probe) == _Gzip().compress(probe)
            except Exception:
                ok = False
        _GZIP_FUSED_OK = bool(ok)
    return _GZIP_FUSED_OK


def is_fused_encode_codec(codec) -> bool:
    """True while `codec` resolves to an implementation the fused native
    ENCODE walk reproduces byte-for-byte: the stock UNCOMPRESSED pass-through,
    the native snappy encoder (the walk calls the same function), or stock
    gzip once the deflate identity probe has passed. register_codec overrides
    and pyarrow-backed snappy stand the fused encoder down."""
    impl = _REGISTRY.get(int(codec))
    if isinstance(impl, _Uncompressed):
        return True
    if isinstance(impl, _NativeSnappy):
        return True
    if isinstance(impl, _Gzip):
        return fused_gzip_identical()
    return False


def is_builtin_codec(codec) -> bool:
    """True while `codec` still resolves to a stock implementation — the
    native whole-chunk walk inlines UNCOMPRESSED/SNAPPY/GZIP and must stand
    down when register_codec has overridden one of them."""
    impl = _REGISTRY.get(int(codec))
    return isinstance(
        impl,
        (_Uncompressed, _Gzip, _NativeSnappy, _PyArrowCodec, _NativeLz4Raw, _Lz4Hadoop),
    )


def _get(codec) -> _Codec:
    impl = _REGISTRY.get(int(codec))
    if impl is None:
        try:
            name = CompressionCodec(codec).name
        except ValueError:
            name = str(codec)
        raise CompressionError(
            f"compression codec {name} not registered "
            "(use parquet_tpu.core.compress.register_codec)"
        )
    return impl


def compress_block(data: bytes, codec) -> bytes:
    return _get(codec).compress(data)


def decompress_block(data: bytes, codec, uncompressed_size: int) -> bytes:
    """Decompress and validate the advertised uncompressed size
    (reference: compress.go:107-120)."""
    if uncompressed_size < 0:
        raise CompressionError(f"invalid uncompressed size {uncompressed_size}")
    impl = _get(codec)
    try:
        out = impl.decompress(data, uncompressed_size)
    except CompressionError:
        raise
    except Exception as e:
        raise CompressionError(f"decompression failed: {e}") from e
    if len(out) != uncompressed_size:
        raise CompressionError(
            f"decompressed size {len(out)} != advertised {uncompressed_size}"
        )
    # every staged decode path funnels through here, making this the one
    # choke point for the always-on byte counters (the fused native walk
    # bypasses it and reports its own totals in kernels/pipeline.py).
    # The same output-byte count rides the ACTIVE trace as the
    # `decode.bytes` account, so a request-scoped trace's decoded-byte
    # total reconciles EXACTLY with the process bytes_uncompressed_total
    # delta — what the serve cost ledger charges per tenant.
    _metrics.io_bytes(len(data), len(out), impl.name)
    _trace_add_bytes("decode.bytes", len(out))
    return out


def _init_registry() -> None:
    _REGISTRY[int(CompressionCodec.UNCOMPRESSED)] = _Uncompressed()
    _REGISTRY[int(CompressionCodec.GZIP)] = _Gzip()
    try:
        _REGISTRY[int(CompressionCodec.SNAPPY)] = _NativeSnappy()
    except Exception:
        try:
            _REGISTRY[int(CompressionCodec.SNAPPY)] = _PyArrowCodec("SNAPPY", "snappy")
        except Exception:
            pass
    try:
        _REGISTRY[int(CompressionCodec.ZSTD)] = _Zstd()
    except Exception:
        pass
    raw: _Codec | None
    try:
        raw = _NativeLz4Raw()
    except Exception:
        try:
            raw = _PyArrowCodec("LZ4_RAW", "lz4_raw")
        except Exception:
            raw = None
    if raw is not None:
        _REGISTRY[int(CompressionCodec.LZ4_RAW)] = raw
        _REGISTRY[int(CompressionCodec.LZ4)] = _Lz4Hadoop(raw)
    try:
        _REGISTRY[int(CompressionCodec.BROTLI)] = _PyArrowCodec("BROTLI", "brotli")
    except Exception:
        pass


_init_registry()

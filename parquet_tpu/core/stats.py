"""Column statistics: min/max/null-count tracking for chunks and pages.

Equivalent of the reference's stats.go (typed min/max for int32/int64/float/
double, lexicographic bytes, nil-stats for boolean) computed vectorized over
page/chunk arrays instead of per-value updates. Written into both the legacy
(min/max) and modern (min_value/max_value) Statistics fields, matching what
current writers emit for TypeDefinedOrder columns.
"""

from __future__ import annotations

import struct

import numpy as np

from ..meta.parquet_types import ConvertedType, Statistics, Type
from .arrays import ByteArrayData

__all__ = ["compute_statistics", "column_is_unsigned"]

_PACK = {
    Type.INT32: struct.Struct("<i"),
    Type.INT64: struct.Struct("<q"),
    Type.FLOAT: struct.Struct("<f"),
    Type.DOUBLE: struct.Struct("<d"),
}

_PACK_UNSIGNED = {
    Type.INT32: struct.Struct("<I"),
    Type.INT64: struct.Struct("<Q"),
}

_UINT_VIEW = {Type.INT32: np.uint32, Type.INT64: np.uint64}

_UNSIGNED_CTS = (
    ConvertedType.UINT_8,
    ConvertedType.UINT_16,
    ConvertedType.UINT_32,
    ConvertedType.UINT_64,
)


def column_is_unsigned(column) -> bool:
    """Whether a leaf's logical/converted type makes its order UNSIGNED —
    min/max must then be computed over the unsigned interpretation
    (parquet-format TypeDefinedOrder for UINT_8..UINT_64)."""
    lt = column.logical_type
    if lt is not None and lt.INTEGER is not None:
        return not lt.INTEGER.isSigned
    ct = column.converted_type
    return ct is not None and ct in _UNSIGNED_CTS

# Cap stored min/max byte length, as modern writers do for wide binary values.
_MAX_STAT_BYTES = 64


def compute_statistics(
    ptype: Type, values, null_count: int, unsigned: bool = False
) -> Statistics:
    """Build Statistics for one page or chunk. `values` holds non-null
    cells. `unsigned=True` (UINT logical/converted types) compares and
    packs min/max in the unsigned domain — the column's defined order; the
    deprecated min/max fields are then left unset (they are specified as
    signed-compared, so an unsigned pair there would mislead old readers)."""
    st = Statistics(null_count=null_count)
    n = len(values) if values is not None else 0
    if n == 0:
        return st
    if unsigned and ptype in _PACK_UNSIGNED:
        arr = np.asarray(values).view(_UINT_VIEW[ptype])
        pk = _PACK_UNSIGNED[ptype]
        st.min_value = pk.pack(int(arr.min()))
        st.max_value = pk.pack(int(arr.max()))
        return st
    if ptype in _PACK:
        arr = np.asarray(values)
        if ptype in (Type.FLOAT, Type.DOUBLE):
            finite = arr[~np.isnan(arr)]
            if finite.size == 0:
                return st  # all-NaN: no stats (NaN order undefined)
            mn, mx = finite.min(), finite.max()
            # ±0.0 normalization like modern writers: report min as -0.0 and
            # max as +0.0 so either sign of zero is covered by the range.
            if mn == 0.0:
                mn = arr.dtype.type(-0.0)
            if mx == 0.0:
                mx = arr.dtype.type(0.0)
        else:
            mn, mx = arr.min(), arr.max()
        pk = _PACK[ptype]
        st.min_value = pk.pack(mn)
        st.max_value = pk.pack(mx)
    elif ptype == Type.BOOLEAN:
        arr = np.asarray(values, dtype=bool)
        st.min_value = bytes([int(arr.min())])
        st.max_value = bytes([int(arr.max())])
    elif ptype in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
        if isinstance(values, ByteArrayData):
            from ..utils.native import get_native

            lib = get_native()
            if lib is not None and lib.has_bytes_minmax:
                # C scan over (offsets, data): no per-value Python object
                i_mn, i_mx = lib.bytes_minmax(values.data, values.offsets)
                mn, mx = values[i_mn], values[i_mx]
            else:
                items = values.to_list(cache=True)
                mn = min(items)
                mx = max(items)
        else:
            if isinstance(values, np.ndarray) and values.ndim == 2:
                items = [v.tobytes() for v in values]
            else:
                items = [bytes(v) for v in values]
            mn = min(items)
            mx = max(items)
        st.min_value, exact_min = _truncate_min(mn)
        st.max_value, exact_max = _truncate_max(mx)
        if not (exact_min and exact_max):
            # truncated bounds are still valid for range pruning; the
            # exactness flags tell readers not to treat them as values
            st.is_min_value_exact = exact_min
            st.is_max_value_exact = exact_max
            st.min = st.max = None  # legacy fields carry no exactness flag
            return st
    else:
        return st  # INT96: no meaningful order (reference nilStats analogue)
    # Legacy fields mirror the modern ones (TypeDefinedOrder).
    st.min = st.min_value
    st.max = st.max_value
    return st


def _truncate_min(raw: bytes):
    """(possibly truncated lower bound, is_exact): a prefix of the min is
    always <= the min, so plain truncation is a valid lower bound."""
    if len(raw) <= _MAX_STAT_BYTES:
        return raw, True
    return raw[:_MAX_STAT_BYTES], False


def _truncate_max(raw: bytes):
    """(possibly truncated-and-incremented upper bound, is_exact): the
    prefix alone would UNDERSTATE the max, so the last non-0xFF byte of the
    prefix increments; an all-0xFF prefix cannot be incremented and the
    bound is dropped (None) rather than made unsound."""
    if len(raw) <= _MAX_STAT_BYTES:
        return raw, True
    prefix = bytearray(raw[:_MAX_STAT_BYTES])
    for i in range(len(prefix) - 1, -1, -1):
        if prefix[i] != 0xFF:
            prefix[i] += 1
            return bytes(prefix[: i + 1]), False
    return None, False

"""FileWriter: the low-level public write API.

Equivalent of the reference's FileWriter (reference: file_writer.go:15-27,
:46-77 ctor/options, :229-276 FlushRowGroup, :280-290 auto-flush, :297-350
Close/footer) with a columnar fast path alongside row-wise shredding.

Write flow per row group (reference: chunk_writer.go:154-332): for each leaf,
convert buffered values to a typed array, decide dictionary encoding over the
whole chunk, split into pages of <= max_page_size, emit [dict page] + data
pages (V1 or V2), then assemble ColumnMetaData (encodings, stats, offsets) and
append the RowGroup; close() writes the Thrift footer + length + magic.

Architecture (beyond the reference): bytes leave through a pluggable
ByteSink (parquet_tpu.sink) — paths get an ATOMIC tmp+rename LocalFileSink,
so a crash, an encode fault, or an abort can never leave a torn parquet
file at the destination. The per-chunk encode lives in sink/encoder.py as a
pure function over an immutable EncoderConfig; `parallel=` fans independent
chunk/row-group encodes out on the dedicated pqt-encode pool while one
in-order flusher commits groups, byte-identical to the serial path, with
bounded in-flight encoded bytes and deferred typed error propagation.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..meta.file_meta import MAGIC, serialize_footer
from ..meta.parquet_types import (
    ColumnOrder,
    CompressionCodec,
    Encoding,
    FileMetaData,
    KeyValue,
    RowGroup,
    SortingColumn,
    Type,
    TypeDefinedOrder,
)
from ..sink.encoder import (
    EncodePipeline,
    EncoderConfig,
    assemble_group,
    commit_group,
    encode_chunk,
    encode_pool,
)
from ..sink.sink import open_sink
from ..utils import metrics as _metrics
from .column_store import MAX_PAGE_SIZE_DEFAULT, ColumnChunkBuilder
from .schema import Column, Schema
from .shred import Shredder

__all__ = ["FileWriter", "WriterError"]

ROW_GROUP_SIZE_DEFAULT = 128 << 20  # bytes, reference file_writer.go default

# Default bound on estimated in-flight encoded bytes for parallel writers —
# the backpressure that keeps a fast producer from buffering every pending
# row group in memory while the sink drains.
MAX_INFLIGHT_BYTES_DEFAULT = 256 << 20

# Allowed fallback (non-dictionary) encodings per physical type — the write
# side of the reference's encoder selection matrix (chunk_writer.go:13-128;
# per-column encoding choice mirrors New*Store(enc, useDict, params),
# data_store.go:364-461).
_ALLOWED_ENCODINGS = {
    Type.BOOLEAN: {Encoding.PLAIN, Encoding.RLE},
    Type.INT32: {
        Encoding.PLAIN,
        Encoding.DELTA_BINARY_PACKED,
        Encoding.BYTE_STREAM_SPLIT,
    },
    Type.INT64: {
        Encoding.PLAIN,
        Encoding.DELTA_BINARY_PACKED,
        Encoding.BYTE_STREAM_SPLIT,
    },
    Type.INT96: {Encoding.PLAIN},
    Type.FLOAT: {Encoding.PLAIN, Encoding.BYTE_STREAM_SPLIT},
    Type.DOUBLE: {Encoding.PLAIN, Encoding.BYTE_STREAM_SPLIT},
    Type.BYTE_ARRAY: {
        Encoding.PLAIN,
        Encoding.DELTA_LENGTH_BYTE_ARRAY,
        Encoding.DELTA_BYTE_ARRAY,
    },
    Type.FIXED_LEN_BYTE_ARRAY: {Encoding.PLAIN, Encoding.BYTE_STREAM_SPLIT},
}


class WriterError(ValueError):
    pass


class FileWriter:
    """Writes Parquet files.

    Usage:
        w = FileWriter(path, schema, codec="snappy")
        w.write_row({"a": 1, "s": "x"})          # row path
        w.write_column("a", np.arange(100))      # columnar fast path
        w.flush_row_group()
        w.close()

    `sink` is a path (written ATOMICALLY: a temp file renamed over the
    destination at close, so failures never leave a torn file), a writable
    binary file object, or any parquet_tpu.sink.ByteSink. `parallel=True`
    encodes row groups on the shared pqt-encode pool (an int spins up a
    dedicated pool of that many workers); output bytes are identical to the
    serial path. Encode/flush faults in parallel mode surface as
    WriterError on the next writer call (deferred propagation) and the
    destination is never committed.
    """

    def __init__(
        self,
        sink,
        schema: Schema,
        *,
        codec: CompressionCodec | str = CompressionCodec.UNCOMPRESSED,
        created_by: str = "parquet_tpu",
        data_page_version: int = 1,
        max_page_size: int = MAX_PAGE_SIZE_DEFAULT,
        row_group_size: int = ROW_GROUP_SIZE_DEFAULT,
        enable_dictionary: bool = True,
        column_encodings: dict | None = None,
        use_dictionary=None,
        with_crc: bool = False,
        key_value_metadata: dict | None = None,
        write_page_index: bool = False,
        bloom_filters=None,
        sorting_columns=None,
        parallel=False,
        max_inflight_bytes: int = MAX_INFLIGHT_BYTES_DEFAULT,
    ):
        """`column_encodings` maps a leaf ("a.b" or tuple) to the fallback
        value encoding used when the column is not dictionary-encoded:
        PLAIN (default), DELTA_BINARY_PACKED (int32/int64), RLE (boolean),
        DELTA_LENGTH_BYTE_ARRAY / DELTA_BYTE_ARRAY (byte arrays).
        `use_dictionary` is True/False for all columns or a list of leaves
        to dictionary-encode (overrides `enable_dictionary` when given) —
        the per-column useDict of the reference (data_store.go:364-461).
        `write_page_index=True` emits the Parquet page index (ColumnIndex +
        OffsetIndex per chunk, written between the last row group and the
        footer) — per-page min/max/null stats readers use for page-level
        pruning; beyond the reference, which has no page-index support.
        `bloom_filters` emits split-block bloom filters (also beyond the
        reference): a {leaf: True | {"fpp": float, "ndv": int}} dict, a
        list of leaves, or True for every eligible leaf; default fpp 0.01,
        default ndv the chunk's value count (exact for dictionary chunks).
        `sorting_columns` declares the row ordering in row-group metadata
        (not enforced): leaf names or (leaf, descending, nulls_first)
        triples, like pyarrow's sorting_columns.
        `parallel` enables the pqt-encode pipeline (True = shared pool,
        int = dedicated pool of that size); `max_inflight_bytes` bounds the
        estimated encoded bytes buffered between encode and flush."""
        # Validate EVERY option before the sink opens: a typo'd codec or
        # column name must fail before any filesystem effect (the atomic
        # sink additionally guarantees the DESTINATION is never touched
        # until a successful close).
        self.schema = schema
        if isinstance(codec, str):
            try:
                codec = CompressionCodec[codec.upper()]
            except KeyError:
                valid = ", ".join(c.name.lower() for c in CompressionCodec)
                raise WriterError(
                    f"writer: unknown codec {codec!r} (expected one of: {valid})"
                ) from None
        self.codec = codec
        if data_page_version not in (1, 2):
            raise WriterError("writer: data page version must be 1 or 2")
        self.data_page_version = data_page_version
        self.max_page_size = max_page_size
        self.row_group_size = row_group_size
        self.enable_dictionary = enable_dictionary
        self._column_encodings = self._resolve_encodings(schema, column_encodings)
        self._dict_columns = self._resolve_use_dictionary(
            schema, use_dictionary, enable_dictionary
        )
        self.with_crc = with_crc
        self.created_by = created_by
        self.key_value_metadata = dict(key_value_metadata or {})
        self._shredder = Shredder(schema)
        self._builders: dict[tuple, ColumnChunkBuilder] = {}
        self._columnar_rows: int | None = None
        self._row_groups: list[RowGroup] = []
        self.write_page_index = write_page_index
        # aligned with _row_groups: per group, per chunk (leaf order):
        # (ColumnChunk, ColumnIndex, OffsetIndex) awaiting emission at close
        self._page_indexes: list[list] = []
        self._bloom_specs = self._resolve_blooms(schema, bloom_filters)
        self._sorting = self._resolve_sorting(schema, sorting_columns)
        self._blooms: list[tuple] = []  # (ColumnMetaData, BloomFilter)
        self._cfg = EncoderConfig(
            codec=int(self.codec),
            data_page_version=data_page_version,
            max_page_size=max_page_size,
            with_crc=with_crc,
            write_page_index=write_page_index,
            column_encodings=dict(self._column_encodings),
            bloom_specs=dict(self._bloom_specs),
            sorting=tuple(self._sorting) if self._sorting else None,
        )
        self._codec_label = _metrics.codec_name(int(self.codec))
        self._own_pool: ThreadPoolExecutor | None = None
        pool = None
        if parallel:
            if parallel is True:
                pool = encode_pool()
            else:
                workers = int(parallel)
                if workers < 1:
                    raise WriterError(
                        "writer: parallel must be True or a positive worker count"
                    )
                self._own_pool = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="pqt-encode"
                )
                pool = self._own_pool
        self._pos = 0
        self._closed = False
        self._aborted = False
        self._failed: BaseException | None = None
        self._meta: FileMetaData | None = None
        self._reset_builders()
        self._sink, self._owns_sink = open_sink(sink)
        self._pipeline: EncodePipeline | None = None
        try:
            self._write(MAGIC)  # leading magic (reference: file_writer.go:240-244)
        except OSError as e:
            self.abort()
            raise WriterError(f"writer: sink write failed: {e}") from e
        if pool is not None:
            self._pipeline = EncodePipeline(
                self._cfg,
                self._sink,
                self._pos,
                pool=pool,
                max_inflight_bytes=max_inflight_bytes,
            )

    @staticmethod
    def _leaf(schema: Schema, key) -> Column:
        try:
            leaf = schema.column(key)
        except Exception:
            raise WriterError(
                f"writer: {key!r} is not a leaf column of the schema"
            ) from None
        if not leaf.is_leaf:
            raise WriterError(f"writer: {key!r} is not a leaf column of the schema")
        return leaf

    def _resolve_encodings(self, schema: Schema, column_encodings) -> dict:
        out: dict[tuple, Encoding] = {}
        for key, enc in (column_encodings or {}).items():
            leaf = self._leaf(schema, key)
            if isinstance(enc, str):
                try:
                    enc = Encoding[enc.upper()]
                except KeyError:
                    raise WriterError(f"writer: unknown encoding {enc!r}") from None
            enc = Encoding(enc)
            allowed = _ALLOWED_ENCODINGS.get(leaf.type, {Encoding.PLAIN})
            if enc not in allowed:
                names = ", ".join(sorted(e.name for e in allowed))
                raise WriterError(
                    f"writer: encoding {enc.name} not supported for "
                    f"{leaf.type.name} column {key!r} (allowed: {names})"
                )
            out[leaf.path] = enc
        return out

    def _resolve_use_dictionary(self, schema: Schema, use_dictionary, default) -> set:
        if use_dictionary is None:
            use_dictionary = default
        if use_dictionary is True:
            return {leaf.path for leaf in schema.leaves}
        if use_dictionary is False:
            return set()
        if isinstance(use_dictionary, str):
            use_dictionary = [use_dictionary]  # one column, not its characters
        return {self._leaf(schema, k).path for k in use_dictionary}

    _BLOOM_TYPES = (
        Type.INT32,
        Type.INT64,
        Type.FLOAT,
        Type.DOUBLE,
        Type.BYTE_ARRAY,
        Type.FIXED_LEN_BYTE_ARRAY,
    )

    def _resolve_blooms(self, schema: Schema, bloom_filters) -> dict:
        """{leaf path: (ndv or None, fpp)} for leaves that get a bloom filter."""
        if not bloom_filters:
            return {}
        if bloom_filters is True:
            bloom_filters = {
                leaf.path: True
                for leaf in schema.leaves
                if leaf.type in self._BLOOM_TYPES
            }
        elif isinstance(bloom_filters, str):
            bloom_filters = {bloom_filters: True}  # one column, not its chars
        elif not isinstance(bloom_filters, dict):
            bloom_filters = {k: True for k in bloom_filters}
        out = {}
        for key, spec in bloom_filters.items():
            leaf = self._leaf(schema, key)
            if leaf.type not in self._BLOOM_TYPES:
                raise WriterError(
                    f"writer: bloom filter unsupported for {leaf.type.name} "
                    f"column {leaf.path_str}"
                )
            if spec is True:
                out[leaf.path] = (None, 0.01)
            else:
                out[leaf.path] = (spec.get("ndv"), spec.get("fpp", 0.01))
        return out

    def _resolve_sorting(self, schema: Schema, sorting_columns):
        if not sorting_columns:
            return None
        if isinstance(sorting_columns, str):
            sorting_columns = [sorting_columns]
        out = []
        for spec in sorting_columns:
            if isinstance(spec, str):
                key, descending, nulls_first = spec, False, False
            elif (
                isinstance(spec, (tuple, list))
                and len(spec) == 3
                and isinstance(spec[1], (bool, int))
            ):
                key, descending, nulls_first = spec
            else:
                raise WriterError(
                    "writer: sorting_columns entries are dotted leaf names "
                    "or (name, descending, nulls_first) triples"
                )
            leaf = self._leaf(schema, key)
            out.append(
                SortingColumn(
                    column_idx=leaf.leaf_index,
                    descending=bool(descending),
                    nulls_first=bool(nulls_first),
                )
            )
        return out

    def _reset_builders(self) -> None:
        self._builders = {
            leaf.path: ColumnChunkBuilder(leaf, leaf.path in self._dict_columns)
            for leaf in self.schema.leaves
        }
        self._device_columns: dict[tuple, object] = {}
        self._columnar_rows = None

    def _write(self, data: bytes) -> int:
        off = self._pos
        self._sink.write(data)
        self._pos += len(data)
        return off

    # -- ingestion -------------------------------------------------------------

    def write_row(self, row: dict) -> None:
        self._check_open()
        if self._columnar_rows is not None:
            raise WriterError("writer: cannot mix write_row and write_column in one row group")
        self._shredder.add_row(row)
        if self._shredder.num_rows % 1000 == 0 and self._estimated_size() >= self.row_group_size:
            self.flush_row_group()

    def write_rows(self, rows) -> None:
        """Bulk ingestion; flat schemas take a batched columnar shred that
        skips the per-row recursive walk (one pass per column per batch)."""
        root = self.schema.root
        if any(
            not c.is_leaf or c.max_rep > 0 or c.max_def > 1 for c in root.children
        ):
            for row in rows:
                self.write_row(row)
            return
        self._check_open()
        if self._columnar_rows is not None:
            raise WriterError(
                "writer: cannot mix write_row and write_column in one row group"
            )
        BATCH = 4096
        batch: list = []
        for row in rows:
            batch.append(row)
            if len(batch) >= BATCH:
                self._write_flat_batch(batch)
                batch.clear()
        if batch:
            self._write_flat_batch(batch)

    def _write_flat_batch(self, batch: list) -> None:
        from .shred import ShredError, _value_size

        # Phase 1 — validate + stage every column WITHOUT touching buffers,
        # so a bad row leaves the writer consistent (a partial append would
        # silently misalign columns and close() would write a corrupt file).
        for row in batch:
            if not isinstance(row, dict):
                raise ShredError(
                    f"shred: row must be a dict, got {type(row).__name__}"
                )
        staged = []
        for leaf in self.schema.root.children:
            name = leaf.name
            if leaf.max_def == 1:
                vals = []
                defs = []
                for row in batch:
                    v = row.get(name)
                    if v is None:
                        defs.append(0)
                    else:
                        defs.append(1)
                        vals.append(v)
            else:
                vals = []
                for row in batch:
                    v = row.get(name)
                    if v is None:
                        raise ShredError(
                            f"shred: required field {leaf.path_str} is None"
                        )
                    vals.append(v)
                defs = [0] * len(batch)
            staged.append((self._shredder.buffers[leaf.path], vals, defs))
        # Phase 2 — commit (list extends cannot fail on valid staged data)
        for buf, vals, defs in staged:
            buf.values.extend(vals)
            buf.def_levels.extend(defs)
            buf.rep_levels.extend([0] * len(batch))
            buf.data_size += sum(_value_size(v) for v in vals)
        self._shredder.num_rows += len(batch)
        if self._estimated_size() >= self.row_group_size:
            self.flush_row_group()

    def write_column(self, path, values, def_levels=None, rep_levels=None) -> None:
        """Columnar fast path for one leaf of the current row group.

        For flat REQUIRED columns pass just `values`; for OPTIONAL pass
        def_levels (or a values array with None handled by caller); for nested
        columns pass explicit def/rep levels (Dremel encoding).
        """
        self._check_open()
        if self._shredder.num_rows:
            raise WriterError("writer: cannot mix write_row and write_column in one row group")
        leaf = self.schema.column(path)
        if not leaf.is_leaf:
            raise WriterError(f"writer: {leaf.path_str} is not a leaf column")
        builder = self._builders[leaf.path]
        builder.set_columnar(values, def_levels, rep_levels)
        n_rows = (
            int((np.asarray(rep_levels) == 0).sum())
            if rep_levels is not None and len(rep_levels)
            else (len(def_levels) if def_levels is not None else len(values))
        )
        if self._columnar_rows is None:
            self._columnar_rows = n_rows
        elif self._columnar_rows != n_rows:
            raise WriterError(
                f"writer: column {leaf.path_str} has {n_rows} rows, "
                f"others have {self._columnar_rows}"
            )

    def write_device_column(self, path, values) -> None:
        """Columnar fast path for a DEVICE-RESIDENT leaf: jax checkpoint
        shards go array -> pages with no host round-trip of the raw values
        (kernels/pipeline.encode_device_column does the dictionary probe,
        hybrid/bit-pack, DELTA block scans and byte-array framing on
        device; the host frames pages and compresses). Output bytes are
        IDENTICAL to write_column for the same values.

        `values` is a 1-D jax array for numeric leaves, or a
        `(data, offsets)` device pair for BYTE_ARRAY leaves. The leaf must
        be flat REQUIRED (levels stay a host concern). Shapes the device
        encoder cannot take (BYTE_STREAM_SPLIT, booleans, page-index
        writers, ...) fall back typed-and-counted through the host encoder
        at flush time (`device_write_engaged` / `device_write_declined`).
        Incompatible with `parallel=` — the encode pipeline snapshots host
        builders, and device arrays must not outlive their buffer donor."""
        self._check_open()
        if self._shredder.num_rows:
            raise WriterError(
                "writer: cannot mix write_row and write_column in one row group"
            )
        if self._pipeline is not None:
            raise WriterError(
                "writer: write_device_column requires a serial writer "
                "(parallel=False)"
            )
        leaf = self.schema.column(path)
        if not leaf.is_leaf:
            raise WriterError(f"writer: {leaf.path_str} is not a leaf column")
        if leaf.max_rep > 0 or leaf.max_def > 0:
            raise WriterError(
                f"writer: {leaf.path_str} is not flat REQUIRED — device "
                "columns carry no levels (use write_column)"
            )
        if leaf.type == Type.BYTE_ARRAY:
            try:
                _data, offsets = values
            except (TypeError, ValueError):
                raise WriterError(
                    "writer: BYTE_ARRAY device columns take a "
                    "(data, offsets) pair"
                ) from None
            n_rows = int(len(offsets)) - 1
        else:
            n_rows = int(len(values))
        self._device_columns[leaf.path] = values
        if self._columnar_rows is None:
            self._columnar_rows = n_rows
        elif self._columnar_rows != n_rows:
            raise WriterError(
                f"writer: column {leaf.path_str} has {n_rows} rows, "
                f"others have {self._columnar_rows}"
            )

    def _encode_device_chunk(self, leaf: Column, values, kv):
        """Encode one device-buffered leaf at flush time: the device route,
        or the typed-and-counted host fallback for shapes it declines."""
        from ..utils.trace import bump as trace_bump

        use_dict = leaf.path in self._dict_columns
        try:
            from ..kernels.pipeline import encode_device_column
        except Exception as e:  # jax missing/broken: host path still works
            trace_bump("device_write_declined")
            return self._host_encode_device_values(leaf, values, kv, use_dict)
        try:
            ec = encode_device_column(
                leaf, values, self._cfg, kv, enable_dict=use_dict
            )
        except ValueError:
            trace_bump("device_write_declined")
            return self._host_encode_device_values(leaf, values, kv, use_dict)
        trace_bump("device_write_engaged")
        return ec

    def _host_encode_device_values(self, leaf, values, kv, use_dict):
        from .arrays import ByteArrayData

        if leaf.type == Type.BYTE_ARRAY:
            data, offsets = values
            host = ByteArrayData(
                offsets=np.asarray(offsets).astype(np.int64, copy=False),
                data=np.asarray(data),
            )
        else:
            host = np.asarray(values)
        b = ColumnChunkBuilder(leaf, use_dict)
        b.set_columnar(host)
        return encode_chunk(self._cfg, b, kv)

    def _estimated_size(self) -> int:
        total = 0
        for b in self._shredder.buffers.values():
            total += b.data_size + 2 * len(b.def_levels)
        return total

    def estimated_buffered_size(self) -> int:
        """Approximate bytes of the not-yet-flushed row group (the sizing
        input of the auto-flush; public for tools sizing output parts)."""
        return self._estimated_size()

    # -- row group flush -------------------------------------------------------

    def flush_row_group(self, metadata=None, column_metadata=None) -> None:
        """Flush buffered rows/columns as one row group.

        `metadata` ({k: v}) attaches key-value metadata to every column chunk
        of this row group; `column_metadata` ({leaf: {k: v}}) targets single
        columns — the reference's per-flush FlushRowGroupOption KV metadata
        (file_writer.go:156-226, WithRowGroupMetaData[ForColumn]).

        With `parallel=`, the encode runs in the background: this returns as
        soon as the group's builders are snapshotted and fanned out (or
        blocks briefly on the in-flight-bytes backpressure), and any fault
        surfaces as WriterError on a LATER call (deferred propagation)."""
        self._check_open()
        per_col: dict[tuple, dict] = {}
        if metadata or column_metadata:
            if not self._shredder.num_rows and self._columnar_rows is None:
                raise WriterError(
                    "writer: flush_row_group with metadata but nothing buffered "
                    "(an auto-flush may have emptied the buffer)"
                )
            for leaf in self.schema.leaves:
                kv = dict(metadata or {})
                per_col[leaf.path] = kv
            for key, kv in (column_metadata or {}).items():
                per_col.setdefault(self._leaf(self.schema, key).path, {}).update(kv)
        if self._shredder.num_rows:
            shredded, n_rows = self._shredder.drain()
            for path, (vals, dls, rls) in shredded.items():
                self._builders[path].extend_shredded(vals, dls, rls)
        elif self._columnar_rows is not None:
            n_rows = self._columnar_rows
            missing = [
                l.path_str
                for l in self.schema.leaves
                if self._builders[l.path]._columnar_values is None
                and l.path not in self._device_columns
            ]
            if missing:
                raise WriterError(f"writer: columnar row group missing columns {missing}")
        else:
            return  # nothing buffered
        # snapshot the builders (leaf order) and hand the writer fresh ones:
        # from here the group encodes from its own private state, whether
        # inline (serial) or on the pqt-encode pool (parallel)
        leaves = self.schema.leaves
        builders = [self._builders[leaf.path] for leaf in leaves]
        kvs = [per_col.get(leaf.path) for leaf in leaves]
        device_cols = self._device_columns
        self._reset_builders()
        if self._pipeline is not None:
            try:
                est = sum(_estimate_input_bytes(b) for b in builders)
                self._pipeline.submit(builders, kvs, n_rows, est)
            except WriterError:
                raise
            except BaseException as e:
                self._failed = e
                self.abort()
                raise WriterError(
                    f"writer: background encode/flush failed: {e}"
                ) from e
            return
        try:
            chunks = [
                self._encode_device_chunk(leaf, device_cols[leaf.path], kv)
                if leaf.path in device_cols
                else encode_chunk(self._cfg, b, kv)
                for leaf, b, kv in zip(leaves, builders, kvs)
            ]
            erg = assemble_group(self._cfg, chunks, n_rows)
        except Exception as e:
            # the group's builders are already consumed: continuing would
            # let close() commit a valid-LOOKING file with this row group
            # silently missing — poison the writer and tear the output
            # down, re-raising the precise input error (StoreError etc.)
            self._failed = e
            self.abort()
            raise
        erg.row_group.ordinal = len(self._row_groups)
        try:
            self._pos = commit_group(erg, self._sink, self._pos, self._codec_label)
        except Exception as e:
            # the sink rejected bytes mid-group (custom sinks may raise
            # non-OSError transport exceptions): _pos is now out of sync
            # with the sink, so the writer can never produce a coherent
            # file — tear the output down (the atomic sink deletes its
            # temp file; the destination is clean)
            self._failed = e
            self.abort()
            raise WriterError(f"writer: flush failed: {e}") from e
        self._row_groups.append(erg.row_group)
        if self.write_page_index:
            self._page_indexes.append(erg.indexes)
        self._blooms.extend(erg.blooms)

    # -- lifecycle -------------------------------------------------------------

    def close(self) -> FileMetaData | None:
        """Flush, write blooms/page indexes/footer, and COMMIT the sink
        (atomic rename for path sinks). Idempotent: a second close returns
        the same FileMetaData. After a write fault (or abort) close()
        aborts instead — the destination never sees a half-written file —
        and returns None."""
        if self._closed:
            return self._meta
        if self._aborted:
            return None
        if self._failed is not None:
            # the failure was already raised to the caller: quiet abort
            self.abort()
            return None
        if self._pipeline is not None and self._pipeline.error is not None:
            # a background fault the caller has NOT seen yet — close() is
            # its last chance to surface; swallowing it would let a `with`
            # block exit cleanly with the destination silently missing
            e = self._pipeline.error
            self._failed = e
            self.abort()
            raise WriterError(
                f"writer: background encode/flush failed: {e}"
            ) from e
        try:
            self.flush_row_group()
            if self._pipeline is not None:
                try:
                    self._pipeline.drain()
                except BaseException as e:
                    self._failed = e
                    raise WriterError(
                        f"writer: background encode/flush failed: {e}"
                    ) from e
                self._row_groups = list(self._pipeline.row_groups)
                self._page_indexes = list(self._pipeline.page_indexes)
                self._blooms = list(self._pipeline.blooms)
                self._pos = self._pipeline.pos
            try:
                meta = self._write_tail()
                self._sink.flush()
                if self._owns_sink:
                    self._sink.close()  # atomic commit for path sinks
            except OSError as e:
                self._failed = e
                raise WriterError(f"writer: close failed: {e}") from e
        except BaseException:
            self.abort()
            raise
        self._closed = True
        self._meta = meta
        if self._own_pool is not None:
            self._own_pool.shutdown(wait=False)
        return meta

    def _write_tail(self) -> FileMetaData:
        """Bloom filters, then page index blobs, live between the last row
        group and the footer, with metadata fields pointing at them."""
        for md, bf in self._blooms:
            blob = bf.to_bytes()
            md.bloom_filter_offset = self._pos
            md.bloom_filter_length = len(blob)
            self._write(blob)
        self._blooms = []
        # (parquet-format PageIndex layout): all ColumnIndexes, then all
        # OffsetIndexes, with ColumnChunk fields pointing at them.
        for group in self._page_indexes:
            for cc, ci, _oi in group:
                blob = ci.dumps()
                cc.column_index_offset = self._pos
                cc.column_index_length = len(blob)
                self._write(blob)
        for group in self._page_indexes:
            for cc, _ci, oi in group:
                blob = oi.dumps()
                cc.offset_index_offset = self._pos
                cc.offset_index_length = len(blob)
                self._write(blob)
        self._page_indexes = []
        meta = FileMetaData(
            version=2,
            schema=self.schema.to_thrift(),
            num_rows=sum(rg.num_rows or 0 for rg in self._row_groups),
            row_groups=self._row_groups,
            created_by=self.created_by,
            key_value_metadata=[
                KeyValue(key=k, value=v) for k, v in self.key_value_metadata.items()
            ]
            or None,
            column_orders=[
                ColumnOrder(TYPE_ORDER=TypeDefinedOrder())
                for _ in self.schema.leaves
            ],
        )
        self._write(serialize_footer(meta))
        return meta

    def abort(self) -> None:
        """Abandon the file: stop background encodes, discard the sink
        WITHOUT committing (the atomic path sink deletes its temp file; the
        destination is untouched). Idempotent, and a no-op after a
        successful close() — committed output is never destroyed."""
        if self._closed or self._aborted:
            return
        self._aborted = True
        if self._pipeline is not None:
            self._pipeline.abort()
        if self._own_pool is not None:
            self._own_pool.shutdown(wait=False)
        try:
            self._sink.abort()
        except Exception:
            pass  # abort is the error path: best-effort cleanup only

    @property
    def current_file_size(self) -> int:
        """Bytes written so far (reference: file_writer.go:362
        CurrentFileSize). Under `parallel=` this is the COMMITTED prefix —
        groups still encoding in the background are not counted yet."""
        if self._pipeline is not None and not self._closed:
            return self._pipeline.pos
        return self._pos

    @property
    def current_row_group_rows(self) -> int:
        return self._shredder.num_rows or (self._columnar_rows or 0)

    @property
    def current_row_group_size(self) -> int:
        """Rough UNCOMPRESSED size of the buffered (unflushed) row group —
        the size-based flush signal (reference: file_writer.go:355
        CurrentRowGroupSize); the flushed bytes will usually be smaller
        once encoded and compressed. Covers both ingestion paths: shredded
        rows still in the Shredder plus columnar data in the builders."""
        return self._estimated_size() + sum(
            b.data_size() for b in self._builders.values()
        )

    def _check_open(self) -> None:
        if self._failed is not None:
            raise WriterError(
                "writer: an earlier write failed; the writer is unusable "
                "(the output was not committed)"
            ) from self._failed
        if self._closed or self._aborted:
            raise WriterError("writer: already closed")
        if self._pipeline is not None and self._pipeline.error is not None:
            # deferred propagation: a background encode/flush fault
            # surfaces on the NEXT writer call, and the output is torn down
            e = self._pipeline.error
            self._failed = e
            self.abort()
            raise WriterError(
                f"writer: background encode/flush failed: {e}"
            ) from e

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *rest):
        if exc_type is None:
            # close() surfaces any still-unseen background fault as
            # WriterError (and quietly aborts only when the fault was
            # already raised to the caller)
            self.close()
        else:
            # an exception inside the `with` must NOT commit a half-written
            # file: tear down the temp file / background work instead
            self.abort()
        return False


def _estimate_input_bytes(builder: ColumnChunkBuilder) -> int:
    """Approximate buffered bytes of one chunk for the pipeline's
    backpressure accounting. Exact for array inputs (nbytes); for long
    Python value lists a 64-point sample extrapolates instead of walking
    every element — an exact `sum(len(x) for x in million_strings)` costs
    more than the backpressure it feeds (profiled at ~0.24 s/M rows)."""
    for v in (builder._columnar_values, builder.values):
        if isinstance(v, list) and len(v) > 256:
            step = max(len(v) // 64, 1)
            sample = v[::step][:64]
            per = sum(
                len(x) + 4 if isinstance(x, (bytes, str)) else 8
                for x in sample
            ) / max(len(sample), 1)
            return int(per * len(v)) + 2 * len(builder.def_levels)
    return builder.data_size()

"""FileWriter: the low-level public write API.

Equivalent of the reference's FileWriter (reference: file_writer.go:15-27,
:46-77 ctor/options, :229-276 FlushRowGroup, :280-290 auto-flush, :297-350
Close/footer) with a columnar fast path alongside row-wise shredding.

Write flow per row group (reference: chunk_writer.go:154-332): for each leaf,
convert buffered values to a typed array, decide dictionary encoding over the
whole chunk, split into pages of <= max_page_size, emit [dict page] + data
pages (V1 or V2), then assemble ColumnMetaData (encodings, stats, offsets) and
append the RowGroup; Close() writes the Thrift footer + length + magic.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from ..meta.file_meta import MAGIC, serialize_footer
from ..meta.parquet_types import (
    BoundaryOrder,
    ColumnChunk,
    ColumnIndex,
    ColumnMetaData,
    ColumnOrder,
    CompressionCodec,
    Encoding,
    FileMetaData,
    KeyValue,
    OffsetIndex,
    PageEncodingStats,
    PageLocation,
    PageType,
    RowGroup,
    SortingColumn,
    Type,
    TypeDefinedOrder,
)
from .arrays import ByteArrayData
from .column_store import (
    DICT_MAX_UNIQUES,
    MAX_PAGE_SIZE_DEFAULT,
    ColumnChunkBuilder,
    StoreError,
)
from .page import (
    encode_data_page_v1,
    encode_data_page_v2,
    encode_dict_page,
)
from .schema import Column, Schema
from .shred import Shredder
from .stats import column_is_unsigned, compute_statistics

__all__ = ["FileWriter", "WriterError"]

ROW_GROUP_SIZE_DEFAULT = 128 << 20  # bytes, reference file_writer.go default

# Allowed fallback (non-dictionary) encodings per physical type — the write
# side of the reference's encoder selection matrix (chunk_writer.go:13-128;
# per-column encoding choice mirrors New*Store(enc, useDict, params),
# data_store.go:364-461).
_ALLOWED_ENCODINGS = {
    Type.BOOLEAN: {Encoding.PLAIN, Encoding.RLE},
    Type.INT32: {
        Encoding.PLAIN,
        Encoding.DELTA_BINARY_PACKED,
        Encoding.BYTE_STREAM_SPLIT,
    },
    Type.INT64: {
        Encoding.PLAIN,
        Encoding.DELTA_BINARY_PACKED,
        Encoding.BYTE_STREAM_SPLIT,
    },
    Type.INT96: {Encoding.PLAIN},
    Type.FLOAT: {Encoding.PLAIN, Encoding.BYTE_STREAM_SPLIT},
    Type.DOUBLE: {Encoding.PLAIN, Encoding.BYTE_STREAM_SPLIT},
    Type.BYTE_ARRAY: {
        Encoding.PLAIN,
        Encoding.DELTA_LENGTH_BYTE_ARRAY,
        Encoding.DELTA_BYTE_ARRAY,
    },
    Type.FIXED_LEN_BYTE_ARRAY: {Encoding.PLAIN, Encoding.BYTE_STREAM_SPLIT},
}


class _PageIndexBuilder:
    """Accumulates one chunk's per-page locations + statistics into
    (ColumnIndex, OffsetIndex) — the Parquet page index (beyond the
    reference, which writes no page index)."""

    def __init__(self, column: Column, dictionary):
        self.column = column
        self.unsigned = column_is_unsigned(column)
        self.dictionary = dictionary  # dict VALUES when pages carry indices
        self.locations: list[PageLocation] = []
        self.null_pages: list[bool] = []
        self.mins: list[bytes] = []
        self.maxs: list[bytes] = []
        self.null_counts: list[int] = []
        self.first_row = 0
        self.ok = True  # a page without computable stats voids the index

    def add_page(self, offset: int, size: int, v_slice, d_slice, r_slice) -> None:
        if not self.ok:
            return
        if r_slice is not None and len(r_slice):
            rows = int((np.asarray(r_slice) == 0).sum())
        elif d_slice is not None:
            rows = len(d_slice)
        else:
            rows = len(v_slice)
        self.locations.append(
            PageLocation(
                offset=offset, compressed_page_size=size, first_row_index=self.first_row
            )
        )
        self.first_row += rows
        nulls = (
            int((np.asarray(d_slice) != self.column.max_def).sum())
            if d_slice is not None
            else 0
        )
        self.null_counts.append(nulls)
        values = v_slice
        if self.dictionary is not None:
            idx = np.asarray(v_slice)
            values = (
                self.dictionary.take(idx.astype(np.int64))
                if isinstance(self.dictionary, ByteArrayData)
                else np.asarray(self.dictionary)[idx]
            )
        if len(values) == 0:
            self.null_pages.append(True)
            self.mins.append(b"")
            self.maxs.append(b"")
            return
        st = compute_statistics(self.column.type, values, nulls, self.unsigned)
        if st.min_value is None or st.max_value is None:
            # all-NaN page / oversized binary: a legal index can't represent
            # it, so write no index for this chunk at all
            self.ok = False
            return
        self.null_pages.append(False)
        self.mins.append(st.min_value)
        self.maxs.append(st.max_value)

    def _boundary_order(self) -> int:
        # the tables that packed these exact bytes
        from ..meta.parquet_types import ConvertedType, Type
        from .stats import _PACK, _PACK_UNSIGNED

        unpack = (
            _PACK_UNSIGNED.get(self.column.type)
            if self.unsigned
            else _PACK.get(self.column.type)
        )
        if unpack is None:
            if self.column.type in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
                ct = self.column.converted_type
                lt = self.column.logical_type
                if ct in (ConvertedType.DECIMAL, ConvertedType.INTERVAL) or (
                    lt is not None
                    and (lt.DECIMAL is not None or lt.FLOAT16 is not None)
                ):
                    # signed / no defined order: lexicographic bytes would
                    # mislead a reader's binary search
                    return int(BoundaryOrder.UNORDERED)
                # unsigned lexicographic IS the defined order for binary
                # columns, and it's how these bounds were computed — sorted
                # string columns keep readers' binary search
                unpack = None
            else:
                return int(BoundaryOrder.UNORDERED)  # INT96 etc.: stay safe
        if unpack is None:
            pairs = [
                (mn, mx)
                for mn, mx, null in zip(self.mins, self.maxs, self.null_pages)
                if not null
            ]
        else:
            pairs = [
                (unpack.unpack(mn)[0], unpack.unpack(mx)[0])
                for mn, mx, null in zip(self.mins, self.maxs, self.null_pages)
                if not null
            ]
        if len(pairs) < 2:
            return int(BoundaryOrder.ASCENDING)
        if all(
            b[0] >= a[0] and b[1] >= a[1] for a, b in zip(pairs, pairs[1:])
        ):
            return int(BoundaryOrder.ASCENDING)
        if all(
            b[0] <= a[0] and b[1] <= a[1] for a, b in zip(pairs, pairs[1:])
        ):
            return int(BoundaryOrder.DESCENDING)
        return int(BoundaryOrder.UNORDERED)

    def build(self):
        if not self.ok:
            return ()
        ci = ColumnIndex(
            null_pages=self.null_pages,
            min_values=self.mins,
            max_values=self.maxs,
            boundary_order=self._boundary_order(),
            null_counts=self.null_counts,
        )
        oi = OffsetIndex(page_locations=self.locations)
        return (ci, oi)


class WriterError(ValueError):
    pass


class FileWriter:
    """Writes Parquet files.

    Usage:
        w = FileWriter(path, schema, codec="snappy")
        w.write_row({"a": 1, "s": "x"})          # row path
        w.write_column("a", np.arange(100))      # columnar fast path
        w.flush_row_group()
        w.close()
    """

    def __init__(
        self,
        sink,
        schema: Schema,
        *,
        codec: CompressionCodec | str = CompressionCodec.UNCOMPRESSED,
        created_by: str = "parquet_tpu",
        data_page_version: int = 1,
        max_page_size: int = MAX_PAGE_SIZE_DEFAULT,
        row_group_size: int = ROW_GROUP_SIZE_DEFAULT,
        enable_dictionary: bool = True,
        column_encodings: dict | None = None,
        use_dictionary=None,
        with_crc: bool = False,
        key_value_metadata: dict | None = None,
        write_page_index: bool = False,
        bloom_filters=None,
        sorting_columns=None,
    ):
        """`column_encodings` maps a leaf ("a.b" or tuple) to the fallback
        value encoding used when the column is not dictionary-encoded:
        PLAIN (default), DELTA_BINARY_PACKED (int32/int64), RLE (boolean),
        DELTA_LENGTH_BYTE_ARRAY / DELTA_BYTE_ARRAY (byte arrays).
        `use_dictionary` is True/False for all columns or a list of leaves
        to dictionary-encode (overrides `enable_dictionary` when given) —
        the per-column useDict of the reference (data_store.go:364-461).
        `write_page_index=True` emits the Parquet page index (ColumnIndex +
        OffsetIndex per chunk, written between the last row group and the
        footer) — per-page min/max/null stats readers use for page-level
        pruning; beyond the reference, which has no page-index support.
        `bloom_filters` emits split-block bloom filters (also beyond the
        reference): a {leaf: True | {"fpp": float, "ndv": int}} dict, a
        list of leaves, or True for every eligible leaf; default fpp 0.01,
        default ndv the chunk's value count (exact for dictionary chunks).
        `sorting_columns` declares the row ordering in row-group metadata
        (not enforced): leaf names or (leaf, descending, nulls_first)
        triples, like pyarrow's sorting_columns."""
        # Validate EVERY option before the sink opens: open(path, "wb")
        # truncates an existing file, so a typo'd codec/column name must
        # fail without destroying anything.
        self.schema = schema
        if isinstance(codec, str):
            try:
                codec = CompressionCodec[codec.upper()]
            except KeyError:
                valid = ", ".join(c.name.lower() for c in CompressionCodec)
                raise WriterError(
                    f"writer: unknown codec {codec!r} (expected one of: {valid})"
                ) from None
        self.codec = codec
        if data_page_version not in (1, 2):
            raise WriterError(f"writer: data page version must be 1 or 2")
        self.data_page_version = data_page_version
        self.max_page_size = max_page_size
        self.row_group_size = row_group_size
        self.enable_dictionary = enable_dictionary
        self._column_encodings = self._resolve_encodings(schema, column_encodings)
        self._dict_columns = self._resolve_use_dictionary(
            schema, use_dictionary, enable_dictionary
        )
        self.with_crc = with_crc
        self.created_by = created_by
        self.key_value_metadata = dict(key_value_metadata or {})
        self._shredder = Shredder(schema)
        self._builders: dict[tuple, ColumnChunkBuilder] = {}
        self._columnar_rows: int | None = None
        self._row_groups: list[RowGroup] = []
        self.write_page_index = write_page_index
        # aligned with _row_groups: per group, per chunk (leaf order):
        # (ColumnChunk, ColumnIndex, OffsetIndex) awaiting emission at close
        self._page_indexes: list[list[tuple]] = []
        self._bloom_specs = self._resolve_blooms(schema, bloom_filters)
        self._sorting = self._resolve_sorting(schema, sorting_columns)
        self._blooms: list[tuple] = []  # (ColumnMetaData, BloomFilter)
        self._flush_kv: dict[tuple, dict] = {}
        self._pos = 0
        self._closed = False
        self._reset_builders()
        if isinstance(sink, (str, Path)):
            self._f = open(sink, "wb")
            self._owns_file = True
        else:
            self._f = sink
            self._owns_file = False
        self._write(MAGIC)  # leading magic (reference: file_writer.go:240-244)

    @staticmethod
    def _leaf(schema: Schema, key) -> Column:
        try:
            leaf = schema.column(key)
        except Exception:
            raise WriterError(
                f"writer: {key!r} is not a leaf column of the schema"
            ) from None
        if not leaf.is_leaf:
            raise WriterError(f"writer: {key!r} is not a leaf column of the schema")
        return leaf

    def _resolve_encodings(self, schema: Schema, column_encodings) -> dict:
        out: dict[tuple, Encoding] = {}
        for key, enc in (column_encodings or {}).items():
            leaf = self._leaf(schema, key)
            if isinstance(enc, str):
                try:
                    enc = Encoding[enc.upper()]
                except KeyError:
                    raise WriterError(f"writer: unknown encoding {enc!r}") from None
            enc = Encoding(enc)
            allowed = _ALLOWED_ENCODINGS.get(leaf.type, {Encoding.PLAIN})
            if enc not in allowed:
                names = ", ".join(sorted(e.name for e in allowed))
                raise WriterError(
                    f"writer: encoding {enc.name} not supported for "
                    f"{leaf.type.name} column {key!r} (allowed: {names})"
                )
            out[leaf.path] = enc
        return out

    def _resolve_use_dictionary(self, schema: Schema, use_dictionary, default) -> set:
        if use_dictionary is None:
            use_dictionary = default
        if use_dictionary is True:
            return {leaf.path for leaf in schema.leaves}
        if use_dictionary is False:
            return set()
        if isinstance(use_dictionary, str):
            use_dictionary = [use_dictionary]  # one column, not its characters
        return {self._leaf(schema, k).path for k in use_dictionary}

    _BLOOM_TYPES = (
        Type.INT32,
        Type.INT64,
        Type.FLOAT,
        Type.DOUBLE,
        Type.BYTE_ARRAY,
        Type.FIXED_LEN_BYTE_ARRAY,
    )

    def _resolve_blooms(self, schema: Schema, bloom_filters) -> dict:
        """{leaf path: (ndv or None, fpp)} for leaves that get a bloom filter."""
        if not bloom_filters:
            return {}
        if bloom_filters is True:
            bloom_filters = {
                leaf.path: True
                for leaf in schema.leaves
                if leaf.type in self._BLOOM_TYPES
            }
        elif isinstance(bloom_filters, str):
            bloom_filters = {bloom_filters: True}  # one column, not its chars
        elif not isinstance(bloom_filters, dict):
            bloom_filters = {k: True for k in bloom_filters}
        out = {}
        for key, spec in bloom_filters.items():
            leaf = self._leaf(schema, key)
            if leaf.type not in self._BLOOM_TYPES:
                raise WriterError(
                    f"writer: bloom filter unsupported for {leaf.type.name} "
                    f"column {leaf.path_str}"
                )
            if spec is True:
                out[leaf.path] = (None, 0.01)
            else:
                out[leaf.path] = (spec.get("ndv"), spec.get("fpp", 0.01))
        return out

    def _resolve_sorting(self, schema: Schema, sorting_columns):
        if not sorting_columns:
            return None
        if isinstance(sorting_columns, str):
            sorting_columns = [sorting_columns]
        out = []
        for spec in sorting_columns:
            if isinstance(spec, str):
                key, descending, nulls_first = spec, False, False
            elif (
                isinstance(spec, (tuple, list))
                and len(spec) == 3
                and isinstance(spec[1], (bool, int))
            ):
                key, descending, nulls_first = spec
            else:
                raise WriterError(
                    "writer: sorting_columns entries are dotted leaf names "
                    "or (name, descending, nulls_first) triples"
                )
            leaf = self._leaf(schema, key)
            out.append(
                SortingColumn(
                    column_idx=leaf.leaf_index,
                    descending=bool(descending),
                    nulls_first=bool(nulls_first),
                )
            )
        return out

    def _reset_builders(self) -> None:
        self._builders = {
            leaf.path: ColumnChunkBuilder(leaf, leaf.path in self._dict_columns)
            for leaf in self.schema.leaves
        }
        self._columnar_rows = None

    def _write(self, data: bytes) -> int:
        off = self._pos
        self._f.write(data)
        self._pos += len(data)
        return off

    # -- ingestion -------------------------------------------------------------

    def write_row(self, row: dict) -> None:
        self._check_open()
        if self._columnar_rows is not None:
            raise WriterError("writer: cannot mix write_row and write_column in one row group")
        self._shredder.add_row(row)
        if self._shredder.num_rows % 1000 == 0 and self._estimated_size() >= self.row_group_size:
            self.flush_row_group()

    def write_rows(self, rows) -> None:
        """Bulk ingestion; flat schemas take a batched columnar shred that
        skips the per-row recursive walk (one pass per column per batch)."""
        root = self.schema.root
        if any(
            not c.is_leaf or c.max_rep > 0 or c.max_def > 1 for c in root.children
        ):
            for row in rows:
                self.write_row(row)
            return
        self._check_open()
        if self._columnar_rows is not None:
            raise WriterError(
                "writer: cannot mix write_row and write_column in one row group"
            )
        BATCH = 4096
        batch: list = []
        for row in rows:
            batch.append(row)
            if len(batch) >= BATCH:
                self._write_flat_batch(batch)
                batch.clear()
        if batch:
            self._write_flat_batch(batch)

    def _write_flat_batch(self, batch: list) -> None:
        from .shred import ShredError, _value_size

        # Phase 1 — validate + stage every column WITHOUT touching buffers,
        # so a bad row leaves the writer consistent (a partial append would
        # silently misalign columns and close() would write a corrupt file).
        for row in batch:
            if not isinstance(row, dict):
                raise ShredError(
                    f"shred: row must be a dict, got {type(row).__name__}"
                )
        staged = []
        for leaf in self.schema.root.children:
            name = leaf.name
            if leaf.max_def == 1:
                vals = []
                defs = []
                for row in batch:
                    v = row.get(name)
                    if v is None:
                        defs.append(0)
                    else:
                        defs.append(1)
                        vals.append(v)
            else:
                vals = []
                for row in batch:
                    v = row.get(name)
                    if v is None:
                        raise ShredError(
                            f"shred: required field {leaf.path_str} is None"
                        )
                    vals.append(v)
                defs = [0] * len(batch)
            staged.append((self._shredder.buffers[leaf.path], vals, defs))
        # Phase 2 — commit (list extends cannot fail on valid staged data)
        for buf, vals, defs in staged:
            buf.values.extend(vals)
            buf.def_levels.extend(defs)
            buf.rep_levels.extend([0] * len(batch))
            buf.data_size += sum(_value_size(v) for v in vals)
        self._shredder.num_rows += len(batch)
        if self._estimated_size() >= self.row_group_size:
            self.flush_row_group()

    def write_column(self, path, values, def_levels=None, rep_levels=None) -> None:
        """Columnar fast path for one leaf of the current row group.

        For flat REQUIRED columns pass just `values`; for OPTIONAL pass
        def_levels (or a values array with None handled by caller); for nested
        columns pass explicit def/rep levels (Dremel encoding).
        """
        self._check_open()
        if self._shredder.num_rows:
            raise WriterError("writer: cannot mix write_row and write_column in one row group")
        leaf = self.schema.column(path)
        if not leaf.is_leaf:
            raise WriterError(f"writer: {leaf.path_str} is not a leaf column")
        builder = self._builders[leaf.path]
        builder.set_columnar(values, def_levels, rep_levels)
        n_rows = (
            int((np.asarray(rep_levels) == 0).sum())
            if rep_levels is not None and len(rep_levels)
            else (len(def_levels) if def_levels is not None else len(values))
        )
        if self._columnar_rows is None:
            self._columnar_rows = n_rows
        elif self._columnar_rows != n_rows:
            raise WriterError(
                f"writer: column {leaf.path_str} has {n_rows} rows, "
                f"others have {self._columnar_rows}"
            )

    def _estimated_size(self) -> int:
        total = 0
        for b in self._shredder.buffers.values():
            total += b.data_size + 2 * len(b.def_levels)
        return total

    def estimated_buffered_size(self) -> int:
        """Approximate bytes of the not-yet-flushed row group (the sizing
        input of the auto-flush; public for tools sizing output parts)."""
        return self._estimated_size()

    # -- row group flush -------------------------------------------------------

    def flush_row_group(self, metadata=None, column_metadata=None) -> None:
        """Flush buffered rows/columns as one row group.

        `metadata` ({k: v}) attaches key-value metadata to every column chunk
        of this row group; `column_metadata` ({leaf: {k: v}}) targets single
        columns — the reference's per-flush FlushRowGroupOption KV metadata
        (file_writer.go:156-226, WithRowGroupMetaData[ForColumn])."""
        self._check_open()
        per_col: dict[tuple, dict] = {}
        if metadata or column_metadata:
            if not self._shredder.num_rows and self._columnar_rows is None:
                raise WriterError(
                    "writer: flush_row_group with metadata but nothing buffered "
                    "(an auto-flush may have emptied the buffer)"
                )
            for leaf in self.schema.leaves:
                kv = dict(metadata or {})
                per_col[leaf.path] = kv
            for key, kv in (column_metadata or {}).items():
                per_col.setdefault(self._leaf(self.schema, key).path, {}).update(kv)
        self._flush_kv = per_col
        if self._shredder.num_rows:
            shredded, n_rows = self._shredder.drain()
            for path, (vals, dls, rls) in shredded.items():
                self._builders[path].extend_shredded(vals, dls, rls)
        elif self._columnar_rows is not None:
            n_rows = self._columnar_rows
            missing = [
                l.path_str
                for l in self.schema.leaves
                if self._builders[l.path]._columnar_values is None
            ]
            if missing:
                raise WriterError(f"writer: columnar row group missing columns {missing}")
        else:
            return  # nothing buffered
        chunks = []
        group_indexes: list[tuple] = []
        total_bytes = 0
        total_compressed = 0
        for leaf in self.schema.leaves:
            cc = self._write_chunk(self._builders[leaf.path], n_rows, group_indexes)
            chunks.append(cc)
            total_bytes += cc.meta_data.total_uncompressed_size
            total_compressed += cc.meta_data.total_compressed_size
        if self.write_page_index:
            self._page_indexes.append(group_indexes)
        self._flush_kv = {}
        first_md = chunks[0].meta_data if chunks else None
        first_page_offset = None
        if first_md is not None:
            # file_offset = first page of the group, dictionary page included.
            first_page_offset = (
                first_md.dictionary_page_offset
                if first_md.dictionary_page_offset is not None
                else first_md.data_page_offset
            )
        self._row_groups.append(
            RowGroup(
                columns=chunks,
                total_byte_size=total_bytes,
                total_compressed_size=total_compressed,
                num_rows=n_rows,
                file_offset=first_page_offset,
                sorting_columns=self._sorting,
                ordinal=len(self._row_groups),
            )
        )
        self._reset_builders()

    def _write_chunk(
        self, builder: ColumnChunkBuilder, n_rows: int, group_indexes: list | None = None
    ) -> ColumnChunk:
        column = builder.column
        self._uncompressed_total = 0
        typed = builder.typed_values()
        def_levels = (
            np.asarray(builder.def_levels, dtype=np.uint16)
            if column.max_def > 0
            else None
        )
        rep_levels = (
            np.asarray(builder.rep_levels, dtype=np.uint16)
            if column.max_rep > 0
            else None
        )
        if def_levels is None:
            num_entries = len(typed)
        else:
            num_entries = len(def_levels)
            if builder._columnar_values is not None and len(def_levels) == 0:
                # columnar input for optional column without explicit levels:
                # treat as fully present
                def_levels = np.full(len(typed), column.max_def, dtype=np.uint16)
                num_entries = len(def_levels)
        if rep_levels is not None and len(rep_levels) == 0:
            rep_levels = np.zeros(num_entries, dtype=np.uint16)
        null_count = (
            int((def_levels != column.max_def).sum()) if def_levels is not None else 0
        )

        dict_result = builder.build_dictionary(typed)
        first_offset = self._pos
        dict_offset = None
        encodings = {int(Encoding.RLE)}
        enc_stats: list[PageEncodingStats] = []
        pages_payload: list[tuple] = []

        if dict_result is not None:
            dict_values, indices = dict_result
            header, block = encode_dict_page(
                column, dict_values, int(self.codec), self.with_crc
            )
            dict_offset = self._pos
            self._write_page(header, block)
            encodings.add(int(Encoding.PLAIN))
            encodings.add(int(Encoding.RLE_DICTIONARY))
            enc_stats.append(
                PageEncodingStats(
                    page_type=int(PageType.DICTIONARY_PAGE),
                    encoding=int(Encoding.PLAIN),
                    count=1,
                )
            )
            value_encoding = Encoding.RLE_DICTIONARY
            page_values = indices
            dict_size = len(dict_values)
        else:
            value_encoding = self._column_encodings.get(column.path, Encoding.PLAIN)
            page_values = typed
            dict_size = None

        data_offset = self._pos
        n_pages = 0
        index = (
            _PageIndexBuilder(column, dict_result[0] if dict_result else None)
            if self.write_page_index and group_indexes is not None
            else None
        )
        for v_slice, d_slice, r_slice in self._split_pages(
            page_values, def_levels, rep_levels, column
        ):
            page_offset = self._pos
            if self.data_page_version == 1:
                header, block = encode_data_page_v1(
                    column, v_slice, d_slice, r_slice, value_encoding,
                    int(self.codec), dict_size, self.with_crc,
                )
            else:
                header, block = encode_data_page_v2(
                    column, v_slice, d_slice, r_slice, value_encoding,
                    int(self.codec), dict_size, self.with_crc,
                )
            self._write_page(header, block)
            if index is not None:
                index.add_page(
                    page_offset, self._pos - page_offset, v_slice, d_slice, r_slice
                )
            n_pages += 1
        page_type = (
            int(PageType.DATA_PAGE) if self.data_page_version == 1 else int(PageType.DATA_PAGE_V2)
        )
        encodings.add(int(value_encoding))
        enc_stats.append(
            PageEncodingStats(
                page_type=page_type, encoding=int(value_encoding), count=n_pages
            )
        )
        total_compressed = self._pos - first_offset
        stats = compute_statistics(
            column.type, typed, null_count, column_is_unsigned(column)
        )
        if dict_result is not None:
            # the dictionary IS the distinct set: record the exact count
            stats.distinct_count = len(dict_result[0])
        kv = self._flush_kv.get(column.path)
        md = ColumnMetaData(
            type=int(column.type),
            encodings=sorted(encodings),
            path_in_schema=list(column.path),
            codec=int(self.codec),
            num_values=num_entries,
            total_uncompressed_size=self._uncompressed_total,
            total_compressed_size=total_compressed,
            data_page_offset=data_offset,
            dictionary_page_offset=dict_offset,
            statistics=stats,
            encoding_stats=enc_stats,
            key_value_metadata=(
                [KeyValue(key=k, value=v) for k, v in kv.items()] if kv else None
            ),
        )
        spec = self._bloom_specs.get(column.path)
        if spec is not None:
            hash_src = dict_result[0] if dict_result is not None else typed
            if len(hash_src):
                from .bloom import BloomFilter, bloom_hash_values

                ndv, fpp = spec
                bf = BloomFilter.sized_for(ndv or len(hash_src), fpp)
                bf.insert_hashes(bloom_hash_values(column.type, hash_src))
                self._blooms.append((md, bf))
        # file_offset: where this chunk's pages begin (parquet-cpp's
        # convention; some readers sanity-check it against the page offsets)
        cc = ColumnChunk(
            file_offset=dict_offset if dict_offset is not None else data_offset,
            meta_data=md,
        )
        if index is not None:
            built = index.build()
            if built:
                group_indexes.append((cc, *built))
        return cc

    def _write_page(self, header, block: bytes) -> None:
        hdr = header.dumps()
        self._write(hdr)
        self._write(block)
        self._uncompressed_total += len(hdr) + (header.uncompressed_page_size or 0)

    def _split_pages(self, values, def_levels, rep_levels, column: Column):
        """Split a chunk into page-sized slices (~max_page_size of value data),
        keeping repeated-value rows intact (page boundaries at rep==0)."""
        n = len(def_levels) if def_levels is not None else len(values)
        if n == 0:
            yield values, def_levels, rep_levels
            return
        per_value = self._value_width(values)
        per_page = max(int(self.max_page_size // max(per_value, 1)), 1)
        if n <= per_page:
            yield values, def_levels, rep_levels
            return
        # candidate boundaries: rows (rep==0) if repeated, else any index
        starts = list(range(0, n, per_page)) + [n]
        if rep_levels is not None and len(rep_levels):
            # Page boundaries must fall on row starts (rep == 0) so a row's
            # repeated values never straddle pages.
            row_starts = np.nonzero(np.asarray(rep_levels) == 0)[0]
            fixed = [0]
            for s in starts[1:-1]:
                k = np.searchsorted(row_starts, s, side="left")
                b = int(row_starts[k]) if k < len(row_starts) else n
                if b > fixed[-1]:
                    fixed.append(b)
            if fixed[-1] != n:
                fixed.append(n)
            starts = fixed
        vpos = 0
        for a, b in zip(starts[:-1], starts[1:]):
            if def_levels is not None:
                d_slice = def_levels[a:b]
                nn = int((d_slice == column.max_def).sum())
                v_slice = _slice_values(values, vpos, vpos + nn)
                vpos += nn
            else:
                d_slice = None
                v_slice = _slice_values(values, a, b)
            r_slice = rep_levels[a:b] if rep_levels is not None else None
            yield v_slice, d_slice, r_slice

    @staticmethod
    def _value_width(values) -> int:
        if isinstance(values, ByteArrayData):
            n = len(values)
            return max(int(len(values.data) / n) + 4, 5) if n else 8
        arr = np.asarray(values)
        if arr.ndim == 2:
            return arr.shape[1]
        return max(arr.itemsize, 1)

    # -- lifecycle -------------------------------------------------------------

    _uncompressed_total = 0

    def close(self) -> FileMetaData:
        self._check_open()
        self.flush_row_group()
        # Bloom filters, then page index blobs, live between the last row
        # group and the footer, with metadata fields pointing at them.
        for md, bf in self._blooms:
            blob = bf.to_bytes()
            md.bloom_filter_offset = self._pos
            md.bloom_filter_length = len(blob)
            self._write(blob)
        self._blooms = []
        # (parquet-format PageIndex layout): all ColumnIndexes, then all
        # OffsetIndexes, with ColumnChunk fields pointing at them.
        for group in self._page_indexes:
            for cc, ci, _oi in group:
                blob = ci.dumps()
                cc.column_index_offset = self._pos
                cc.column_index_length = len(blob)
                self._write(blob)
        for group in self._page_indexes:
            for cc, _ci, oi in group:
                blob = oi.dumps()
                cc.offset_index_offset = self._pos
                cc.offset_index_length = len(blob)
                self._write(blob)
        self._page_indexes = []
        meta = FileMetaData(
            version=2,
            schema=self.schema.to_thrift(),
            num_rows=sum(rg.num_rows or 0 for rg in self._row_groups),
            row_groups=self._row_groups,
            created_by=self.created_by,
            key_value_metadata=[
                KeyValue(key=k, value=v) for k, v in self.key_value_metadata.items()
            ]
            or None,
            column_orders=[
                ColumnOrder(TYPE_ORDER=TypeDefinedOrder())
                for _ in self.schema.leaves
            ],
        )
        self._write(serialize_footer(meta))
        if self._owns_file:
            self._f.close()
        else:
            self._f.flush()
        self._closed = True
        return meta

    @property
    def current_file_size(self) -> int:
        """Bytes written so far (reference: file_writer.go:362 CurrentFileSize)."""
        return self._pos

    @property
    def current_row_group_rows(self) -> int:
        return self._shredder.num_rows or (self._columnar_rows or 0)

    @property
    def current_row_group_size(self) -> int:
        """Rough UNCOMPRESSED size of the buffered (unflushed) row group —
        the size-based flush signal (reference: file_writer.go:355
        CurrentRowGroupSize); the flushed bytes will usually be smaller
        once encoded and compressed. Covers both ingestion paths: shredded
        rows still in the Shredder plus columnar data in the builders."""
        return self._estimated_size() + sum(
            b.data_size() for b in self._builders.values()
        )

    def _check_open(self) -> None:
        if self._closed:
            raise WriterError("writer: already closed")

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *rest):
        if not self._closed and exc_type is None:
            self.close()
        elif not self._closed and self._owns_file:
            self._f.close()
        return False


def _slice_values(values, a: int, b: int):
    if isinstance(values, ByteArrayData):
        off = values.offsets
        sub = off[a : b + 1] - off[a]
        return ByteArrayData(offsets=sub, data=values.data[off[a] : off[b]])
    return values[a:b]

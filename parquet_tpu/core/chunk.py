"""Column-chunk read/write: the page walk.

Read side mirrors the reference's chunk_reader.go: seek to the dictionary (or
first data) page offset, walk Thrift page headers until TotalCompressedSize is
consumed (:187-190), at most one dictionary page (:196-228), CRC validation
opt-in (:161-180), every size validated before allocation. Decoded pages are
concatenated into one ChunkData of typed arrays.

Write side mirrors chunk_writer.go: build a dictionary over the whole chunk
with the <= 32767-unique cutoff (:174-209), then emit [dict page] + data pages,
and assemble ColumnMetaData with encodings, stats and offsets (:264-314).
"""

from __future__ import annotations

import time as _time
import zlib
from dataclasses import dataclass

import numpy as np

from ..meta.parquet_types import (
    ColumnChunk,
    ColumnMetaData,
    Encoding,
    PageHeader,
    PageType,
)
from ..meta.thrift import CompactReader, ThriftError
from ..ops.packed_levels import PackedLevels
from ..utils import metrics as _metrics
from ..utils.trace import active as trace_active
from ..utils.trace import bump, span, stage
from .alloc import decoded_nbytes
from .arrays import ByteArrayData
from .compress import decompress_block
from .page import (
    DecodedPage,
    decode_data_page_v1,
    decode_data_page_v2,
    decode_dict_page,
)
from .schema import Column

__all__ = [
    "ChunkData",
    "ChunkError",
    "read_chunk",
    "read_chunk_row_ranges",
    "RawPage",
    "iter_chunk_pages",
    "iter_page_sites",
]

# Page headers are small; peek a bounded window per header read, growing up to
# the max for headers with embedded wide statistics.
_HEADER_PEEK = 1 << 16
_HEADER_PEEK_MAX = 1 << 24


class ChunkError(ValueError):
    pass


@dataclass
class ChunkData:
    """All values of one column chunk, concatenated across pages.

    Levels are uint16 ndarrays by default; readers opened with
    compact_levels=True deliver them as ops.packed_levels.PackedLevels
    (bit-packed at rest, ndarray-operator compatible, widen-on-demand)."""

    column: Column
    num_values: int  # level entries incl. nulls
    values: object  # ndarray | ByteArrayData (non-null cells only)
    def_levels: "np.ndarray | PackedLevels | None"
    rep_levels: "np.ndarray | PackedLevels | None"
    dictionary: object | None = None  # decoded dict page values, if any
    # dictionary-preserving reads only (read_chunk keep_dict_indices=True):
    # int32 indices of the non-null cells; values is None then
    indices: "np.ndarray | None" = None


@dataclass
class RawPage:
    """A page as stored: parsed header + undecoded (still-compressed) payload.

    This is the unit the TPU pipeline batches: headers/offsets on host, payload
    decode on device.
    """

    header: PageHeader
    payload: bytes
    offset: int  # absolute file offset of the page header


_ABSENT = -(1 << 63)  # ptq_parse_page_header's "field absent" sentinel


def _header_from_slots(s) -> PageHeader:
    """Build a PageHeader from the native parser's slot array (layout in
    native/parquet_tpu_native.cc ptq_parse_page_header). Page-header
    statistics are not materialized — they are not consumed on read, matching
    the reference ("not used by parquet-go", README.md:47).

    Construction writes instance __dict__ directly: this runs once per page
    (the hot metadata path, SURVEY §7.3.6) and the generic TStruct kwargs
    __init__ was measurable there.
    """
    from ..meta.parquet_types import (
        DataPageHeader,
        DataPageHeaderV2,
        DictionaryPageHeader,
        IndexPageHeader,
    )

    v = s.tolist()  # one C call instead of 23 np scalar boxings

    def g(i):
        return None if v[i] == _ABSENT else v[i]

    h = PageHeader.__new__(PageHeader)
    h.__dict__.update(
        type=g(1),
        uncompressed_page_size=g(2),
        compressed_page_size=g(3),
        crc=g(4),
        data_page_header=None,
        index_page_header=None,
        dictionary_page_header=None,
        data_page_header_v2=None,
    )
    if v[5] == 1:
        dp = DataPageHeader.__new__(DataPageHeader)
        dp.__dict__.update(
            num_values=g(6),
            encoding=g(7),
            definition_level_encoding=g(8),
            repetition_level_encoding=g(9),
            statistics=None,
        )
        h.data_page_header = dp
    if v[10] == 1:
        sorted_ = g(13)
        dh = DictionaryPageHeader.__new__(DictionaryPageHeader)
        dh.__dict__.update(
            num_values=g(11),
            encoding=g(12),
            is_sorted=None if sorted_ is None else bool(sorted_),
        )
        h.dictionary_page_header = dh
    if v[14] == 1:
        comp = g(21)
        d2 = DataPageHeaderV2.__new__(DataPageHeaderV2)
        d2.__dict__.update(
            num_values=g(15),
            num_nulls=g(16),
            num_rows=g(17),
            encoding=g(18),
            definition_levels_byte_length=g(19),
            repetition_levels_byte_length=g(20),
            is_compressed=None if comp is None else bool(comp),
            statistics=None,
        )
        h.data_page_header_v2 = d2
    if v[22] == 1:
        h.index_page_header = IndexPageHeader()
    return h


def _read_page_header(f) -> PageHeader:
    """Decode one page header from the stream, consuming exactly its bytes.

    Thrift needs lookahead but over-reading would swallow page data (the
    reference solves this with an unbuffered reader, helpers.go:104-106); here
    we peek a bounded window, decode, and seek back to the consumed position.
    One header per page makes this the hot metadata path (SURVEY §7.3.6): the
    native compact-protocol parser handles it when built, falling back to the
    declarative Python reader for corrupt input (exact error messages) or
    when the library is absent.
    """
    from ..utils.native import get_native

    start = f.tell()
    peek = _HEADER_PEEK
    lib = get_native()
    use_native = lib is not None and lib.has_parse_page_header
    while True:
        f.seek(start)
        window = f.read(peek)
        if not window:
            raise ChunkError("chunk: eof reading page header")
        if use_native:
            try:
                slots = lib.parse_page_header(window)
            except ValueError:
                use_native = False  # corrupt: Python reader for its exact error
                continue
            if slots is not None:
                f.seek(start + int(slots[0]))
                return _header_from_slots(slots)
            if len(window) == peek and peek < _HEADER_PEEK_MAX:
                peek *= 8  # truncated window: re-peek larger
                continue
            use_native = False  # truncated file: Python reader for the error
            continue
        r = CompactReader(window)
        try:
            header = PageHeader.read(r)
        except ThriftError as e:
            # A truncated window is indistinguishable from corruption; if the
            # window wasn't exhausted (or can't grow), it really is corrupt.
            if len(window) == peek and peek < _HEADER_PEEK_MAX:
                peek *= 8
                continue
            raise ChunkError(f"chunk: corrupt page header: {e}") from e
        f.seek(start + r.pos)
        return header


def chunk_byte_range(chunk: ColumnChunk) -> tuple[int, int]:
    """Absolute (offset, size) of a chunk's page bytes in the file."""
    md: ColumnMetaData = chunk.meta_data
    if md is None:
        raise ChunkError("chunk: missing metadata")
    if chunk.file_path:
        raise ChunkError("chunk: external column chunks not supported")
    total = md.total_compressed_size
    if total is None or total < 0:
        raise ChunkError("chunk: invalid total_compressed_size")
    offset = md.data_page_offset
    if md.dictionary_page_offset is not None and md.dictionary_page_offset > 0:
        # Chunk starts at the dictionary page when present (reference:
        # chunk_reader.go:317-323). Some writers (pyarrow, empty row groups)
        # leave data_page_offset at 0, which would point at the file magic.
        if offset is None or offset <= 0 or md.dictionary_page_offset < offset:
            offset = md.dictionary_page_offset
    if offset is None or offset <= 0:
        raise ChunkError(f"chunk: invalid page offset {offset}")
    return offset, total


class ChunkWindow:
    """File-like view over one chunk's preloaded bytes, at absolute offsets.

    Lets the page walk (iter_chunk_pages/_read_page_header, which seek/tell
    in file coordinates) run against a buffer fetched with a single pread —
    one I/O per chunk instead of one per page, and no shared file-position
    state, so chunk preparation can run on worker threads.
    """

    __slots__ = ("_mv", "_base", "_pos")

    def __init__(self, buf, base: int):
        self._mv = memoryview(buf)
        self._base = base
        self._pos = 0

    def seek(self, offset: int, whence: int = 0):
        if whence == 0:
            self._pos = offset - self._base
        elif whence == 1:
            self._pos += offset
        else:
            self._pos = len(self._mv) + offset
        return self._base + self._pos

    def tell(self) -> int:
        return self._base + self._pos

    def read(self, n: int = -1):
        """Returns a zero-copy memoryview slice (payloads are ~1 MiB; all
        downstream consumers — thrift reader, codecs, np.frombuffer, crc —
        accept any buffer)."""
        if self._pos < 0 or self._pos > len(self._mv):
            return b""
        end = len(self._mv) if n is None or n < 0 else min(self._pos + n, len(self._mv))
        out = self._mv[self._pos : end]
        self._pos = end
        return out


def iter_page_sites(f, chunk: ColumnChunk):
    """Yield (header_offset, header, header_len, payload_len) for every page
    of a chunk WITHOUT reading payloads — the page-location walk shared by
    parquet-tool verify and the fault harness's page mapper, so the two can
    never disagree about page boundaries. Raises ChunkError on a header that
    cannot be parsed or a page size escaping the chunk's byte range (the
    caller decides whether that ends triage or the read). Size errors carry
    `.stage = "layout"` so triage can classify them without matching
    message text."""
    offset, total = chunk_byte_range(chunk)
    pos = offset
    while pos < offset + total:
        f.seek(pos)
        header = _read_page_header(f)
        hlen = f.tell() - pos
        plen = header.compressed_page_size
        if plen is None or plen < 0:
            # same invariant (and message) as the read path below: an absent
            # size must NOT silently walk on as a 0-byte payload, or triage
            # and the actual read would disagree about page boundaries
            err = ChunkError(f"chunk: invalid compressed page size {plen}")
            err.stage = "layout"
            raise err
        if pos + hlen + plen > offset + total:
            err = ChunkError(
                f"chunk: compressed page size {plen} exceeds chunk bounds"
            )
            err.stage = "layout"
            raise err
        yield pos, header, hlen, plen
        pos += hlen + plen


def iter_chunk_pages(f, chunk: ColumnChunk):
    """Yield RawPage for every page of a chunk (dictionary page first if any)."""
    offset, total = chunk_byte_range(chunk)
    f.seek(offset)
    consumed = 0
    while consumed < total:
        page_start = f.tell()
        header = _read_page_header(f)
        size = header.compressed_page_size
        if size is None or size < 0:
            raise ChunkError(f"chunk: invalid compressed page size {size}")
        with stage("io", size):
            payload = f.read(size)
        if len(payload) != size:
            raise ChunkError("chunk: truncated page payload")
        yield RawPage(header=header, payload=payload, offset=page_start)
        consumed += (f.tell() - page_start)


def read_chunk_row_ranges(
    f,
    chunk: ColumnChunk,
    column: Column,
    offset_index,
    ranges: list,
    num_rows: int,
    validate_crc: bool = False,
    alloc=None,
) -> ChunkData:
    """Decode ONLY the pages covering `ranges` (sorted disjoint row spans),
    using the chunk's OffsetIndex to seek straight to each admitted page —
    non-admitted pages are neither read nor decompressed. Returns a ChunkData
    holding exactly the rows of `ranges`, in order (row-aligned with any
    other column decoded with the same ranges). Flat columns only
    (max_rep == 0): repeated pages interleave rows and values, which range
    slicing by row index cannot express.

    Beyond the reference (which always decodes whole chunks); the payoff is
    selective filtered scans — decode cost proportional to matching pages,
    not file size.
    """
    if column.max_rep > 0:
        raise ChunkError("chunk: range decode requires a flat column")
    md = chunk.meta_data
    codec = md.codec or 0
    locs = offset_index.page_locations or []
    if not locs:
        raise ChunkError("chunk: empty offset index")
    firsts = [loc.first_row_index for loc in locs] + [num_rows]
    dictionary = None
    dict_off = md.dictionary_page_offset
    if dict_off is not None and dict_off > 0 and dict_off < (locs[0].offset or 0):
        f.seek(dict_off)
        header = _read_page_header(f)
        payload = f.read(header.compressed_page_size or 0)
        if validate_crc:
            _check_crc(header, payload)
        if alloc is not None:
            alloc.check(header.uncompressed_page_size or 0)
        block = decompress_block(payload, codec, header.uncompressed_page_size or 0)
        dictionary = decode_dict_page(header, block, column)
        if alloc is not None:
            alloc.register_buffers(dictionary)
    pages: list[DecodedPage] = []
    ri = 0
    n_out = 0
    for k, loc in enumerate(locs):
        a, b = firsts[k], firsts[k + 1]
        while ri < len(ranges) and ranges[ri][1] <= a:
            ri += 1
        if ri >= len(ranges):
            break
        if ranges[ri][0] >= b:
            continue  # page admitted no range: skip without reading
        f.seek(loc.offset)
        header = _read_page_header(f)
        size = header.compressed_page_size or 0
        payload = f.read(size)
        if len(payload) != size:
            raise ChunkError("chunk: truncated page payload")
        if validate_crc:
            _check_crc(header, payload)
        if alloc is not None:
            # ceiling BEFORE decompression, like read_chunk: a header
            # claiming a huge uncompressed size must not allocate
            alloc.check(header.uncompressed_page_size or 0)
        if header.type == int(PageType.DATA_PAGE):
            block = decompress_block(payload, codec, header.uncompressed_page_size or 0)
            dict_size = len(dictionary) if dictionary is not None else None
            est = _precharge(alloc, header.data_page_header, len(block))
            page = decode_data_page_v1(header, block, column, dict_size)
        elif header.type == int(PageType.DATA_PAGE_V2):
            dict_size = len(dictionary) if dictionary is not None else None
            est = _precharge(
                alloc, header.data_page_header_v2, header.uncompressed_page_size or 0
            )
            page = decode_data_page_v2(header, payload, column, dict_size, codec)
        else:
            raise ChunkError(f"chunk: offset index points at page type {header.type}")
        if page.num_values != b - a:
            raise ChunkError(
                f"chunk: page holds {page.num_values} rows, offset index says {b - a}"
            )
        _account_page(alloc, est, page, dictionary)
        page.materialize(dictionary)
        # slice this page down to the admitted rows
        rj = ri
        keep = []
        while rj < len(ranges) and ranges[rj][0] < b:
            s = max(ranges[rj][0], a) - a
            e = min(ranges[rj][1], b) - a
            keep.append((s, e))
            rj += 1
        pages.append(_slice_page(page, keep, column))
        n_out += sum(e - s for s, e in keep)
    data = _concat_pages(column, pages, dictionary)
    if data.num_values != n_out:
        raise ChunkError("chunk: range decode row-count mismatch")
    return data


def _slice_page(page: DecodedPage, keep: list, column: Column) -> DecodedPage:
    """Restrict one decoded flat page to local row spans `keep`."""
    if len(keep) == 1 and keep[0] == (0, page.num_values):
        return page
    dl = page.def_levels
    n = sum(e - s for s, e in keep)
    if dl is None:
        # no nulls: rows ARE value indices
        vals = _concat_value_slices(page.values, keep)
        return DecodedPage(num_values=n, def_levels=None, rep_levels=None, values=vals)
    # nulls: map row spans to value spans via the non-null prefix sum
    prefix = np.zeros(len(dl) + 1, dtype=np.int64)
    np.cumsum(dl == column.max_def, out=prefix[1:])
    vspans = [(int(prefix[s]), int(prefix[e])) for s, e in keep]
    vals = _concat_value_slices(page.values, vspans)
    new_dl = np.concatenate([dl[s:e] for s, e in keep]) if keep else dl[:0]
    return DecodedPage(
        num_values=n, def_levels=new_dl, rep_levels=None, values=vals
    )


def _concat_value_slices(values, spans: list):
    if isinstance(values, ByteArrayData):
        o = values.offsets
        parts = [
            ByteArrayData(
                offsets=o[s : e + 1] - int(o[s]),
                data=values.data[int(o[s]) : int(o[e])],
            )
            for s, e in spans
        ]
        if not parts:
            return ByteArrayData(offsets=np.zeros(1, dtype=np.int64), data=b"")
        return _concat_byte_arrays(parts)  # returns parts[0] unchanged for one
    arr = np.asarray(values)
    if len(spans) == 1:
        s, e = spans[0]
        return arr[s:e]
    return (
        np.concatenate([arr[s:e] for s, e in spans]) if spans else arr[:0]
    )


def _check_crc(header: PageHeader, payload: bytes) -> None:
    if header.crc is None:
        return
    actual = zlib.crc32(payload) & 0xFFFFFFFF
    expected = header.crc & 0xFFFFFFFF
    if actual != expected:
        raise ChunkError(
            f"chunk: page CRC mismatch (stored {expected:#x}, computed {actual:#x})"
        )


def read_chunk(
    f,
    chunk: ColumnChunk,
    column: Column,
    validate_crc: bool = False,
    alloc=None,
    keep_dict_indices: bool = False,
) -> ChunkData:
    """Read and decode all pages of one column chunk (host path).

    keep_dict_indices=True returns ChunkData with `indices` set (and
    values=None) when EVERY data page is dictionary-encoded — the
    dictionary-preserving columnar lane (to_arrow read_dictionary=);
    mixed chunks fall back to materialized values.

    Observability: the whole chunk runs under a "chunk" span (page spans and
    decompress/decode stages nest inside it when a trace is active) and
    feeds the always-on chunk_decode_seconds histogram."""
    t0 = _time.perf_counter()
    with span("chunk", {"column": column.path_str}):
        out = _read_chunk_impl(
            f, chunk, column, validate_crc, alloc, keep_dict_indices
        )
    _metrics.observe("chunk_decode_seconds", _time.perf_counter() - t0)
    return out


def _read_chunk_impl(
    f,
    chunk: ColumnChunk,
    column: Column,
    validate_crc: bool,
    alloc,
    keep_dict_indices: bool,
) -> ChunkData:
    md = chunk.meta_data
    codec = md.codec or 0
    dictionary = None
    pages: list[DecodedPage] = []
    seen_data_values = 0
    deferred_gather = 0
    expected = md.num_values or 0
    # staged (per-page Python) walk: the counterpart of the fused native
    # prepare's prepare_fused_engaged — lets traces attribute a read to a path
    bump("prepare_staged_chunk")
    collecting = trace_active()  # build span args only when someone listens
    page_idx = 0
    for raw in iter_chunk_pages(f, chunk):
        header = raw.header
        if alloc is not None:
            alloc.check(header.uncompressed_page_size or 0)
        ptype = header.type
        with span(
            "page", {"page": page_idx, "type": int(ptype)} if collecting else None
        ):
            if ptype == int(PageType.DICTIONARY_PAGE):
                if dictionary is not None:
                    raise ChunkError("chunk: more than one dictionary page")
                if pages:
                    raise ChunkError("chunk: dictionary page after data pages")
                if validate_crc:
                    _check_crc(header, raw.payload)
                with stage("decompress", len(raw.payload)):
                    block = decompress_block(
                        raw.payload, codec, header.uncompressed_page_size or 0
                    )
                dictionary = decode_dict_page(header, block, column)
                if alloc is not None:
                    alloc.register_buffers(dictionary)
            elif ptype == int(PageType.DATA_PAGE):
                if validate_crc:
                    _check_crc(header, raw.payload)
                with stage("decompress", len(raw.payload)):
                    block = decompress_block(
                        raw.payload, codec, header.uncompressed_page_size or 0
                    )
                dict_size = len(dictionary) if dictionary is not None else None
                est = _precharge(
                    alloc, header.data_page_header, len(block)
                )
                with stage("decode", len(block)):
                    page = decode_data_page_v1(header, block, column, dict_size)
                deferred_gather += _account_page(
                    alloc, est, page, dictionary, keep_dict_indices
                ) or 0
                pages.append(page)  # dict pages materialize at chunk level
                seen_data_values += page.num_values
            elif ptype == int(PageType.DATA_PAGE_V2):
                if validate_crc:
                    _check_crc(header, raw.payload)
                dict_size = len(dictionary) if dictionary is not None else None
                est = _precharge(
                    alloc, header.data_page_header_v2, header.uncompressed_page_size or 0
                )
                with stage("decode", header.uncompressed_page_size or 0):
                    page = decode_data_page_v2(header, raw.payload, column, dict_size, codec)
                deferred_gather += _account_page(
                    alloc, est, page, dictionary, keep_dict_indices
                ) or 0
                pages.append(page)  # dict pages materialize at chunk level
                seen_data_values += page.num_values
            elif ptype == int(PageType.INDEX_PAGE):
                page_idx += 1
                continue  # skip, like the reference
            else:
                raise ChunkError(f"chunk: unknown page type {ptype}")
        page_idx += 1
    if seen_data_values != expected:
        raise ChunkError(
            f"chunk: pages hold {seen_data_values} values, metadata says {expected}"
        )
    if keep_dict_indices and deferred_gather and alloc is not None:
        will_keep = (
            dictionary is not None
            and pages
            and all(p.values is None and p.indices is not None for p in pages)
        )
        if not will_keep:
            # mixed chunk falls back to materialization: charge the gather
            # the per-page accounting deferred
            alloc.check(deferred_gather)
            alloc.register(deferred_gather)
    return _concat_pages(column, pages, dictionary, keep_dict_indices)


def _precharge(alloc, page_header, block_len: int):
    """Bound a page's decode allocations BEFORE they happen: levels (2+2 B)
    plus indices/values (<= 8 B) per header-claimed value, plus the block
    itself. A header claiming a huge num_values trips the ceiling here, not
    in the allocator (validation-before-allocation, reference: alloc.go
    test())."""
    if alloc is None:
        return 0
    n = (page_header.num_values or 0) if page_header is not None else 0
    est = n * 12 + block_len
    alloc.register(est)
    return est


def _account_page(
    alloc, est: int, page: DecodedPage, dictionary, keep_dict_indices=False
) -> None:
    """Swap the pre-charge for the page's actual decoded footprint, charging
    the upcoming dictionary gather before materialize() allocates it (a few
    RLE bytes can gather to n x longest-dict-entry bytes). A dictionary-
    preserving read (keep_dict_indices) never gathers, so only the indices
    themselves are charged — the point of that lane is the small footprint."""
    if alloc is None:
        return 0
    alloc.release(est)
    gather = 0
    if page.indices is not None and isinstance(dictionary, ByteArrayData):
        lengths = np.diff(dictionary.offsets)
        gather = int(lengths[page.indices].sum()) + (len(page.indices) + 1) * 8
    elif page.indices is not None and dictionary is not None:
        gather = len(page.indices) * np.asarray(dictionary).itemsize
    if keep_dict_indices:
        # indices stay indices: the gather is DEFERRED — the caller
        # re-charges it only if the chunk falls back to materialization
        deferred, gather = gather, 0
    alloc.register(
        gather
        + sum(
            decoded_nbytes(b)
            for b in (page.values, page.indices, page.def_levels, page.rep_levels)
        )
    )
    return deferred if keep_dict_indices else 0


def _concat_pages(
    column: Column, pages: list[DecodedPage], dictionary,
    keep_dict_indices: bool = False,
) -> ChunkData:
    num_values = sum(p.num_values for p in pages)
    def_levels = None
    rep_levels = None
    if column.max_def > 0:
        def_levels = _concat([p.def_levels for p in pages], np.uint16)
    if column.max_rep > 0:
        rep_levels = _concat([p.rep_levels for p in pages], np.uint16)
    from ..meta.parquet_types import Type

    if (
        dictionary is not None
        and pages
        and all(p.values is None and p.indices is not None for p in pages)
    ):
        # every data page is dictionary-encoded and still unmaterialized:
        # ONE chunk-level gather instead of a per-page take + a second
        # byte-array concat (halves the copies on dict-string chunks — the
        # dominant cost of materializing dictionary columns)
        idx = (
            np.concatenate([np.asarray(p.indices) for p in pages])
            if len(pages) > 1
            else np.asarray(pages[0].indices)
        )
        if keep_dict_indices:
            return ChunkData(
                column=column,
                num_values=num_values,
                values=None,
                def_levels=def_levels,
                rep_levels=rep_levels,
                dictionary=dictionary,
                indices=idx.astype(np.int32, copy=False),
            )
        try:
            values = (
                dictionary.take(idx)
                if isinstance(dictionary, ByteArrayData)
                else np.asarray(dictionary)[idx]
            )
        except (IndexError, ValueError) as e:
            # corrupt index stream, not a programming error: stay typed
            raise ChunkError(f"chunk: dictionary index out of range: {e}") from e
        return ChunkData(
            column=column,
            num_values=num_values,
            values=values,
            def_levels=def_levels,
            rep_levels=rep_levels,
            dictionary=dictionary,
        )
    if dictionary is not None:
        for p in pages:  # mixed dict/PLAIN chunk: per-page materialize
            p.materialize(dictionary)
    value_parts = [p.values for p in pages]
    if any(isinstance(v, ByteArrayData) for v in value_parts):
        values = _concat_byte_arrays([v for v in value_parts if v is not None])
    else:
        arrs = [np.asarray(v) for v in value_parts if v is not None and len(v)]
        if arrs:
            values = np.concatenate(arrs) if len(arrs) > 1 else arrs[0]
        elif column.type == Type.BYTE_ARRAY:
            values = ByteArrayData(offsets=np.zeros(1, dtype=np.int64), data=b"")
        else:
            values = np.empty(0, dtype=_empty_dtype(column))
    return ChunkData(
        column=column,
        num_values=num_values,
        values=values,
        def_levels=def_levels,
        rep_levels=rep_levels,
        dictionary=dictionary,
    )


def _concat(parts, dtype):
    arrs = [p for p in parts if p is not None]
    if not arrs:
        return np.empty(0, dtype=dtype)
    return np.concatenate(arrs) if len(arrs) > 1 else arrs[0]


def _concat_byte_arrays(parts: list) -> ByteArrayData:
    if len(parts) == 1:
        return parts[0]
    datas = []
    offsets = [np.zeros(1, dtype=np.int64)]
    base = 0
    for p in parts:
        datas.append(p.data)
        offsets.append(p.offsets[1:] + base)
        base += len(p.data)
    return ByteArrayData(offsets=np.concatenate(offsets), data=b"".join(datas))


def _empty_dtype(column: Column):
    from ..meta.parquet_types import Type

    return {
        Type.BOOLEAN: np.bool_,
        Type.INT32: np.int32,
        Type.INT64: np.int64,
        Type.FLOAT: np.float32,
        Type.DOUBLE: np.float64,
    }.get(column.type, np.uint8)

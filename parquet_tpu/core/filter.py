"""Statistics-based row-group pruning + row-level predicate filtering.

The reference writes chunk statistics but deliberately never consumes them
("Page meta data is generally not made available to users and not used by
parquet-go", reference README.md:47). A scan framework should: a predicate
over a sorted or clustered column lets whole row groups be skipped before a
single page is read or decoded — the cheapest decode is the one that never
happens. This module goes beyond the reference's capability set on purpose.

Filters are pyarrow-style conjunctive triples:

    FileReader(path).iter_rows(filters=[("ts", ">=", t0), ("vendor", "==", "v1")])

Pruning is CONSERVATIVE: a row group is skipped only when its written
min/max/null-count statistics prove no row can match. Surviving groups are
decoded normally and the predicate re-checked per row, so the result is
exact regardless of how coarse (or absent) the statistics are.
"""

from __future__ import annotations

import datetime as dt
import decimal
import struct

from ..meta.parquet_types import ConvertedType, Type
from .assembly import logical_kind
from .schema import Schema
from .stats import _PACK

__all__ = ["FilterError", "normalize_filters", "row_group_may_match", "row_matches"]

_OPS = ("==", "!=", "<", "<=", ">", ">=", "is_null", "not_null")

_EPOCH_DATE = dt.date(1970, 1, 1)
_EPOCH_UTC = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)

_UNSIGNED = {
    Type.INT32: struct.Struct("<I"),
    Type.INT64: struct.Struct("<Q"),
}

_UNSIGNED_CT = (
    ConvertedType.UINT_8,
    ConvertedType.UINT_16,
    ConvertedType.UINT_32,
    ConvertedType.UINT_64,
)


class FilterError(ValueError):
    pass


def _is_unsigned(leaf) -> bool:
    lt = leaf.logical_type
    if lt is not None and lt.INTEGER is not None:
        return not lt.INTEGER.isSigned
    return leaf.converted_type in _UNSIGNED_CT


def normalize_filters(schema: Schema, filters) -> list:
    """Validate and resolve [(column, op, value)] against flat leaf columns.

    Each entry carries the value in TWO domains: `row_value` for exact
    per-row comparison (the ergonomic domain iter_rows yields — datetime,
    date, Decimal, str) and `stat_value` for statistics pruning (the
    physical storage domain), or None when this column's statistics cannot
    be ordered safely (INT96, binary-backed DECIMAL, legacy binary min/max).
    """
    out = []
    for f in filters:
        if len(f) == 2:
            name, op = f
            value = None
        else:
            name, op, value = f
        if op not in _OPS:
            raise FilterError(f"filter: unknown op {op!r} (use one of {_OPS})")
        path = tuple(name.split(".")) if isinstance(name, str) else tuple(name)
        try:
            leaf = schema.column(path)
        except Exception as e:
            raise FilterError(f"filter: unknown column {name!r}") from e
        if not leaf.is_leaf or leaf.max_rep > 0:
            raise FilterError(
                f"filter: {name!r} is not a flat leaf column (repeated/nested "
                "columns cannot be pruned by chunk statistics)"
            )
        if op in ("is_null", "not_null"):
            if value is not None:
                raise FilterError(f"filter: {op} takes no value")
            out.append((path, leaf, op, None, None))
            continue
        row_value, stat_value = _coerce_value(leaf, value)
        out.append((path, leaf, op, row_value, stat_value))
    return out


def _coerce_value(leaf, value):
    """(row-domain value, physical stat-domain value or None)."""
    if value is None:
        raise FilterError("filter: comparison against None (use is_null)")
    t = leaf.type
    kind = logical_kind(leaf)
    if kind is not None:
        return _coerce_logical(leaf, kind, value)
    if t in (Type.INT32, Type.INT64):
        v = int(value)
        return v, v
    if t in (Type.FLOAT, Type.DOUBLE):
        v = float(value)
        return v, v
    if t == Type.BOOLEAN:
        v = bool(value)
        return v, v
    b = value.encode("utf-8") if isinstance(value, str) else bytes(value)
    return b, b


def _coerce_logical(leaf, kind, value):
    """Logically-typed columns: rows yield converted Python objects; stats
    store the physical encoding. Produce both."""
    if kind[0] == "uint":
        v = int(value)
        if v < 0:
            raise FilterError("filter: unsigned column takes a non-negative int")
        return v, v
    if kind == "int96":
        if not isinstance(value, dt.datetime):
            raise FilterError("filter: INT96 column takes a datetime")
        if value.tzinfo is None:
            value = value.replace(tzinfo=dt.timezone.utc)
        return value, None  # INT96 byte stats have no usable ordering
    if kind == "decimal":
        v = decimal.Decimal(value)
        scale = leaf.element.scale or (
            leaf.logical_type.DECIMAL.scale if leaf.logical_type and leaf.logical_type.DECIMAL else 0
        )
        if leaf.type in (Type.INT32, Type.INT64):
            unscaled = int(v.scaleb(scale or 0).to_integral_value())
            return v, unscaled
        return v, None  # binary-backed decimals: sign-magnitude bytes unordered
    if kind == "date":
        if isinstance(value, dt.datetime):
            value = value.date()
        if not isinstance(value, dt.date):
            raise FilterError("filter: DATE column takes a date")
        return value, (value - _EPOCH_DATE).days
    if kind[0] == "timestamp":
        _, unit, utc = kind
        if not isinstance(value, dt.datetime):
            raise FilterError("filter: TIMESTAMP column takes a datetime")
        aware = value if value.tzinfo is not None else value.replace(tzinfo=dt.timezone.utc)
        micros = (aware - _EPOCH_UTC) // dt.timedelta(microseconds=1)
        phys = _from_micros(micros, unit)
        if unit == "NANOS":
            import numpy as np

            row_value = np.datetime64(micros * 1000, "ns")  # rows yield datetime64[ns]
        else:
            row_value = aware if utc else aware.replace(tzinfo=None)
        return row_value, phys
    if kind[0] == "time":
        unit = kind[1]
        from ..floor.time import Time

        if isinstance(value, Time):
            nanos = value.nanos
        elif isinstance(value, dt.time):
            nanos = (
                ((value.hour * 60 + value.minute) * 60 + value.second) * 1_000_000_000
                + value.microsecond * 1000
            )
        else:
            raise FilterError("filter: TIME column takes a time or floor.Time")
        phys = nanos // {"MILLIS": 1_000_000, "MICROS": 1_000, "NANOS": 1}[unit]
        if unit == "NANOS":
            row_value = Time.from_nanos(nanos, utc=kind[2])
        else:
            micros = nanos // 1000
            row_value = dt.time(
                micros // 3_600_000_000,
                (micros // 60_000_000) % 60,
                (micros // 1_000_000) % 60,
                micros % 1_000_000,
            )
        return row_value, phys
    raise FilterError(f"filter: unsupported logical type on {leaf.path_str}")


def _from_micros(micros: int, unit: str) -> int:
    if unit == "MILLIS":
        return micros // 1000
    if unit == "NANOS":
        return micros * 1000
    return micros


def _decode_stat(leaf, raw: bytes, legacy: bool):
    """PLAIN-encoded chunk statistic -> comparable physical value."""
    if raw is None:
        return None
    t = leaf.type
    try:
        if t in (Type.INT32, Type.INT64) and _is_unsigned(leaf):
            return _UNSIGNED[t].unpack(raw)[0]
        fmt = _PACK.get(t)
        if fmt is not None:
            return fmt.unpack(raw)[0]
        if t == Type.BOOLEAN:
            return bool(raw[0])
    except (struct.error, IndexError):
        return None  # malformed stats: never prune on them
    if legacy:
        # deprecated min/max used signed-byte comparison for binary in old
        # writers (parquet-format ORDER caveat): unsafe to prune on
        return None
    return bytes(raw)  # byte arrays compare lexicographically (min/max_value)


def row_group_may_match(rg, normalized) -> bool:
    """False only when statistics PROVE no row of the group matches."""
    chunks = {tuple(c.meta_data.path_in_schema or []): c for c in rg.columns or []}
    for path, leaf, op, _row_value, value in normalized:
        cc = chunks.get(path)
        if cc is None or cc.meta_data is None:
            continue
        md = cc.meta_data
        st = md.statistics
        if st is None:
            continue
        null_count = st.null_count
        num_values = md.num_values or 0
        if op == "is_null":
            if null_count == 0:
                return False
            continue
        if op == "not_null":
            if null_count is not None and null_count >= num_values:
                return False
            continue
        if value is None:
            continue  # no orderable physical form for this column's stats
        legacy = st.min_value is None or st.max_value is None
        lo = _decode_stat(leaf, st.min_value if not legacy else st.min, legacy)
        hi = _decode_stat(leaf, st.max_value if not legacy else st.max, legacy)
        if lo is None or hi is None:
            continue
        # NaN bounds make float stats unusable for ordering
        if isinstance(lo, float) and (lo != lo or hi != hi):
            continue
        if op == "==" and (value < lo or value > hi):
            return False
        if op == "<" and lo >= value:
            return False
        if op == "<=" and lo > value:
            return False
        if op == ">" and hi <= value:
            return False
        if op == ">=" and hi < value:
            return False
        # "!=" can only be pruned when lo == hi == value and nothing is null
        if op == "!=" and lo == hi == value and not null_count:
            return False
    return True


def row_matches(row: dict, normalized) -> bool:
    for path, leaf, op, value, _stat_value in normalized:
        v = row.get(path[0]) if len(path) == 1 else _nested_get(row, path)
        if op == "is_null":
            if v is not None:
                return False
            continue
        if op == "not_null":
            if v is None:
                return False
            continue
        if v is None:
            return False
        if isinstance(v, str) and isinstance(value, bytes):
            v = v.encode("utf-8")
        if op == "==" and not v == value:
            return False
        if op == "!=" and not v != value:
            return False
        if op == "<" and not v < value:
            return False
        if op == "<=" and not v <= value:
            return False
        if op == ">" and not v > value:
            return False
        if op == ">=" and not v >= value:
            return False
    return True


def _nested_get(row, path):
    v = row
    for part in path:
        if not isinstance(v, dict):
            return None
        v = v.get(part)
        if v is None:
            return None
    return v

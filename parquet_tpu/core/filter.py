"""Statistics-based row-group pruning + row-level predicate filtering.

The reference writes chunk statistics but deliberately never consumes them
("Page meta data is generally not made available to users and not used by
parquet-go", reference README.md:47). A scan framework should: a predicate
over a sorted or clustered column lets whole row groups be skipped before a
single page is read or decoded — the cheapest decode is the one that never
happens. This module goes beyond the reference's capability set on purpose.

Filters are pyarrow-style conjunctive triples:

    FileReader(path).iter_rows(filters=[("ts", ">=", t0), ("vendor", "==", "v1")])

Pruning is CONSERVATIVE: a row group is skipped only when its written
min/max/null-count statistics prove no row can match. Surviving groups are
decoded normally and the predicate re-checked per row, so the result is
exact regardless of how coarse (or absent) the statistics are.
"""

from __future__ import annotations

import datetime as dt
import decimal
import math
import struct

from ..meta.parquet_types import Type
from .assembly import logical_kind
from .schema import Schema
from .stats import _PACK

__all__ = [
    "FilterError",
    "normalize_filters",
    "normalize_dnf",
    "row_group_may_match",
    "row_matches",
    "dnf_group_may_match",
    "dnf_row_matches",
    "dnf_page_ranges",
]

_OPS = (
    "==", "!=", "<", "<=", ">", ">=", "is_null", "not_null", "in", "not_in",
    "contains",
)

_EPOCH_DATE = dt.date(1970, 1, 1)
_EPOCH_UTC = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)

_UNSIGNED = {
    Type.INT32: struct.Struct("<I"),
    Type.INT64: struct.Struct("<Q"),
}



class FilterError(ValueError):
    pass


def _is_unsigned(leaf) -> bool:
    # one shared definition of UNSIGNED order (stats.py writes with it,
    # this module decodes with it — they must never drift)
    from .stats import column_is_unsigned

    return column_is_unsigned(leaf)


def normalize_filters(schema: Schema, filters) -> list:
    """Validate and resolve [(column, op, value)] against flat leaf columns.

    Each entry carries the value in TWO domains: `row_value` for exact
    per-row comparison (the ergonomic domain iter_rows yields — datetime,
    date, Decimal, str) and a `(stat_lo, stat_hi)` bracket for statistics
    pruning (the physical storage domain), or (None, None) when this
    column's statistics cannot be ordered safely (INT96, binary-backed
    DECIMAL, legacy binary min/max). The bracket satisfies
    stat_lo <= value <= stat_hi with both ends representable physically, so
    an inexact coercion (fractional decimal beyond the column's scale, a
    sub-unit timestamp) straddles the value and pruning stays conservative
    in BOTH comparison directions; stat_lo != stat_hi means no stored value
    can equal the filter value exactly.
    """
    out = []
    for f in filters:
        if len(f) == 2:
            name, op = f
            value = None
        else:
            name, op, value = f
        if op not in _OPS:
            raise FilterError(f"filter: unknown op {op!r} (use one of {_OPS})")
        path = tuple(name.split(".")) if isinstance(name, str) else tuple(name)
        try:
            leaf = schema.column(path)
        except Exception as e:
            raise FilterError(f"filter: unknown column {name!r}") from e
        if op == "contains":
            # list membership: the named field must resolve (through an
            # annotated LIST wrapper, or directly for a legacy repeated
            # leaf) to ONE single-level repeated element leaf. The row
            # domain is the top-level field (rows hold the unwrapped list),
            # so only top-level names are addressable.
            if len(path) != 1:
                raise FilterError(
                    f"filter: contains on {name!r}: only top-level LIST "
                    "columns can be tested for membership"
                )
            leaf = _contains_leaf(name, leaf)
            row_value, stat_lo, stat_hi = _coerce_value(leaf, value)
            out.append((leaf.path, leaf, op, row_value, stat_lo, stat_hi))
            continue
        if not leaf.is_leaf or leaf.max_rep > 0:
            raise FilterError(
                f"filter: {name!r} is not a flat leaf column (repeated/nested "
                "columns cannot be pruned by chunk statistics; use "
                "'contains' for LIST membership)"
            )
        if op in ("is_null", "not_null"):
            if value is not None:
                raise FilterError(f"filter: {op} takes no value")
            out.append((path, leaf, op, None, None, None))
            continue
        if op in ("in", "not_in"):
            # row_value = members in ONE shared row domain (set when
            # hashable, for O(1) membership); vlo = list of (stat_lo,
            # stat_hi) brackets (None when any element's stats are
            # un-orderable — pruning then declines); vhi unused
            if not isinstance(value, (list, tuple, set, frozenset)):
                raise FilterError(f"filter: {op} takes a list/tuple/set of values")
            rows, brackets = [], []
            for v in value:
                rv, lo, hi = _coerce_value(leaf, v)
                rows.append(rv)
                brackets.append((lo, hi))
            if any(lo is None for lo, _ in brackets):
                brackets = None
            rows = _unify_members(rows)
            try:
                members = frozenset(rows)
            except TypeError:
                members = rows  # unhashable member type: linear scan
            out.append((path, leaf, op, members, brackets, None))
            continue
        row_value, stat_lo, stat_hi = _coerce_value(leaf, value)
        out.append((path, leaf, op, row_value, stat_lo, stat_hi))
    return out


def _contains_leaf(name, node):
    """Resolve a top-level field to its single LIST element leaf for a
    'contains' predicate: a legacy repeated leaf IS the element; an
    annotated LIST wrapper descends its single-child chain. Anything else
    (struct elements, multi-level lists, flat leaves) is refused typed."""
    while not node.is_leaf:
        if len(node.children) != 1:
            raise FilterError(
                f"filter: contains on {name!r}: list elements must be a "
                "single leaf column (struct elements cannot be compared)"
            )
        node = node.children[0]
    if node.max_rep != 1:
        raise FilterError(
            f"filter: contains on {name!r}: expected a single-level LIST "
            f"column (element repetition depth is {node.max_rep})"
        )
    return node


def _unify_members(rows: list) -> list:
    """Lift in-list members into ONE comparison domain. TIME coercion is the
    only mixed case: sub-microsecond members become Time, whole-microsecond
    members dt.time — comparing across those is order-dependent, so every
    dt.time member lifts to Time when any Time member exists."""
    from ..floor.time import Time

    if any(isinstance(r, Time) for r in rows) and any(
        isinstance(r, dt.time) and not isinstance(r, Time) for r in rows
    ):
        utc = next(r.utc for r in rows if isinstance(r, Time))
        return [
            Time.from_time(r, utc=utc)
            if isinstance(r, dt.time) and not isinstance(r, Time)
            else r
            for r in rows
        ]
    return rows


def _int_bracket(value):
    """Exact row value + integer floor/ceil bracket for an integer-backed
    physical domain. Accepts int, float, Decimal, or numeric-string values."""
    if isinstance(value, str):
        try:
            v = int(value)
        except ValueError as e:
            raise FilterError(f"filter: integer column takes a number, got {value!r}") from e
        return v, v, v
    try:
        f = math.floor(value)
        c = math.ceil(value)
    except (TypeError, ValueError, OverflowError, ArithmeticError) as e:
        # inf/nan (float or Decimal) and non-numeric values all land here
        raise FilterError(f"filter: cannot compare an integer column against {value!r}") from e
    # keep the caller's exact value for per-row comparison when inexact
    # (int vs float/Decimal compare exactly in Python)
    row = int(value) if f == c else value
    return row, f, c


def _coerce_value(leaf, value):
    """(row-domain value, physical stat floor, physical stat ceil)."""
    if value is None:
        raise FilterError("filter: comparison against None (use is_null)")
    t = leaf.type
    kind = logical_kind(leaf)
    if kind is not None:
        return _coerce_logical(leaf, kind, value)
    if t in (Type.INT32, Type.INT64):
        return _int_bracket(value)
    if t in (Type.FLOAT, Type.DOUBLE):
        v = float(value)
        return v, v, v
    if t == Type.BOOLEAN:
        v = bool(value)
        return v, v, v
    b = value.encode("utf-8") if isinstance(value, str) else bytes(value)
    return b, b, b


def _coerce_logical(leaf, kind, value):
    """Logically-typed columns: rows yield converted Python objects; stats
    store the physical encoding. Produce both."""
    if kind[0] == "uint":
        row, lo, hi = _int_bracket(value)
        if row < 0:
            raise FilterError("filter: unsigned column takes a non-negative int")
        return row, lo, hi
    if kind == "int96":
        if not isinstance(value, dt.datetime):
            raise FilterError("filter: INT96 column takes a datetime")
        if value.tzinfo is None:
            value = value.replace(tzinfo=dt.timezone.utc)
        return value, None, None  # INT96 byte stats have no usable ordering
    if kind == "decimal":
        try:
            v = decimal.Decimal(value)
        except (decimal.InvalidOperation, TypeError, ValueError) as e:
            raise FilterError(f"filter: DECIMAL column takes a number, got {value!r}") from e
        scale = leaf.element.scale or (
            leaf.logical_type.DECIMAL.scale if leaf.logical_type and leaf.logical_type.DECIMAL else 0
        )
        if leaf.type in (Type.INT32, Type.INT64):
            try:
                unscaled = v.scaleb(scale or 0)
                lo = int(unscaled.to_integral_value(rounding=decimal.ROUND_FLOOR))
                hi = int(unscaled.to_integral_value(rounding=decimal.ROUND_CEILING))
            except (decimal.InvalidOperation, OverflowError, ValueError) as e:
                # non-finite (NaN/Infinity) values have no integer bracket
                raise FilterError(f"filter: cannot compare DECIMAL column against {value!r}") from e
            return v, lo, hi
        return v, None, None  # binary-backed decimals: sign-magnitude bytes unordered
    if kind == "date":
        if isinstance(value, dt.datetime):
            value = value.date()
        if not isinstance(value, dt.date):
            raise FilterError("filter: DATE column takes a date")
        days = (value - _EPOCH_DATE).days
        return value, days, days
    if kind[0] == "timestamp":
        _, unit, utc = kind
        if not isinstance(value, dt.datetime):
            raise FilterError("filter: TIMESTAMP column takes a datetime")
        aware = value if value.tzinfo is not None else value.replace(tzinfo=dt.timezone.utc)
        micros = (aware - _EPOCH_UTC) // dt.timedelta(microseconds=1)
        lo, hi = _unit_bracket(micros, unit)
        if unit == "NANOS":
            import numpy as np

            row_value = np.datetime64(micros * 1000, "ns")  # rows yield datetime64[ns]
        else:
            row_value = aware if utc else aware.replace(tzinfo=None)
        return row_value, lo, hi
    if kind[0] == "time":
        unit = kind[1]
        from ..floor.time import Time

        if isinstance(value, Time):
            nanos = value.nanos
        elif isinstance(value, dt.time):
            nanos = (
                ((value.hour * 60 + value.minute) * 60 + value.second) * 1_000_000_000
                + value.microsecond * 1000
            )
        else:
            raise FilterError("filter: TIME column takes a time or floor.Time")
        div = {"MILLIS": 1_000_000, "MICROS": 1_000, "NANOS": 1}[unit]
        lo, hi = nanos // div, -(-nanos // div)
        if unit == "NANOS" or nanos % 1000:
            # NANOS rows yield Time; a sub-microsecond filter value on a
            # MILLIS/MICROS column keeps exact nanos too (dt.time would
            # truncate and flip comparisons) — row_matches converts the
            # row's dt.time to Time before comparing
            row_value = Time.from_nanos(nanos, utc=kind[2])
        else:
            micros = nanos // 1000
            row_value = dt.time(
                micros // 3_600_000_000,
                (micros // 60_000_000) % 60,
                (micros // 1_000_000) % 60,
                micros % 1_000_000,
            )
        return row_value, lo, hi
    raise FilterError(f"filter: unsupported logical type on {leaf.path_str}")


def _unit_bracket(micros: int, unit: str) -> tuple:
    """Floor/ceil of a microsecond instant in the column's stored unit."""
    if unit == "MILLIS":
        return micros // 1000, -(-micros // 1000)
    if unit == "NANOS":
        return micros * 1000, micros * 1000
    return micros, micros


def _decode_stat(leaf, raw: bytes, legacy: bool):
    """PLAIN-encoded chunk statistic -> comparable physical value."""
    if raw is None:
        return None
    t = leaf.type
    try:
        if t in (Type.INT32, Type.INT64) and _is_unsigned(leaf):
            if legacy:
                # deprecated min/max were computed with SIGNED comparison by
                # old writers; decoding them unsigned inverts the ordering for
                # values with the top bit set — unusable for pruning
                return None
            return _UNSIGNED[t].unpack(raw)[0]
        fmt = _PACK.get(t)
        if fmt is not None:
            return fmt.unpack(raw)[0]
        if t == Type.BOOLEAN:
            return bool(raw[0])
    except (struct.error, IndexError):
        return None  # malformed stats: never prune on them
    if legacy:
        # deprecated min/max used signed-byte comparison for binary in old
        # writers (parquet-format ORDER caveat): unsafe to prune on
        return None
    return bytes(raw)  # byte arrays compare lexicographically (min/max_value)


def _bounds_admit(op, vlo, vhi, lo, hi, null_count) -> bool:
    """Whether a [lo, hi] stat range (with null_count) may contain a match
    for op against the [vlo, vhi] bracket of the filter value. Shared by
    row-group pruning (chunk statistics) and page pruning (ColumnIndex).

    [vlo, vhi] brackets the filter value in the stat domain; vlo != vhi
    means the value falls between representable stored values, so each
    comparison uses the end that keeps pruning conservative."""
    if op == "contains":
        # a list can only contain the value if some ELEMENT equals it, and
        # the stats bracket the element values — equality semantics
        op = "=="
    if op == "in":
        # admits iff ANY member could be present ([] provably matches nothing)
        return any(
            _bounds_admit("==", a, b, lo, hi, null_count) for a, b in vlo
        )
    if op == "not_in":
        return True  # a range can't prove every row is in the set
    if op == "==" and (vlo != vhi or vhi < lo or vlo > hi):
        return False  # inexact value: NO stored value can equal it
    if op == "<" and lo >= vhi:
        return False
    if op == "<=" and lo > vlo:
        return False
    if op == ">" and hi <= vlo:
        return False
    if op == ">=" and hi < vhi:
        return False
    # "!=" can only be pruned when lo == hi == value and nothing is null
    if op == "!=" and vlo == vhi and lo == hi == vlo and not null_count:
        return False
    return True


def chunks_by_path(rg) -> dict:
    """{leaf path: ColumnChunk} for one row group, skipping chunks whose
    metadata is absent (mutated/corrupt footers must degrade, not crash)."""
    return {
        tuple(c.meta_data.path_in_schema or []): c
        for c in rg.columns or []
        if c.meta_data is not None
    }


def row_group_may_match(rg, normalized) -> bool:
    """False only when statistics PROVE no row of the group matches."""
    chunks = chunks_by_path(rg)
    for path, leaf, op, _row_value, vlo, vhi in normalized:
        cc = chunks.get(path)
        if cc is None:
            continue
        md = cc.meta_data
        st = md.statistics
        if st is None:
            continue
        null_count = st.null_count
        num_values = md.num_values or 0
        if op == "is_null":
            if null_count == 0:
                return False
            continue
        if op == "not_null":
            if null_count is not None and null_count >= num_values:
                return False
            continue
        if vlo is None:
            continue  # no orderable physical form for this column's stats
        legacy = st.min_value is None or st.max_value is None
        lo = _decode_stat(leaf, st.min_value if not legacy else st.min, legacy)
        hi = _decode_stat(leaf, st.max_value if not legacy else st.max, legacy)
        if lo is None or hi is None:
            continue
        # NaN bounds make float stats unusable for ordering
        if isinstance(lo, float) and (lo != lo or hi != hi):
            continue
        if not _bounds_admit(op, vlo, vhi, lo, hi, null_count):
            return False
    return True


def page_ranges_matching(normalized, indexes, num_rows: int):
    """Row ranges of one row group that may hold matching rows, proven by
    the page index ({path: (ColumnIndex, OffsetIndex)}). Returns a sorted
    disjoint [(start, stop)] list; [(0, num_rows)] when nothing can be
    pruned. Conservative: a range is dropped only when every filter column's
    ColumnIndex PROVES its pages empty of matches."""
    ranges = [(0, num_rows)] if num_rows > 0 else []
    for path, leaf, op, _row_value, vlo, vhi in normalized:
        pair = indexes.get(path)
        if not pair:
            continue
        ci, oi = pair
        if ci is None or oi is None or not oi.page_locations:
            continue
        locs = oi.page_locations
        n_pages = len(locs)
        # a malformed/foreign index (thrift decodes lists independently, so
        # lengths can disagree, and first_row_index can be absent) must
        # degrade to "cannot prune on this column", never crash
        if (
            ci.null_pages is None
            or len(ci.null_pages) != n_pages
            or ci.min_values is None
            or len(ci.min_values) != n_pages
            or ci.max_values is None
            or len(ci.max_values) != n_pages
            or (ci.null_counts and len(ci.null_counts) != n_pages)
            or any(not isinstance(loc.first_row_index, int) for loc in locs)
            or locs[0].first_row_index < 0
            # non-monotonic row indexes would break the sorted-disjoint
            # contract of the range intersection below
            or any(
                b.first_row_index <= a.first_row_index
                for a, b in zip(locs, locs[1:])
            )
        ):
            continue
        nulls = ci.null_counts if ci.null_counts else [None] * n_pages
        keep = []
        for k, loc in enumerate(locs):
            start = loc.first_row_index
            stop = (
                locs[k + 1].first_row_index if k + 1 < n_pages else num_rows
            )
            if stop <= start:
                continue
            if _page_admits(
                leaf, op, vlo, vhi, ci.null_pages[k],
                ci.min_values[k], ci.max_values[k], nulls[k], stop - start,
            ):
                keep.append((start, stop))
        ranges = _intersect_ranges(ranges, keep)
        if not ranges:
            return []
    return _coalesce_ranges(ranges)


def normalize_dnf(schema: Schema, filters) -> list:
    """Normalize a predicate into disjunctive normal form: a list of
    normalized conjunctions (OR of ANDs).

    Accepts pyarrow's convention: a flat list of (column, op, value) triples
    is one conjunction; a list of LISTS of triples is an OR of conjunctions.
    Disambiguation matches pyarrow: an element whose first item is a string
    is a TRIPLE (so JSON-style list-triples like ["id", "==", 3] stay a flat
    conjunction), and only all-list elements with non-string heads form DNF.
    """
    filters = list(filters)  # may be a generator: iterate exactly once
    if filters and all(
        isinstance(c, list) and c and not isinstance(c[0], str) for c in filters
    ):
        return [normalize_filters(schema, c) for c in filters]
    if filters and all(isinstance(c, list) for c in filters) and any(
        not c for c in filters
    ):
        raise FilterError("filter: empty conjunction in OR-of-ANDs form")
    return [normalize_filters(schema, filters)]


def dnf_group_may_match(rg, dnf, bloom_excludes=None, group_index=None) -> bool:
    """A group survives when ANY conjunction admits it (and, when a
    bloom_excludes(i, conjunction) callback is given, isn't bloom-proven
    empty for that conjunction)."""
    for conj in dnf:
        if not row_group_may_match(rg, conj):
            continue
        if bloom_excludes is not None and bloom_excludes(group_index, conj):
            continue
        return True
    return False


def dnf_row_matches(row: dict, dnf) -> bool:
    return any(row_matches(row, conj) for conj in dnf)


def dnf_page_ranges(dnf, indexes, num_rows: int):
    """Union of each conjunction's admitted row ranges."""
    all_ranges: list = []
    for conj in dnf:
        rs = page_ranges_matching(conj, indexes, num_rows)
        if rs == [(0, num_rows)]:
            return rs  # one conjunction admits everything
        all_ranges.extend(rs)
    all_ranges.sort()
    return _coalesce_ranges(all_ranges)


def _coalesce_ranges(rs):
    out: list = []
    for s, e in rs:
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _page_admits(leaf, op, vlo, vhi, is_null_page, min_raw, max_raw, null_count, rows):
    if is_null_page:
        return op == "is_null"
    if op == "is_null":
        return null_count is None or null_count > 0
    if op == "not_null":
        # rows counts ROWS; null_count counts level slots — only the
        # all-null proof is safe, and only for non-repeated columns
        return not (
            leaf.max_rep == 0 and null_count is not None and null_count >= rows
        )
    if vlo is None:
        return True
    lo = _decode_stat(leaf, min_raw, legacy=False)
    hi = _decode_stat(leaf, max_raw, legacy=False)
    if lo is None or hi is None:
        return True
    if isinstance(lo, float) and (lo != lo or hi != hi):
        return True
    return _bounds_admit(op, vlo, vhi, lo, hi, null_count)


def _intersect_ranges(a, b):
    """Intersection of two sorted disjoint [(start, stop)] lists."""
    out = []
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if lo < hi:
            out.append((lo, hi))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _lift_row_value(v, value):
    """Adapt a row value to the filter value's comparison domain."""
    if isinstance(v, str) and isinstance(value, bytes):
        return v.encode("utf-8")
    if isinstance(v, dt.time) and not isinstance(value, dt.time):
        # sub-microsecond TIME filter value on a MILLIS/MICROS column:
        # lift the row into exact-nanos Time space for the comparison
        from ..floor.time import Time

        if isinstance(value, Time):
            return Time.from_time(v, utc=value.utc)
    return v


def row_matches(row: dict, normalized) -> bool:
    for path, leaf, op, value, _vlo, _vhi in normalized:
        v = row.get(path[0]) if len(path) == 1 else _nested_get(row, path)
        if op == "is_null":
            if v is not None:
                return False
            continue
        if op == "not_null":
            if v is None:
                return False
            continue
        if op == "contains":
            # rows hold the unwrapped list under the TOP name (the leaf
            # path addresses the element for stats; normalize_filters pins
            # len-1 user paths, so path[0] is the top field)
            v = row.get(path[0])
            if not isinstance(v, list):
                return False  # null list, or not the expected shape
            if not any(
                e is not None and _lift_row_value(e, value) == value for e in v
            ):
                return False
            continue
        if v is None:
            return False
        if op in ("in", "not_in"):
            # members were unified into one domain at normalize time, so
            # the row value lifts once (against any member), not per member
            if value:
                lifted = _lift_row_value(v, next(iter(value)))
                hit = (
                    lifted in value
                    if isinstance(value, frozenset)
                    else any(lifted == x for x in value)
                )
            else:
                hit = False
            if hit == (op == "not_in"):
                return False
            continue
        v = _lift_row_value(v, value)
        if op == "==" and not v == value:
            return False
        if op == "!=" and not v != value:
            return False
        if op == "<" and not v < value:
            return False
        if op == "<=" and not v <= value:
            return False
        if op == ">" and not v > value:
            return False
        if op == ">=" and not v >= value:
            return False
    return True


def _nested_get(row, path):
    v = row
    for part in path:
        if not isinstance(v, dict):
            return None
        v = v.get(part)
        if v is None:
            return None
    return v

"""Dremel record assembly: the scalar cursor walk and shared value plumbing.

Host-side equivalent of the reference's record-assembly stack
(reference: schema.go:216-312 getData/getNextData, data_store.go:262-309
ColumnStore.get): walks the schema tree with one cursor per leaf and rebuilds
each row's nested structure from the level streams.

The DEFAULT assembly engine lives in core/assembly_vec.py: whole-column
prefix scans over the level streams build an offsets/validity intermediate
and materialize rows by batched slicing, ~10-100x faster than this walk.
The cursor walk here remains as the PQT_VEC_ASSEMBLY=0 fallback, the
engine for shapes the scans cannot prove, and the differential-test oracle
(RecordAssembler iterates through the vectorized engine by default; pass
engine="scalar" to force the walk).

Two output modes:
  raw=True   reference-style nested maps: LIST/MAP annotations are not
             unwrapped ({"list": [{"element": v}]}), byte arrays stay bytes —
             matches what goparquet's NextRow returns.
  raw=False  ergonomic rows: LIST -> Python list, MAP -> dict, UTF8 -> str,
             matching pyarrow's to_pylist() for conformance testing.
"""

from __future__ import annotations

import numpy as np

from ..meta.parquet_types import ConvertedType, FieldRepetitionType, Type
from .arrays import ByteArrayData
from .chunk import ChunkData
from .schema import Column, Schema

__all__ = ["RecordAssembler", "AssemblyError"]


class AssemblyError(ValueError):
    pass


class _LeafCursor:
    __slots__ = (
        "chunk", "pos", "vpos", "max_def", "max_rep", "n", "nvals", "defs", "reps",
    )

    def __init__(self, chunk: ChunkData):
        self.chunk = chunk
        self.pos = 0  # index into level entries
        self.vpos = 0  # index into non-null values
        self.max_def = chunk.column.max_def
        self.max_rep = chunk.column.max_rep
        self.n = chunk.num_values
        try:
            self.nvals = len(chunk.values)
        except TypeError:
            self.nvals = chunk.num_values
        # pre-convert the level arrays ONCE per chunk: a per-entry
        # `int(levels[pos])` pays numpy scalar extraction + int() in the
        # walk's innermost loop; a plain-int list indexes at C speed
        # (~2-3x on the whole walk) and keeps the oracle usable in tests
        d = chunk.def_levels
        r = chunk.rep_levels
        self.defs = np.asarray(d).tolist() if d is not None else None
        self.reps = np.asarray(r).tolist() if r is not None else None

    def peek_def(self) -> int:
        d = self.defs
        return d[self.pos] if d is not None else self.max_def

    def peek_rep(self) -> int:
        r = self.reps
        return r[self.pos] if r is not None else 0

    def exhausted(self) -> bool:
        return self.pos >= self.n

    def advance_null(self) -> None:
        self.pos += 1

    def pop_value(self):
        i = self.vpos
        if i >= self.nvals:
            # fewer values than the def levels promise: typed, not IndexError
            raise AssemblyError(
                f"assembly: {self.chunk.column.path_str}: value stream "
                f"exhausted at {i} (levels promise more)"
            )
        self.vpos += 1
        self.pos += 1
        return self.chunk.values[i]


def _leaf_python_values(node: Column, chunk: ChunkData, raw: bool) -> list:
    """The chunk's non-null values as a Python list (C-speed tolist, string
    decode, logical conversion)."""
    v = chunk.values
    if isinstance(v, ByteArrayData):
        vals = v.to_list()
        if not raw and node.is_string():
            vals = [b.decode("utf-8", errors="replace") for b in vals]
    else:
        arr = np.asarray(v)
        if arr.ndim == 2:  # int96 / fixed rows -> bytes
            vals = [r.tobytes() for r in arr]
        else:
            vals = arr.tolist()
    if not raw and logical_kind(node) is not None:
        conv = convert_logical
        vals = [conv(node, x) for x in vals]
    return vals


def logical_kind(node: Column):
    """The single dispatch point for value-level logical conversions.

    Returns one of None | 'int96' | 'decimal' | 'date' | ('timestamp', unit,
    utc) | ('time', unit, utc). Both convert_logical and the flat fast path
    consult this, so a new conversion cannot silently diverge between the two
    paths.
    """
    ct = node.converted_type
    lt = node.logical_type
    if node.type == Type.INT96:
        return "int96"
    if lt is not None and lt.INTEGER is not None and not lt.INTEGER.isSigned:
        if node.type == Type.INT32:
            return ("uint", 32)
        if node.type == Type.INT64:
            return ("uint", 64)
    if ct in (ConvertedType.UINT_32, ConvertedType.UINT_64):
        return ("uint", 32 if node.type == Type.INT32 else 64)
    if ct == ConvertedType.DECIMAL or (lt is not None and lt.DECIMAL is not None):
        return "decimal"
    if ct == ConvertedType.DATE or (lt is not None and lt.DATE is not None):
        return "date"
    if lt is not None and lt.TIMESTAMP is not None:
        u = lt.TIMESTAMP.unit
        return ("timestamp", u.unit_name() if u is not None else "MICROS",
                bool(lt.TIMESTAMP.isAdjustedToUTC))
    if ct == ConvertedType.TIMESTAMP_MILLIS:
        return ("timestamp", "MILLIS", True)
    if ct == ConvertedType.TIMESTAMP_MICROS:
        return ("timestamp", "MICROS", True)
    if lt is not None and lt.TIME is not None:
        u = lt.TIME.unit
        return ("time", u.unit_name() if u is not None else "MICROS",
                bool(lt.TIME.isAdjustedToUTC))
    if ct == ConvertedType.TIME_MILLIS:
        return ("time", "MILLIS", True)
    if ct == ConvertedType.TIME_MICROS:
        return ("time", "MICROS", True)
    return None


class RecordAssembler:
    """Assembles rows from the leaf chunks of one row group.

    `engine` selects how iteration assembles:
      "auto"    (default) the vectorized engine (core/assembly_vec.py) when
                PQT_VEC_ASSEMBLY != 0 and the level scans can prove the
                shape; the scalar cursor walk otherwise
      "vec"     force the vectorized engine (raises AssemblyError when the
                scans cannot prove the shape)
      "scalar"  force the cursor walk — the differential-test oracle
    """

    def __init__(
        self,
        schema: Schema,
        chunks: dict[tuple, ChunkData],
        raw: bool = False,
        engine: str = "auto",
    ):
        self.schema = schema
        self.raw = raw
        self.engine = engine
        self.chunks = chunks
        # Cursor construction is LAZY: each _LeafCursor tolist()s the full
        # level arrays, which the default (vectorized) iteration path never
        # touches — only the scalar walk pays for its own state.
        self.cursors: dict[tuple, _LeafCursor] | None = None
        self._covered_cache: dict[tuple, bool] = {}
        self._first_leaf_cache: dict[tuple, _LeafCursor] = {}
        self.selected_roots: list[Column] | None = None

    def _ensure_cursors(self) -> None:
        """Build the per-leaf cursors and the static per-node caches (hot
        path: consulted per field per row) on first scalar use."""
        if self.cursors is not None:
            return
        self.cursors = {path: _LeafCursor(c) for path, c in self.chunks.items()}
        self._build_caches(self.schema.root)
        # Only assemble the subtree covered by the provided chunks (projection).
        self.selected_roots = [
            child for child in self.schema.root.children if self._covered(child)
        ]

    def _build_caches(self, node: Column) -> None:
        if node.is_leaf:
            covered = node.path in self.cursors
            if covered:
                self._first_leaf_cache[node.path] = self.cursors[node.path]
        else:
            covered = False
            for c in node.children:
                self._build_caches(c)
                if self._covered_cache[c.path] and not covered:
                    covered = True
                    self._first_leaf_cache[node.path] = self._first_leaf_cache[c.path]
        self._covered_cache[node.path] = covered

    def _covered(self, node: Column) -> bool:
        return self._covered_cache[node.path]

    def _first_leaf(self, node: Column) -> _LeafCursor:
        cur = self._first_leaf_cache.get(node.path)
        if cur is None:
            raise AssemblyError(f"assembly: no selected leaf under {node.path_str}")
        return cur

    def _advance_subtree_null(self, node: Column) -> None:
        if node.is_leaf:
            self.cursors[node.path].advance_null()
            return
        for c in node.children:
            if self._covered(c):
                self._advance_subtree_null(c)

    # -- row iteration ---------------------------------------------------------

    def __iter__(self):
        if self.engine != "scalar":
            from . import assembly_vec

            if self.engine == "vec" or assembly_vec.vec_enabled():
                rc = assembly_vec.assemble_row_columns(
                    self.schema, self.chunks, self.raw
                )
                if rc is not None:
                    # materialize in bounded windows (the scalar walk's
                    # constant-memory streaming contract: only one window
                    # of row dicts is forced live at a time — the column
                    # values themselves are already materialized either way)
                    names, columns, n = rc
                    if not names:
                        return
                    step = 1 << 16
                    for s in range(0, n, step):
                        e = min(s + step, n)
                        yield from assembly_vec._zip_dict_rows(
                            names,
                            [assembly_vec.slice_column(c, s, e) for c in columns],
                        )
                    return
                if self.engine == "vec":
                    raise AssemblyError(
                        "assembly: vectorized engine cannot prove this shape"
                    )
        yield from self._iter_scalar()

    def _iter_scalar(self):
        self._ensure_cursors()
        while True:
            lead = None
            for child in self.selected_roots:
                lead = self._first_leaf(child)
                break
            if lead is None or lead.exhausted():
                return
            yield self.assemble_row()

    def assemble_row(self) -> dict:
        self._ensure_cursors()
        row = {}
        for child in self.selected_roots:
            value = self._read_field(child)
            if value is not _ABSENT:
                row[child.name] = value
        return row

    # -- field assembly --------------------------------------------------------

    def _read_field(self, node: Column):
        """Read one instance of `node` (ancestors known present)."""
        rep = node.repetition
        if rep == FieldRepetitionType.REPEATED:
            return self._read_repeated(node)
        lead = self._first_leaf(node)
        if lead.exhausted():
            raise AssemblyError(f"assembly: leaf exhausted at {node.path_str}")
        d = lead.peek_def()
        if rep == FieldRepetitionType.OPTIONAL and d < node.max_def:
            self._advance_subtree_null(node)
            return None
        return self._read_present(node)

    def _read_present(self, node: Column):
        if node.is_leaf:
            cur = self.cursors[node.path]
            if cur.peek_def() != cur.max_def:
                # present at this node but null deeper — impossible for a leaf
                raise AssemblyError(
                    f"assembly: def level {cur.peek_def()} below leaf max "
                    f"{cur.max_def} at {node.path_str}"
                )
            return self._convert(node, cur.pop_value())
        if not self.raw:
            unwrapped = self._try_unwrap(node)
            if unwrapped is not _ABSENT:
                return unwrapped
        out = {}
        for child in node.children:
            if not self._covered(child):
                continue
            v = self._read_field(child)
            if v is not _ABSENT:
                out[child.name] = v
        return out

    def _read_repeated(self, node: Column):
        """A REPEATED node: zero or more instances -> list."""
        lead = self._first_leaf(node)
        if lead.exhausted():
            raise AssemblyError(f"assembly: leaf exhausted at {node.path_str}")
        d = lead.peek_def()
        if d < node.max_def:
            # zero elements (or null ancestor list wrapper)
            self._advance_subtree_null(node)
            return []
        items = [self._read_present(node)]
        while True:
            if lead.exhausted():
                break
            r = lead.peek_rep()
            if r != node.max_rep:
                break
            items.append(self._read_present(node))
        return items

    # -- ergonomic unwrapping --------------------------------------------------

    def _try_unwrap(self, node: Column):
        ct = node.converted_type
        lt = node.logical_type
        is_list = ct == ConvertedType.LIST or (lt is not None and lt.LIST is not None)
        is_map = ct in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE) or (
            lt is not None and lt.MAP is not None
        )
        if is_list and len(node.children) == 1:
            mid = node.children[0]
            if mid.repetition == FieldRepetitionType.REPEATED and self._covered(mid):
                if mid.is_leaf or len(mid.children) != 1:
                    # 2-level legacy list: repeated element directly
                    return self._read_repeated_unwrapped(mid, unwrap_child=False)
                return self._read_repeated_unwrapped(mid, unwrap_child=True)
        if is_map and len(node.children) == 1:
            kv = node.children[0]
            if (
                kv.repetition == FieldRepetitionType.REPEATED
                and not kv.is_leaf
                and len(kv.children) == 2
                and self._covered(kv)
            ):
                pairs = self._read_repeated(kv)
                try:
                    return {p.get(kv.children[0].name): p.get(kv.children[1].name) for p in pairs}
                except TypeError:
                    # unhashable key (e.g. nested) — fall back to pair list
                    return pairs
        return _ABSENT

    def _read_repeated_unwrapped(self, mid: Column, unwrap_child: bool):
        """LIST middle group: return element values directly."""
        lead = self._first_leaf(mid)
        if lead.exhausted():
            raise AssemblyError("assembly: leaf exhausted in list")
        d = lead.peek_def()
        if d < mid.max_def:
            self._advance_subtree_null(mid)
            return []
        items = []
        while True:
            v = self._read_present(mid)
            if unwrap_child:
                elem = mid.children[0]
                v = v.get(elem.name) if isinstance(v, dict) else v
            items.append(v)
            if lead.exhausted() or lead.peek_rep() != mid.max_rep:
                break
        return items

    # -- value conversion ------------------------------------------------------

    def _convert(self, node: Column, v):
        if self.raw:
            if isinstance(v, np.generic):
                return v.item()
            if isinstance(v, np.ndarray):  # int96 / fixed rows
                return v.tobytes()
            return v
        return convert_logical(node, v)


_NANOTIME_CTOR = None


def _nanotime():
    """floor.Time.from_nanos, imported once (core cannot import floor at
    module load — floor imports core — and a per-value import would sit in
    the decode hot loop)."""
    global _NANOTIME_CTOR
    if _NANOTIME_CTOR is None:
        from ..floor.time import Time

        _NANOTIME_CTOR = Time.from_nanos
    return _NANOTIME_CTOR


def _to_micros(v: int, unit: str) -> int:
    if unit == "MILLIS":
        return v * 1000
    if unit == "NANOS":
        return v // 1000
    return v


_KIND_UNSET = object()


def convert_to_storage(node: Column, v, kind=_KIND_UNSET):
    """Ergonomic Python value -> storage value (the INVERSE of
    convert_logical, same logical_kind dispatch table): datetime ->
    epoch int at the declared unit (exact integer arithmetic — float
    total_seconds() drifts microseconds past ~270 years from epoch),
    date -> days, time -> unit int, Decimal -> unscaled int (or
    big-endian bytes for FLBA/BYTE_ARRAY storage, exact-scale and
    width-fit enforced as ValueError). Raw ints/floats/bytes pass
    through. `kind` takes a precomputed logical_kind(node) so per-chunk
    callers dispatch once. Closes the write_row side of the iter_rows
    round-trip."""
    import datetime as dt
    import decimal

    if kind is _KIND_UNSET:
        kind = logical_kind(node)
    if v is None or isinstance(v, (int, float, np.integer, np.floating)):
        if (
            isinstance(v, (int, np.integer))
            and kind is not None
            and kind[0] == "uint"
        ):
            bits = kind[1]
            u = int(v) & ((1 << bits) - 1)
            return u - (1 << bits) if u >= (1 << (bits - 1)) else u
        return v
    if kind == "decimal" and isinstance(v, decimal.Decimal):
        lt = node.logical_type
        scale = node.element.scale
        if scale is None and lt is not None and lt.DECIMAL is not None:
            scale = lt.DECIMAL.scale
        scale = scale or 0
        scaled = v.scaleb(scale)
        unscaled = int(scaled)
        if scaled != unscaled:
            raise ValueError(
                f"decimal {v} does not fit scale {scale} of "
                f"{node.path_str} exactly"
            )
        try:
            if node.type == Type.FIXED_LEN_BYTE_ARRAY:
                w = node.type_length or 0
                if w <= 0:
                    raise ValueError(
                        f"fixed column {node.path_str} lacks type_length"
                    )
                return unscaled.to_bytes(w, "big", signed=True)
            if node.type == Type.BYTE_ARRAY:
                n = max((unscaled.bit_length() + 8) // 8, 1)
                return unscaled.to_bytes(n, "big", signed=True)
        except OverflowError as e:
            raise ValueError(
                f"decimal {v} does not fit {node.type_length}-byte storage "
                f"of {node.path_str}"
            ) from e
        return unscaled
    if kind == "date" and isinstance(v, dt.date) and not isinstance(v, dt.datetime):
        return (v - dt.date(1970, 1, 1)).days
    if kind is not None and kind[0] == "timestamp":
        unit = kind[1]
        if isinstance(v, np.datetime64):
            ns = int(v.astype("datetime64[ns]").astype(np.int64))
            return ns // {"NANOS": 1, "MICROS": 1_000, "MILLIS": 1_000_000}[unit]
        if isinstance(v, dt.datetime):
            epoch = dt.datetime(
                1970, 1, 1, tzinfo=dt.timezone.utc if v.tzinfo else None
            )
            delta = v - epoch
            micros = (
                delta.days * 86_400_000_000
                + delta.seconds * 1_000_000
                + delta.microseconds
            )
            return {
                "MILLIS": micros // 1_000,
                "MICROS": micros,
                "NANOS": micros * 1_000,
            }[unit]
    if kind is not None and kind[0] == "time":
        nanos = None
        if isinstance(v, dt.time):
            nanos = (
                (v.hour * 3600 + v.minute * 60 + v.second) * 10**9
                + v.microsecond * 1_000
            )
        elif hasattr(v, "nanos"):  # floor.Time
            nanos = int(v.nanos)
        if nanos is not None:
            return nanos // {"NANOS": 1, "MICROS": 1_000, "MILLIS": 1_000_000}[
                kind[1]
            ]
    return v


def convert_logical(node: Column, v):
    """Storage value -> ergonomic Python value by logical type, matching
    pyarrow's to_pylist() conventions (DATE -> date, TIMESTAMP -> datetime,
    TIME -> time, DECIMAL -> Decimal, INT96 -> datetime, UTF8 -> str).
    Dispatch comes from logical_kind() — the shared table with the flat path."""
    import datetime as dt
    import decimal

    if isinstance(v, bytes) and node.is_string():
        return v.decode("utf-8", errors="replace")
    kind = logical_kind(node)
    if kind == "int96" and isinstance(v, (np.ndarray, bytes)):
        from ..utils.int96 import int96_to_datetime

        return int96_to_datetime(bytes(v))
    if isinstance(v, np.ndarray):
        v = v.tobytes()
    if isinstance(v, np.generic):
        v = v.item()
    if kind is None:
        return v
    if kind[0] == "uint":
        # UINT(32/64) logical annotation on a signed physical type: the bit
        # pattern reinterprets unsigned (pyarrow to_pylist parity)
        return int(v) & ((1 << kind[1]) - 1)
    if kind == "decimal":
        lt = node.logical_type
        scale = node.element.scale
        if scale is None and lt is not None and lt.DECIMAL is not None:
            scale = lt.DECIMAL.scale
        scale = scale or 0
        if isinstance(v, bytes):
            unscaled = int.from_bytes(v, "big", signed=True) if v else 0
        else:
            unscaled = int(v)
        return decimal.Decimal(unscaled).scaleb(-scale)
    if kind == "date":
        return dt.date(1970, 1, 1) + dt.timedelta(days=int(v))
    if kind[0] == "timestamp":
        _, unit, utc = kind
        if unit == "NANOS":
            # datetime caps at microseconds; numpy datetime64[ns] carries the
            # full precision (the reference's time.Time is nanosecond-native)
            return np.datetime64(int(v), "ns")
        tz = dt.timezone.utc if utc else None
        return dt.datetime(1970, 1, 1, tzinfo=tz) + dt.timedelta(
            microseconds=_to_micros(int(v), unit)
        )
    if kind[0] == "time":
        if kind[1] == "NANOS":
            # datetime.time cannot hold nanoseconds; the floor Time type
            # keeps them (reference: floor/time.go:10-13)
            return _nanotime()(int(v), utc=kind[2])
        micros = _to_micros(int(v), kind[1])
        return dt.time(
            hour=micros // 3_600_000_000,
            minute=(micros // 60_000_000) % 60,
            second=(micros // 1_000_000) % 60,
            microsecond=micros % 1_000_000,
        )
    return v


class _Absent:
    __slots__ = ()

    def __repr__(self):
        return "<absent>"


_ABSENT = _Absent()

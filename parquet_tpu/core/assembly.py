"""Dremel record assembly: repetition/definition levels -> nested rows.

Host-side equivalent of the reference's record-assembly stack
(reference: schema.go:216-312 getData/getNextData, data_store.go:262-309
ColumnStore.get): walks the schema tree with one cursor per leaf and rebuilds
each row's nested structure from the level streams.

Two output modes:
  raw=True   reference-style nested maps: LIST/MAP annotations are not
             unwrapped ({"list": [{"element": v}]}), byte arrays stay bytes —
             matches what goparquet's NextRow returns.
  raw=False  ergonomic rows: LIST -> Python list, MAP -> dict, UTF8 -> str,
             matching pyarrow's to_pylist() for conformance testing.
"""

from __future__ import annotations

import numpy as np

from ..meta.parquet_types import ConvertedType, FieldRepetitionType, Type
from .arrays import ByteArrayData, _ext
from .chunk import ChunkData
from .schema import Column, Schema

__all__ = ["RecordAssembler", "AssemblyError"]


class AssemblyError(ValueError):
    pass


# dtype chars the C dict_rows array-elems path accepts, with the itemsize it
# assumes for each (mirrors pyext.c's format check so ineligible arrays fall
# back to the tolist path instead of raising)
_ARR_ELEM_SIZES = {
    "b": 1, "B": 1, "?": 1, "h": 2, "H": 2, "i": 4, "I": 4, "f": 4,
    "l": 8, "L": 8, "q": 8, "Q": 8, "d": 8,
}


class _LeafCursor:
    __slots__ = ("chunk", "pos", "vpos", "max_def", "max_rep", "n")

    def __init__(self, chunk: ChunkData):
        self.chunk = chunk
        self.pos = 0  # index into level entries
        self.vpos = 0  # index into non-null values
        self.max_def = chunk.column.max_def
        self.max_rep = chunk.column.max_rep
        self.n = chunk.num_values

    def peek_def(self) -> int:
        d = self.chunk.def_levels
        return int(d[self.pos]) if d is not None else self.max_def

    def peek_rep(self) -> int:
        r = self.chunk.rep_levels
        return int(r[self.pos]) if r is not None else 0

    def exhausted(self) -> bool:
        return self.pos >= self.n

    def advance_null(self) -> None:
        self.pos += 1

    def pop_value(self):
        i = self.vpos
        self.vpos += 1
        self.pos += 1
        return self.chunk.values[i]


def _leaf_python_values(node: Column, chunk: ChunkData, raw: bool) -> list:
    """The chunk's non-null values as a Python list (C-speed tolist, string
    decode, logical conversion)."""
    v = chunk.values
    if isinstance(v, ByteArrayData):
        vals = v.to_list()
        if not raw and node.is_string():
            vals = [b.decode("utf-8", errors="replace") for b in vals]
    else:
        arr = np.asarray(v)
        if arr.ndim == 2:  # int96 / fixed rows -> bytes
            vals = [r.tobytes() for r in arr]
        else:
            vals = arr.tolist()
    if not raw and logical_kind(node) is not None:
        conv = convert_logical
        vals = [conv(node, x) for x in vals]
    return vals


def _flat_column_values(node: Column, chunk: ChunkData, raw: bool) -> list:
    """One flat leaf column as a row-aligned Python list (nulls expanded)."""
    vals = _leaf_python_values(node, chunk, raw)
    if node.max_def == 1 and chunk.def_levels is not None:
        mask = chunk.def_levels == 1
        full = [None] * chunk.num_values
        it = iter(vals)
        for idx in np.nonzero(mask)[0]:
            full[idx] = next(it)
        vals = full
    return vals


def _flat_columns(chunks: dict[tuple, ChunkData], raw: bool):
    """(names, column value lists, n_rows) for flat schemas (no groups, no
    repetition) — per-column null-expansion at C speed via ndarray.tolist().
    None when the shape needs more than that."""
    cols = []
    for path, chunk in chunks.items():
        node = chunk.column
        if len(path) != 1 or not node.is_leaf or node.max_rep > 0 or node.max_def > 1:
            return None
        cols.append((node, chunk))
    n = None
    for _node, chunk in cols:
        if n is None:
            n = chunk.num_values
        elif n != chunk.num_values:
            return None
    if n is None:
        return [], [], 0
    names = [node.name for node, _ in cols]
    return names, [_flat_column_values(node, chunk, raw) for node, chunk in cols], n


def fast_flat_rows(chunks: dict[tuple, ChunkData], raw: bool):
    """Vectorized row assembly for flat schemas (the recursive assembler
    costs ~14 us/row; this is one zip at C speed). None when the shape needs
    the full Dremel walk."""
    fc = _flat_columns(chunks, raw)
    if fc is None:
        return None
    names, columns, _n = fc
    if not names:
        return []
    return _zip_dict_rows(names, columns)


def _list_wrapper(top: Column):
    """The repeated middle group of a canonical LIST wrapper, or None."""
    ct = top.converted_type
    lt = top.logical_type
    is_list = ct == ConvertedType.LIST or (lt is not None and lt.LIST is not None)
    if not is_list or len(top.children) != 1:
        return None
    mid = top.children[0]
    if mid.repetition != FieldRepetitionType.REPEATED or mid.max_rep != 1:
        return None
    return mid


def _canonical_list_nodes(top: Column, chunks) -> tuple | None:
    """(mid, leaf) when `top` is a canonical LIST of scalars whose single
    leaf chunk is present: 3-level {top (LIST) -> repeated mid -> leaf} or
    2-level legacy {top -> repeated leaf}. None otherwise."""
    mid = _list_wrapper(top)
    if mid is None:
        return None
    if mid.is_leaf:
        return (mid, mid) if mid.path in chunks else None  # 2-level legacy
    if len(mid.children) != 1:
        return None
    leaf = mid.children[0]
    if not leaf.is_leaf or leaf.max_rep != 1:
        return None
    return (mid, leaf) if leaf.path in chunks else None


def _list_column_values(top: Column, mid: Column, leaf: Column,
                        chunk: ChunkData, raw: bool) -> list | None:
    """Vectorized assembly of one canonical LIST-of-scalars column.

    Entry classification is pure ndarray math on the level arrays; only the
    final per-row slice-to-list runs in Python (two ops per row). The
    recursive cursor walk costs ~10 us per ELEMENT; this costs ~0.3 us per
    row + C-speed element copies.
    """
    dfl = chunk.def_levels
    rep = chunk.rep_levels
    if dfl is None or rep is None:
        return None
    row_start = np.flatnonzero(rep == 0)
    n_rows = len(row_start)
    if n_rows == 0:
        return []
    # plain numeric leaf with no logical conversion: keep the ndarray — the
    # C dict_rows builds each row's element list straight from the buffer,
    # skipping the whole-column tolist() (the assembly hot path's largest
    # single cost on LIST<numeric> columns)
    arr = None
    if (
        _ext is not None
        and not isinstance(chunk.values, ByteArrayData)
        and (raw or logical_kind(leaf) is None)
    ):
        a = np.asarray(chunk.values)
        if (
            a.ndim == 1
            and a.dtype.isnative
            and _ARR_ELEM_SIZES.get(a.dtype.char) == a.dtype.itemsize
        ):
            arr = np.ascontiguousarray(a)
    vals = arr if arr is not None else _leaf_python_values(leaf, chunk, raw)
    has_elem = dfl >= mid.max_def  # entry carries an element (maybe null)
    n_elem = int(has_elem.sum())
    if mid is leaf:
        if len(vals) != n_elem:
            raise AssemblyError(
                f"assembly: {leaf.path_str}: {len(vals)} values for "
                f"{n_elem} elements"
            )
        elems = vals
    else:
        is_val_within = dfl[has_elem] == leaf.max_def
        n_present = int(is_val_within.sum())
        if len(vals) != n_present:
            raise AssemblyError(
                f"assembly: {leaf.path_str}: {len(vals)} values for "
                f"{n_present} present elements"
            )
        if n_present == n_elem:
            elems = vals  # no null elements: the value list IS the entry list
        else:
            full = np.empty(n_elem, dtype=object)  # initialized to None
            full[is_val_within] = (
                arr.tolist() if arr is not None else vals
            )
            elems = full.tolist()
    # per-row element counts WITHOUT a full cumsum/bincount pass: a
    # no-element marker (null/empty list) appears only as a row's single
    # record, so count = segment length minus that one marker
    seg_len = np.diff(np.append(row_start, len(rep)))
    counts = seg_len - np.where(has_elem[row_start], 0, 1)
    offsets = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    if int(offsets[-1]) != n_elem:
        raise AssemblyError(
            f"assembly: {leaf.path_str}: inconsistent repetition levels"
        )
    first_def = dfl[row_start]
    if _ext is not None:
        # defer the per-row slicing: dict_rows slices elements straight into
        # each row dict (one pass instead of slice-list + dict-zip)
        all_present = top.max_def == 0 or bool((first_def >= top.max_def).all())
        mask = None if all_present else (first_def < top.max_def).astype(np.uint8)
        return ("slices", elems, offsets, mask)
    return _rows_from_entries(top, first_def, elems, offsets)


def _canonical_list_of_struct_nodes(top: Column, chunks) -> tuple | None:
    """(mid, elem, leaves) when `top` is a canonical LIST whose element is a
    group of scalar leaves, all present in chunks; None otherwise."""
    mid = _list_wrapper(top)
    if mid is None or mid.is_leaf or len(mid.children) != 1:
        return None
    elem = mid.children[0]
    if elem.is_leaf or elem.max_rep != 1:
        return None
    leaves = [c for c in elem.children if c.path in chunks]
    if not leaves or any(not c.is_leaf or c.max_rep != 1 for c in leaves):
        return None
    return mid, elem, leaves


def _list_of_struct_column_values(top: Column, mid: Column, elem: Column,
                                  leaves: list, chunks, raw: bool):
    """Vectorized assembly of LIST<struct-of-scalars> (e.g. list[Point]).

    Entry structure (row boundaries, element presence, struct nullity) comes
    from the FIRST leaf's level arrays; each leaf contributes a row-aligned
    element array; elements zip into dicts at C speed.
    """
    first = chunks[leaves[0].path]
    dfl0, rep0 = first.def_levels, first.rep_levels
    if dfl0 is None or rep0 is None:
        return None
    row_start = np.flatnonzero(rep0 == 0)
    n_rows = len(row_start)
    if n_rows == 0:
        return []
    has_elem = dfl0 >= mid.max_def  # entry carries a (maybe-null) element
    elem_present = dfl0 >= elem.max_def  # the struct itself is non-null
    n_elem = int(has_elem.sum())
    cols = []
    for leaf in leaves:
        chunk = chunks[leaf.path]
        dfl = chunk.def_levels
        if dfl is None or len(dfl) != len(dfl0):
            return None
        vals = _leaf_python_values(leaf, chunk, raw)
        present = dfl[has_elem] == leaf.max_def
        if len(vals) != int(present.sum()):
            raise AssemblyError(
                f"assembly: {leaf.path_str}: {len(vals)} values for "
                f"{int(present.sum())} present entries"
            )
        full = np.empty(n_elem, dtype=object)
        full[present] = vals
        cols.append((leaf.name, full.tolist()))
    names = [name for name, _ in cols]
    structs = _zip_dict_rows(names, [v for _, v in cols])
    # null struct elements (def between mid and elem thresholds)
    null_elem = ~elem_present[has_elem]
    if null_elem.any():
        for i in np.flatnonzero(null_elem).tolist():
            structs[i] = None
    row_of = np.cumsum(rep0 == 0) - 1
    counts = np.bincount(row_of[has_elem], minlength=n_rows)
    offsets = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    return _rows_from_entries(top, dfl0[row_start], structs, offsets)


def _rows_from_entries(top: Column, first_def, elems_list: list, offsets) -> list:
    """Slice per-entry element values into per-row lists, applying null-row
    detection from the first entry's definition level (shared tail of the
    LIST / MAP / LIST<struct> vectorized paths)."""
    all_present = top.max_def == 0 or bool((first_def >= top.max_def).all())
    if _ext is not None:
        mask = None if all_present else (first_def < top.max_def).astype(np.uint8)
        return _ext.rows_from_slices(elems_list, np.ascontiguousarray(offsets), mask)
    off = offsets.tolist()
    if all_present:
        return [elems_list[a:b] for a, b in zip(off[:-1], off[1:])]
    null_row = (first_def < top.max_def).tolist()
    return [
        None if is_null else elems_list[a:b]
        for is_null, a, b in zip(null_row, off[:-1], off[1:])
    ]


def _col_len(col) -> int:
    """Row count of a column value list or a deferred slices spec."""
    if isinstance(col, tuple):
        return len(col[2]) - 1
    return len(col)


def _zip_dict_rows(names: list, columns: list) -> list:
    """Zip column value lists (or deferred slices specs, see
    _list_column_values) into row dicts — C fast path when built; specs are
    only produced when it is. Very wide tables (>256 columns, past the C
    helper's stack table) take the Python zip."""
    if _ext is not None and len(names) <= 256:
        return _ext.dict_rows(tuple(names), tuple(columns))
    columns = [
        _rows_from_entries_spec(c) if isinstance(c, tuple) else c for c in columns
    ]
    return [dict(zip(names, row)) for row in zip(*columns)]


def _rows_from_entries_spec(spec) -> list:
    """Materialize a deferred ("slices", elems, offsets, mask) column."""
    _tag, elems, offsets, mask = spec
    if isinstance(elems, np.ndarray):  # array-backed spec (C path skipped)
        # convert only this window's element range (a window-sliced spec
        # keeps the FULL elems array with absolute offsets — a whole-column
        # tolist here would repeat per window)
        base = int(offsets[0]) if len(offsets) else 0
        elems = elems[base : int(offsets[-1]) if len(offsets) else 0].tolist()
        offsets = offsets - base
    off = offsets.tolist()
    if mask is None:
        return [elems[a:b] for a, b in zip(off[:-1], off[1:])]
    return [
        None if m else elems[a:b]
        for m, a, b in zip(mask.tolist(), off[:-1], off[1:])
    ]


def _canonical_map_nodes(top: Column, chunks) -> tuple | None:
    """(kv, key, value) when `top` is a canonical MAP of scalar key/value
    with both leaf chunks present; None otherwise."""
    ct = top.converted_type
    lt = top.logical_type
    is_map = ct in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE) or (
        lt is not None and lt.MAP is not None
    )
    if not is_map or len(top.children) != 1:
        return None
    kv = top.children[0]
    if (
        kv.repetition != FieldRepetitionType.REPEATED
        or kv.max_rep != 1
        or len(kv.children) != 2
    ):
        return None
    key, value = kv.children
    if not (key.is_leaf and value.is_leaf):
        return None
    # the vectorized path assumes spec-compliant maps: REQUIRED keys, one
    # level of repetition; legacy files that violate this (optional keys
    # under MAP_KEY_VALUE) fall back to the Dremel assembler
    if key.repetition != FieldRepetitionType.REQUIRED:
        return None
    if key.max_rep != 1 or value.max_rep != 1:
        return None
    if key.path not in chunks or value.path not in chunks:
        return None
    return kv, key, value


def _map_column_values(top: Column, kv: Column, key: Column, value: Column,
                       kchunk: ChunkData, vchunk: ChunkData, raw: bool):
    """Vectorized assembly of one canonical MAP-of-scalars column into row
    dicts (same entry math as _list_column_values; keys are REQUIRED within
    a present key_value entry, values may be null)."""
    kdfl, krep = kchunk.def_levels, kchunk.rep_levels
    vdfl = vchunk.def_levels
    if kdfl is None or krep is None or vdfl is None:
        return None
    if len(kdfl) != len(vdfl):
        return None
    row_start = np.flatnonzero(krep == 0)
    n_rows = len(row_start)
    if n_rows == 0:
        return []
    has_kv = kdfl >= kv.max_def
    n_kv = int(has_kv.sum())
    keys = _leaf_python_values(key, kchunk, raw)
    if len(keys) != n_kv:
        raise AssemblyError(
            f"assembly: {key.path_str}: {len(keys)} keys for {n_kv} map entries"
        )
    vals = _leaf_python_values(value, vchunk, raw)
    velems = np.empty(n_kv, dtype=object)
    present = vdfl[has_kv] == value.max_def
    if len(vals) != int(present.sum()):
        raise AssemblyError(
            f"assembly: {value.path_str}: {len(vals)} values for "
            f"{int(present.sum())} present entries"
        )
    velems[present] = vals
    row_of = np.cumsum(krep == 0) - 1
    counts = np.bincount(row_of[has_kv], minlength=n_rows)
    offsets = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    pairs = list(zip(keys, velems.tolist()))
    rows = _rows_from_entries(top, kdfl[row_start], pairs, offsets)
    return [None if r is None else dict(r) for r in rows]


def _struct_column_values(top: Column, chunks, raw: bool):
    """Vectorized assembly of a non-repeated group of scalar leaves.

    Every selected leaf expands to a row-aligned list; the struct itself is
    None on rows where its definition level shows the group absent (read
    from any selected leaf's def levels). Returns None when the shape
    doesn't fit (repeated/nested children)."""
    if top.max_rep != 0:
        return None
    leaves = []
    for child in top.children:
        if not child.is_leaf or child.max_rep != 0:
            return None
        if child.path in chunks:
            leaves.append(child)
    if not leaves:
        return None
    first = chunks[leaves[0].path]
    if first.def_levels is None and top.max_def > 0:
        return None
    n = first.num_values
    cols = []
    for leaf in leaves:
        chunk = chunks[leaf.path]
        if chunk.num_values != n:
            return None
        vals = _leaf_python_values(leaf, chunk, raw)
        if leaf.max_def > 0 and chunk.def_levels is not None:
            present = chunk.def_levels == leaf.max_def
            if int(present.sum()) != len(vals):
                raise AssemblyError(
                    f"assembly: {leaf.path_str}: {len(vals)} values for "
                    f"{int(present.sum())} present entries"
                )
            full = np.empty(n, dtype=object)
            full[present] = vals
            vals = full.tolist()
        cols.append((leaf.name, vals))
    names = [name for name, _ in cols]
    rows = _zip_dict_rows(names, [v for _, v in cols])
    if top.max_def > 0:
        # struct is null where the def level sits below its own max_def
        null_mask = (first.def_levels < top.max_def).tolist()
        rows = [None if is_null else r for is_null, r in zip(null_mask, rows)]
    return rows


def fast_row_columns(schema: Schema, chunks: dict[tuple, ChunkData], raw: bool):
    """Column-oriented vectorized assembly for flat schemas plus canonical
    LIST-of-scalars and MAP-of-scalars columns (the overwhelmingly common
    nested shapes). Returns (names, columns, n_rows) where each column is a
    row-aligned value list or a deferred ("slices", ...) spec (see
    _list_column_values) that _zip_dict_rows materializes — callers may
    window-slice columns to bound live row objects. None when any column
    needs the full Dremel walk (deep nesting, structs, non-compliant legacy
    maps, raw-mode nested columns — raw rows carry the wire shape the
    vectorized path doesn't build)."""
    flat_cols = _flat_columns(chunks, raw)
    if flat_cols is not None:
        names, columns, n = flat_cols
        return names, columns, n
    if raw:
        return None
    by_top: dict[str, list] = {}
    for path in chunks:
        by_top.setdefault(path[0], []).append(path)
    columns = []  # (name, value list | slices spec)
    n_rows = None
    for top in schema.root.children:
        paths = by_top.get(top.name)
        if not paths:
            continue  # not selected
        if top.is_leaf and top.max_rep == 0 and top.max_def <= 1:
            columns.append((top.name, _flat_column_values(top, chunks[paths[0]], raw)))
        else:
            ln = _canonical_list_nodes(top, chunks)
            if ln is not None and len(paths) == 1:
                mid, leaf = ln
                vals = _list_column_values(top, mid, leaf, chunks[paths[0]], raw)
            else:
                mn = _canonical_map_nodes(top, chunks)
                if mn is not None and len(paths) == 2:
                    kv, key, value = mn
                    vals = _map_column_values(
                        top, kv, key, value, chunks[key.path], chunks[value.path], raw
                    )
                elif (
                    (ls := _canonical_list_of_struct_nodes(top, chunks)) is not None
                    and len(paths) == len(ls[2])
                ):
                    mid, elem, leaves = ls
                    vals = _list_of_struct_column_values(
                        top, mid, elem, leaves, chunks, raw
                    )
                elif not top.is_leaf:
                    vals = _struct_column_values(top, chunks, raw)
                else:
                    return None
            if vals is None:
                return None
            columns.append((top.name, vals))
        if n_rows is None:
            n_rows = _col_len(columns[-1][1])
        elif n_rows != _col_len(columns[-1][1]):
            return None  # inconsistent; let the assembler raise precisely
    if n_rows is None:
        return [], [], 0
    return [name for name, _ in columns], [vals for _, vals in columns], n_rows


def slice_column(col, start: int, end: int):
    """Row-window of a fast_row_columns column (list or slices spec)."""
    if isinstance(col, tuple):
        tag, elems, offsets, mask = col
        return (tag, elems, offsets[start : end + 1],
                None if mask is None else mask[start:end])
    return col[start:end]


def fast_rows(schema: Schema, chunks: dict[tuple, ChunkData], raw: bool):
    """Vectorized row assembly (fast_row_columns + one zip). Returns None
    when the shape needs the full Dremel walk."""
    rc = fast_row_columns(schema, chunks, raw)
    if rc is None:
        return None
    names, columns, n_rows = rc
    if not names:
        return []
    return _zip_dict_rows(names, columns)


# -- general level-vectorized assembly (arbitrary nesting) ---------------------
#
# The canonical fast paths above cover flat / LIST / MAP / struct /
# LIST<struct> shapes; everything deeper used to drop into the per-row
# RecordAssembler cursor walk (~10 us per element, pure Python). This
# recursion assembles ARBITRARY nesting (struct-of-list, list-of-list,
# map-of-struct, ...) from whole-column level math instead: every node
# produces a value list at its own repetition "slot" granularity, repeated
# children aggregate one level up via the same run-boundary math the
# canonical paths use, and groups zip children at C speed. Any structural
# inconsistency falls back to the RecordAssembler, which raises the precise
# error (or proves the data fine).


def _is_list_node(node: Column) -> bool:
    ct = node.converted_type
    lt = node.logical_type
    return ct == ConvertedType.LIST or (lt is not None and lt.LIST is not None)


def _is_map_node(node: Column) -> bool:
    ct = node.converted_type
    lt = node.logical_type
    return ct in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE) or (
        lt is not None and lt.MAP is not None
    )


class _ShapeMismatch(Exception):
    """Internal: the vectorized walk met a shape it can't prove; fall back."""


def _node_values(node: Column, chunks, raw: bool):
    """(values, defs, reps) at `node`'s slot granularity (one entry per
    record at node.max_rep). values[i] is the assembled value assuming
    ancestors are present — None where the node itself is null; garbage
    (masked by ancestors) where an ancestor is null. defs/reps are the level
    arrays of the node's first covered leaf (None when the column has no
    def/rep dimension)."""
    if node.is_leaf:
        chunk = chunks.get(node.path)
        if chunk is None:
            raise _ShapeMismatch(node.path_str)
        vals = _leaf_python_values(node, chunk, raw)
        dfl = chunk.def_levels
        rep = chunk.rep_levels
        if node.max_def > 0 and dfl is not None:
            present = dfl == node.max_def
            n_present = int(present.sum())
            if len(vals) != n_present:
                raise AssemblyError(
                    f"assembly: {node.path_str}: {len(vals)} values for "
                    f"{n_present} present entries"
                )
            if n_present != len(dfl):
                full = np.empty(len(dfl), dtype=object)
                full[present] = vals
                vals = full.tolist()
        elif node.max_def > 0 and dfl is None:
            raise _ShapeMismatch(node.path_str)
        return vals, dfl, rep

    if not raw and _is_list_node(node) and len(node.children) == 1:
        mid = node.children[0]
        if mid.repetition == FieldRepetitionType.REPEATED and _subtree_covered(mid, chunks):
            if mid.is_leaf or len(mid.children) != 1:
                ev, ed, er = _node_values(mid, chunks, raw)  # 2-level legacy
            else:
                inner = mid.children[0]
                if inner.repetition == FieldRepetitionType.REPEATED:
                    ev, ed, er = _aggregated_child(mid, inner, chunks, raw)
                else:
                    ev, ed, er = _node_values(inner, chunks, raw)  # unwrap
            return _slots_to_lists(node, mid, ev, ed, er)

    if not raw and _is_map_node(node) and len(node.children) == 1:
        kv = node.children[0]
        if (
            kv.repetition == FieldRepetitionType.REPEATED
            and not kv.is_leaf
            and len(kv.children) == 2
            and _subtree_covered(kv, chunks)
        ):
            ev, ed, er = _node_values(kv, chunks, raw)
            pair_lists, defs, reps = _slots_to_lists(node, kv, ev, ed, er)
            kname, vname = kv.children[0].name, kv.children[1].name
            out = []
            for pairs in pair_lists:
                if pairs is None:
                    out.append(None)
                    continue
                try:
                    out.append(
                        {p.get(kname): p.get(vname) for p in pairs}
                    )
                except TypeError:  # unhashable key: keep the pair list
                    out.append(pairs)
            return out, defs, reps

    # generic group (also the raw-mode path: no unwrapping)
    names = []
    cols = []
    base_d = base_r = None
    n_slots = None
    for c in node.children:
        if not _subtree_covered(c, chunks):
            continue
        if c.repetition == FieldRepetitionType.REPEATED:
            v, d, r = _aggregated_child(node, c, chunks, raw)
        else:
            v, d, r = _node_values(c, chunks, raw)
        if n_slots is None:
            n_slots = len(v)
            base_d, base_r = d, r
        elif len(v) != n_slots:
            raise _ShapeMismatch(node.path_str)
        names.append(c.name)
        cols.append(v)
    if n_slots is None:
        raise _ShapeMismatch(node.path_str)
    values = _zip_dict_rows(names, cols)
    if (
        node.repetition == FieldRepetitionType.OPTIONAL
        and node.max_def > 0
        and base_d is not None
    ):
        absent = base_d < node.max_def
        if absent.any():
            for i in np.flatnonzero(absent).tolist():
                values[i] = None
    return values, base_d, base_r


def _aggregate_entries(parent_rep: int, elem_def: int, null_def, ev, ed, er, where):
    """Core of one level of repeated aggregation: group element entries
    (ev, ed, er) into per-slot lists at `parent_rep` granularity. Elements
    exist where ed >= elem_def; slots whose first def sits below `null_def`
    (when given) become None instead of a list. Returns
    (values, first_defs, first_reps)."""
    if er is None or ed is None:
        raise _ShapeMismatch(where)
    is_boundary = er <= parent_rep
    if len(er) and not is_boundary[0]:
        # corrupt levels: the stream must open a slot before extending one
        # (the Dremel fallback raises the precise error)
        raise _ShapeMismatch(where)
    starts = np.flatnonzero(is_boundary)
    has_elem = ed >= elem_def
    if bool(has_elem.all()):
        elems = ev
    else:
        # fromiter keeps nested list/dict elements as objects (a 2-D
        # broadcast would mangle equal-length list elements)
        arr = np.fromiter(ev, dtype=object, count=len(ev))
        elems = arr[has_elem].tolist()
    row_of = np.cumsum(is_boundary) - 1
    counts = np.bincount(row_of[has_elem], minlength=len(starts))
    offsets = np.zeros(len(starts) + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    first_def = ed[starts]
    mask = None
    if null_def is not None and null_def > 0:
        if not bool((first_def >= null_def).all()):
            mask = (first_def < null_def).astype(np.uint8)
    if _ext is not None:
        values = _ext.rows_from_slices(elems, offsets, mask)
    else:
        off = offsets.tolist()
        if mask is None:
            values = [elems[a:b] for a, b in zip(off[:-1], off[1:])]
        else:
            values = [
                None if m else elems[a:b]
                for m, a, b in zip(mask.tolist(), off[:-1], off[1:])
            ]
    return values, first_def, er[starts]


def _aggregated_child(parent: Column, c: Column, chunks, raw: bool):
    """A REPEATED child aggregated to the parent's slot granularity: each
    parent slot gets the list of child elements (empty when the levels show
    no element — reference data_store.go:294-308 loop-until-rep-drops)."""
    cv, cd, cr = _node_values(c, chunks, raw)
    return _aggregate_entries(
        parent.max_rep, c.max_def, None, cv, cd, cr, c.path_str
    )


def _slots_to_lists(node: Column, mid: Column, ev, ed, er):
    """Shared tail of the LIST/MAP unwrap: aggregate element slots into
    per-slot lists at `node`'s granularity with null-wrapper detection."""
    return _aggregate_entries(
        node.max_rep, mid.max_def, node.max_def, ev, ed, er, node.path_str
    )


def _subtree_covered(node: Column, chunks) -> bool:
    if node.is_leaf:
        return node.path in chunks
    return any(_subtree_covered(c, chunks) for c in node.children)


def vector_row_columns(schema: Schema, chunks: dict[tuple, ChunkData], raw: bool):
    """General level-vectorized assembly for arbitrary nesting, in the same
    column-oriented form as fast_row_columns (so callers window-materialize
    identically). Returns (names, columns, n_rows), or None when the walk
    meets a shape it cannot prove (the RecordAssembler then decides — and
    raises its precise error if the data really is inconsistent)."""
    try:
        names = []
        cols = []
        n_rows = None
        for top in schema.root.children:
            if not _subtree_covered(top, chunks):
                continue
            if top.repetition == FieldRepetitionType.REPEATED:
                v, _d, _r = _aggregated_child(schema.root, top, chunks, raw)
            else:
                v, _d, _r = _node_values(top, chunks, raw)
            if n_rows is None:
                n_rows = len(v)
            elif len(v) != n_rows:
                return None
            names.append(top.name)
            cols.append(v)
        if n_rows is None:
            return [], [], 0
        return names, cols, n_rows
    except _ShapeMismatch:
        return None


def vector_rows(schema: Schema, chunks: dict[tuple, ChunkData], raw: bool):
    """Row-list form of vector_row_columns (None on unprovable shapes)."""
    rc = vector_row_columns(schema, chunks, raw)
    if rc is None:
        return None
    names, cols, _n = rc
    if not names:
        return []
    return _zip_dict_rows(names, cols)


def logical_kind(node: Column):
    """The single dispatch point for value-level logical conversions.

    Returns one of None | 'int96' | 'decimal' | 'date' | ('timestamp', unit,
    utc) | ('time', unit, utc). Both convert_logical and the flat fast path
    consult this, so a new conversion cannot silently diverge between the two
    paths.
    """
    ct = node.converted_type
    lt = node.logical_type
    if node.type == Type.INT96:
        return "int96"
    if lt is not None and lt.INTEGER is not None and not lt.INTEGER.isSigned:
        if node.type == Type.INT32:
            return ("uint", 32)
        if node.type == Type.INT64:
            return ("uint", 64)
    if ct in (ConvertedType.UINT_32, ConvertedType.UINT_64):
        return ("uint", 32 if node.type == Type.INT32 else 64)
    if ct == ConvertedType.DECIMAL or (lt is not None and lt.DECIMAL is not None):
        return "decimal"
    if ct == ConvertedType.DATE or (lt is not None and lt.DATE is not None):
        return "date"
    if lt is not None and lt.TIMESTAMP is not None:
        u = lt.TIMESTAMP.unit
        return ("timestamp", u.unit_name() if u is not None else "MICROS",
                bool(lt.TIMESTAMP.isAdjustedToUTC))
    if ct == ConvertedType.TIMESTAMP_MILLIS:
        return ("timestamp", "MILLIS", True)
    if ct == ConvertedType.TIMESTAMP_MICROS:
        return ("timestamp", "MICROS", True)
    if lt is not None and lt.TIME is not None:
        u = lt.TIME.unit
        return ("time", u.unit_name() if u is not None else "MICROS",
                bool(lt.TIME.isAdjustedToUTC))
    if ct == ConvertedType.TIME_MILLIS:
        return ("time", "MILLIS", True)
    if ct == ConvertedType.TIME_MICROS:
        return ("time", "MICROS", True)
    return None


class RecordAssembler:
    """Assembles rows from the leaf chunks of one row group."""

    def __init__(self, schema: Schema, chunks: dict[tuple, ChunkData], raw: bool = False):
        self.schema = schema
        self.raw = raw
        self.cursors: dict[tuple, _LeafCursor] = {
            path: _LeafCursor(c) for path, c in chunks.items()
        }
        # Static per-node caches (hot path: consulted per field per row).
        self._covered_cache: dict[tuple, bool] = {}
        self._first_leaf_cache: dict[tuple, _LeafCursor] = {}
        self._build_caches(schema.root)
        # Only assemble the subtree covered by the provided chunks (projection).
        self.selected_roots = [
            child for child in schema.root.children if self._covered(child)
        ]

    def _build_caches(self, node: Column) -> None:
        if node.is_leaf:
            covered = node.path in self.cursors
            if covered:
                self._first_leaf_cache[node.path] = self.cursors[node.path]
        else:
            covered = False
            for c in node.children:
                self._build_caches(c)
                if self._covered_cache[c.path] and not covered:
                    covered = True
                    self._first_leaf_cache[node.path] = self._first_leaf_cache[c.path]
        self._covered_cache[node.path] = covered

    def _covered(self, node: Column) -> bool:
        return self._covered_cache[node.path]

    def _first_leaf(self, node: Column) -> _LeafCursor:
        cur = self._first_leaf_cache.get(node.path)
        if cur is None:
            raise AssemblyError(f"assembly: no selected leaf under {node.path_str}")
        return cur

    def _advance_subtree_null(self, node: Column) -> None:
        if node.is_leaf:
            self.cursors[node.path].advance_null()
            return
        for c in node.children:
            if self._covered(c):
                self._advance_subtree_null(c)

    # -- row iteration ---------------------------------------------------------

    def __iter__(self):
        while True:
            lead = None
            for child in self.selected_roots:
                lead = self._first_leaf(child)
                break
            if lead is None or lead.exhausted():
                return
            yield self.assemble_row()

    def assemble_row(self) -> dict:
        row = {}
        for child in self.selected_roots:
            value = self._read_field(child)
            if value is not _ABSENT:
                row[child.name] = value
        return row

    # -- field assembly --------------------------------------------------------

    def _read_field(self, node: Column):
        """Read one instance of `node` (ancestors known present)."""
        rep = node.repetition
        if rep == FieldRepetitionType.REPEATED:
            return self._read_repeated(node)
        lead = self._first_leaf(node)
        if lead.exhausted():
            raise AssemblyError(f"assembly: leaf exhausted at {node.path_str}")
        d = lead.peek_def()
        if rep == FieldRepetitionType.OPTIONAL and d < node.max_def:
            self._advance_subtree_null(node)
            return None
        return self._read_present(node)

    def _read_present(self, node: Column):
        if node.is_leaf:
            cur = self.cursors[node.path]
            if cur.peek_def() != cur.max_def:
                # present at this node but null deeper — impossible for a leaf
                raise AssemblyError(
                    f"assembly: def level {cur.peek_def()} below leaf max "
                    f"{cur.max_def} at {node.path_str}"
                )
            return self._convert(node, cur.pop_value())
        if not self.raw:
            unwrapped = self._try_unwrap(node)
            if unwrapped is not _ABSENT:
                return unwrapped
        out = {}
        for child in node.children:
            if not self._covered(child):
                continue
            v = self._read_field(child)
            if v is not _ABSENT:
                out[child.name] = v
        return out

    def _read_repeated(self, node: Column):
        """A REPEATED node: zero or more instances -> list."""
        lead = self._first_leaf(node)
        if lead.exhausted():
            raise AssemblyError(f"assembly: leaf exhausted at {node.path_str}")
        d = lead.peek_def()
        if d < node.max_def:
            # zero elements (or null ancestor list wrapper)
            self._advance_subtree_null(node)
            return []
        items = [self._read_present(node)]
        while True:
            if lead.exhausted():
                break
            r = lead.peek_rep()
            if r != node.max_rep:
                break
            items.append(self._read_present(node))
        return items

    # -- ergonomic unwrapping --------------------------------------------------

    def _try_unwrap(self, node: Column):
        ct = node.converted_type
        lt = node.logical_type
        is_list = ct == ConvertedType.LIST or (lt is not None and lt.LIST is not None)
        is_map = ct in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE) or (
            lt is not None and lt.MAP is not None
        )
        if is_list and len(node.children) == 1:
            mid = node.children[0]
            if mid.repetition == FieldRepetitionType.REPEATED and self._covered(mid):
                if mid.is_leaf or len(mid.children) != 1:
                    # 2-level legacy list: repeated element directly
                    return self._read_repeated_unwrapped(mid, unwrap_child=False)
                return self._read_repeated_unwrapped(mid, unwrap_child=True)
        if is_map and len(node.children) == 1:
            kv = node.children[0]
            if (
                kv.repetition == FieldRepetitionType.REPEATED
                and not kv.is_leaf
                and len(kv.children) == 2
                and self._covered(kv)
            ):
                pairs = self._read_repeated(kv)
                try:
                    return {p.get(kv.children[0].name): p.get(kv.children[1].name) for p in pairs}
                except TypeError:
                    # unhashable key (e.g. nested) — fall back to pair list
                    return pairs
        return _ABSENT

    def _read_repeated_unwrapped(self, mid: Column, unwrap_child: bool):
        """LIST middle group: return element values directly."""
        lead = self._first_leaf(mid)
        if lead.exhausted():
            raise AssemblyError("assembly: leaf exhausted in list")
        d = lead.peek_def()
        if d < mid.max_def:
            self._advance_subtree_null(mid)
            return []
        items = []
        while True:
            v = self._read_present(mid)
            if unwrap_child:
                elem = mid.children[0]
                v = v.get(elem.name) if isinstance(v, dict) else v
            items.append(v)
            if lead.exhausted() or lead.peek_rep() != mid.max_rep:
                break
        return items

    # -- value conversion ------------------------------------------------------

    def _convert(self, node: Column, v):
        if self.raw:
            if isinstance(v, np.generic):
                return v.item()
            if isinstance(v, np.ndarray):  # int96 / fixed rows
                return v.tobytes()
            return v
        return convert_logical(node, v)


_NANOTIME_CTOR = None


def _nanotime():
    """floor.Time.from_nanos, imported once (core cannot import floor at
    module load — floor imports core — and a per-value import would sit in
    the decode hot loop)."""
    global _NANOTIME_CTOR
    if _NANOTIME_CTOR is None:
        from ..floor.time import Time

        _NANOTIME_CTOR = Time.from_nanos
    return _NANOTIME_CTOR


def _to_micros(v: int, unit: str) -> int:
    if unit == "MILLIS":
        return v * 1000
    if unit == "NANOS":
        return v // 1000
    return v


_KIND_UNSET = object()


def convert_to_storage(node: Column, v, kind=_KIND_UNSET):
    """Ergonomic Python value -> storage value (the INVERSE of
    convert_logical, same logical_kind dispatch table): datetime ->
    epoch int at the declared unit (exact integer arithmetic — float
    total_seconds() drifts microseconds past ~270 years from epoch),
    date -> days, time -> unit int, Decimal -> unscaled int (or
    big-endian bytes for FLBA/BYTE_ARRAY storage, exact-scale and
    width-fit enforced as ValueError). Raw ints/floats/bytes pass
    through. `kind` takes a precomputed logical_kind(node) so per-chunk
    callers dispatch once. Closes the write_row side of the iter_rows
    round-trip."""
    import datetime as dt
    import decimal

    if kind is _KIND_UNSET:
        kind = logical_kind(node)
    if v is None or isinstance(v, (int, float, np.integer, np.floating)):
        if (
            isinstance(v, (int, np.integer))
            and kind is not None
            and kind[0] == "uint"
        ):
            bits = kind[1]
            u = int(v) & ((1 << bits) - 1)
            return u - (1 << bits) if u >= (1 << (bits - 1)) else u
        return v
    if kind == "decimal" and isinstance(v, decimal.Decimal):
        lt = node.logical_type
        scale = node.element.scale
        if scale is None and lt is not None and lt.DECIMAL is not None:
            scale = lt.DECIMAL.scale
        scale = scale or 0
        scaled = v.scaleb(scale)
        unscaled = int(scaled)
        if scaled != unscaled:
            raise ValueError(
                f"decimal {v} does not fit scale {scale} of "
                f"{node.path_str} exactly"
            )
        try:
            if node.type == Type.FIXED_LEN_BYTE_ARRAY:
                w = node.type_length or 0
                if w <= 0:
                    raise ValueError(
                        f"fixed column {node.path_str} lacks type_length"
                    )
                return unscaled.to_bytes(w, "big", signed=True)
            if node.type == Type.BYTE_ARRAY:
                n = max((unscaled.bit_length() + 8) // 8, 1)
                return unscaled.to_bytes(n, "big", signed=True)
        except OverflowError as e:
            raise ValueError(
                f"decimal {v} does not fit {node.type_length}-byte storage "
                f"of {node.path_str}"
            ) from e
        return unscaled
    if kind == "date" and isinstance(v, dt.date) and not isinstance(v, dt.datetime):
        return (v - dt.date(1970, 1, 1)).days
    if kind is not None and kind[0] == "timestamp":
        unit = kind[1]
        if isinstance(v, np.datetime64):
            ns = int(v.astype("datetime64[ns]").astype(np.int64))
            return ns // {"NANOS": 1, "MICROS": 1_000, "MILLIS": 1_000_000}[unit]
        if isinstance(v, dt.datetime):
            epoch = dt.datetime(
                1970, 1, 1, tzinfo=dt.timezone.utc if v.tzinfo else None
            )
            delta = v - epoch
            micros = (
                delta.days * 86_400_000_000
                + delta.seconds * 1_000_000
                + delta.microseconds
            )
            return {
                "MILLIS": micros // 1_000,
                "MICROS": micros,
                "NANOS": micros * 1_000,
            }[unit]
    if kind is not None and kind[0] == "time":
        nanos = None
        if isinstance(v, dt.time):
            nanos = (
                (v.hour * 3600 + v.minute * 60 + v.second) * 10**9
                + v.microsecond * 1_000
            )
        elif hasattr(v, "nanos"):  # floor.Time
            nanos = int(v.nanos)
        if nanos is not None:
            return nanos // {"NANOS": 1, "MICROS": 1_000, "MILLIS": 1_000_000}[
                kind[1]
            ]
    return v


def convert_logical(node: Column, v):
    """Storage value -> ergonomic Python value by logical type, matching
    pyarrow's to_pylist() conventions (DATE -> date, TIMESTAMP -> datetime,
    TIME -> time, DECIMAL -> Decimal, INT96 -> datetime, UTF8 -> str).
    Dispatch comes from logical_kind() — the shared table with the flat path."""
    import datetime as dt
    import decimal

    if isinstance(v, bytes) and node.is_string():
        return v.decode("utf-8", errors="replace")
    kind = logical_kind(node)
    if kind == "int96" and isinstance(v, (np.ndarray, bytes)):
        from ..utils.int96 import int96_to_datetime

        return int96_to_datetime(bytes(v))
    if isinstance(v, np.ndarray):
        v = v.tobytes()
    if isinstance(v, np.generic):
        v = v.item()
    if kind is None:
        return v
    if kind[0] == "uint":
        # UINT(32/64) logical annotation on a signed physical type: the bit
        # pattern reinterprets unsigned (pyarrow to_pylist parity)
        return int(v) & ((1 << kind[1]) - 1)
    if kind == "decimal":
        lt = node.logical_type
        scale = node.element.scale
        if scale is None and lt is not None and lt.DECIMAL is not None:
            scale = lt.DECIMAL.scale
        scale = scale or 0
        if isinstance(v, bytes):
            unscaled = int.from_bytes(v, "big", signed=True) if v else 0
        else:
            unscaled = int(v)
        return decimal.Decimal(unscaled).scaleb(-scale)
    if kind == "date":
        return dt.date(1970, 1, 1) + dt.timedelta(days=int(v))
    if kind[0] == "timestamp":
        _, unit, utc = kind
        if unit == "NANOS":
            # datetime caps at microseconds; numpy datetime64[ns] carries the
            # full precision (the reference's time.Time is nanosecond-native)
            return np.datetime64(int(v), "ns")
        tz = dt.timezone.utc if utc else None
        return dt.datetime(1970, 1, 1, tzinfo=tz) + dt.timedelta(
            microseconds=_to_micros(int(v), unit)
        )
    if kind[0] == "time":
        if kind[1] == "NANOS":
            # datetime.time cannot hold nanoseconds; the floor Time type
            # keeps them (reference: floor/time.go:10-13)
            return _nanotime()(int(v), utc=kind[2])
        micros = _to_micros(int(v), kind[1])
        return dt.time(
            hour=micros // 3_600_000_000,
            minute=(micros // 60_000_000) % 60,
            second=(micros // 1_000_000) % 60,
            microsecond=micros % 1_000_000,
        )
    return v


class _Absent:
    __slots__ = ()

    def __repr__(self):
        return "<absent>"


_ABSENT = _Absent()

"""Vectorized residual predicate evaluation: filter spec -> boolean row mask.

Row-group pruning (core/filter.py) proves whole groups empty of matches,
but every SURVIVING row was still re-checked by the scalar predicate
walker — one Python `row_matches` call per row, which measured as the
ceiling of filtered scans. This module is the data-parallel formulation:
each leaf predicate of the (already normalized) DNF compiles to one
boolean ndarray over the decoded chunk buffers, conjunctions AND their
leaf masks, disjunctions OR the conjunctions, and rows materialize only
where the combined mask is set (predicate -> per-leaf mask -> combined
mask -> gather). The same mask drives the zero-copy arrow path: a pyarrow
`table.filter(mask)` is a buffer-level take, so filtered arrow-ipc results
never box a row.

Comparisons happen in the PHYSICAL storage domain using the (stat_lo,
stat_hi) bracket normalize_filters already computes per value: lo == hi
means the filter value is exactly representable (compare against it);
lo != hi means it falls BETWEEN representable stored values (equality is
impossible, ordered comparisons use the end that keeps the answer exact —
the same bracket argument statistics pruning relies on, applied per row).
Columns whose physical form has no usable ordering (INT96 timestamps,
binary-backed decimals) and shapes the pipeline does not cover raise the
typed VecFilterError and the caller falls back to the scalar walk — the
engine ladder of core/assembly_vec.py, with `row_matches` kept as the
always-exact differential oracle (PQT_VEC_FILTER=0 forces it everywhere).

Null semantics are selectable because the two consumers pin different
conventions (tests assert both):

  "row"    core/filter.row_matches: a null cell fails every value op
           (is_null/not_null see validity; not_in drops nulls too)
  "arrow"  pyarrow.parquet.read_table: identical EXCEPT not_in, where
           pc.invert(pc.is_in(...)) maps null to True (nulls are KEPT)

`("tags", "contains", x)` predicates mask at the list-SLOT level: the
element stream compares dense values once, and one scatter through the
record-start prefix scan (ops/levels.rows_from_rep — the same scan whose
device twin is kernels/device_ops.list_layout_device) lifts element hits
to row membership.
"""

from __future__ import annotations

import os

import numpy as np

from ..ops.levels import rows_from_rep
from .arrays import ByteArrayData
from .filter import FilterError
from .stats import column_is_unsigned

__all__ = [
    "VecFilterError",
    "vec_filter_enabled",
    "dnf_mask",
    "group_row_count",
    "mask_to_ranges",
    "masked_flat_columns",
]

# Guards against pathological byte-array shapes: padding n values to the
# longest value's width is the vectorized compare's only super-linear cost,
# so chunks with huge values (or a huge filter value) take the scalar walk.
_MAX_BYTES_WIDTH = 1 << 12
_MAX_PAD_BYTES = 256 << 20


class VecFilterError(FilterError):
    """The mask pipeline cannot evaluate this predicate over these buffers
    (unorderable physical domain, uncovered shape, pathological widths).
    Callers fall back to the scalar row_matches walk, which is exact for
    everything — same contract as assembly_vec's VecStructureError."""


def vec_filter_enabled() -> bool:
    """Engine-selection knob: PQT_VEC_FILTER=0 forces the scalar predicate
    walk (the differential oracle) everywhere the mask pipeline would run."""
    return os.environ.get("PQT_VEC_FILTER", "1") != "0"


# -- mask combination ----------------------------------------------------------


def dnf_mask(chunks: dict, dnf, n_rows: int, *, null_mode: str = "row"):
    """bool[n_rows] row mask of a normalized DNF over one row group's
    decoded chunks ({leaf path: ChunkData}). Raises VecFilterError when any
    referenced predicate cannot vectorize — all or nothing, so engines
    never mix within one group and outputs stay byte-identical to the
    scalar walk."""
    if null_mode not in ("row", "arrow"):
        raise ValueError('null_mode must be "row" or "arrow"')
    cache: dict = {}
    out = None
    for conj in dnf:
        m = None
        for entry in conj:
            lm = _leaf_mask(chunks, entry, n_rows, null_mode, cache)
            m = lm if m is None else (m & lm)
        if m is None:  # empty conjunction is vacuously true
            return np.ones(n_rows, dtype=bool)
        out = m if out is None else (out | m)
    if out is None:
        return np.ones(n_rows, dtype=bool)
    return out


def group_row_count(chunks: dict) -> int:
    """Row count one group's decoded chunks promise (record starts for
    repeated leaves, level entries otherwise) — raising VecFilterError on
    disagreement, so callers fall back and the scalar walk raises its
    precise typed error if the data really is inconsistent."""
    n = None
    for path, cd in chunks.items():
        if cd.rep_levels is None:
            c = cd.num_values
        else:
            rl = np.asarray(cd.rep_levels)
            if len(rl) and int(rl[0]) != 0:
                raise VecFilterError(
                    f"filter_vec: {'.'.join(path)}: stream opens mid-record"
                )
            c = int((rl == 0).sum())
        if n is None:
            n = c
        elif n != c:
            raise VecFilterError("filter_vec: leaves disagree on row count")
    if n is None:
        raise VecFilterError("filter_vec: no decoded chunks")
    return n


def mask_to_ranges(mask) -> list:
    """Sorted disjoint [(start, stop)) runs of True — the gather plan the
    reader's windowed row materialization already consumes."""
    d = np.diff(mask.astype(np.int8), prepend=np.int8(0), append=np.int8(0))
    starts = np.flatnonzero(d == 1)
    ends = np.flatnonzero(d == -1)
    return list(zip(starts.tolist(), ends.tolist()))


# -- masked row-column gather ---------------------------------------------------


def masked_flat_columns(chunks: dict, raw: bool, mask):
    """(names, columns, k) holding ONLY the mask's rows for flat schemas
    (single-level leaves, max_def <= 1) — the selective twin of
    assembly_vec._flat_columns. This is where the mask pays: value boxing
    and logical conversion run over the k kept rows, never the dropped
    ones, so a 1%-selective predicate boxes 1% of the group. None when any
    chunk needs the general assembly path."""
    for path, cd in chunks.items():
        node = cd.column
        if (
            len(path) != 1
            or not node.is_leaf
            or node.max_rep > 0
            or node.max_def > 1
        ):
            return None
    if not chunks:
        return [], [], 0
    idx = np.flatnonzero(mask)
    names: list = []
    columns: list = []
    for path, cd in chunks.items():
        node = cd.column
        valid = None
        if node.max_def == 1 and cd.def_levels is not None:
            v = np.asarray(cd.def_levels) == 1
            if not v.all():
                valid = v
        if valid is None:
            vals = _gather_values(cd, node, idx, raw)
        else:
            didx = np.clip(np.cumsum(valid) - 1, 0, None)
            ok = valid[idx]
            dense = _gather_values(cd, node, didx[idx][ok], raw)
            it = iter(dense)
            vals = [next(it) if o else None for o in ok.tolist()]
        names.append(node.name)
        columns.append(vals)
    return names, columns, len(idx)


def _gather_values(cd, node, dense_idx, raw: bool) -> list:
    """Python values of the chunk's dense cells at `dense_idx`, with the
    exact decode/convert semantics of assembly._leaf_python_values applied
    to ONLY those cells."""
    from .assembly import convert_logical, logical_kind

    v = cd.values
    if v is None and cd.indices is not None and cd.dictionary is not None:
        idx_arr = np.asarray(cd.indices)[dense_idx]
        sub = type(cd)(
            column=cd.column, num_values=0, values=cd.dictionary,
            def_levels=None, rep_levels=None,
        )
        dvals = _gather_values(sub, node, np.asarray(idx_arr), raw)
        return dvals
    if isinstance(v, ByteArrayData):
        offs = np.asarray(v.offsets, dtype=np.int64)
        data = v.data
        s = offs[dense_idx].tolist()
        e = offs[np.asarray(dense_idx) + 1].tolist()
        decode = not raw and node.is_string()
        if decode:
            vals = [
                data[a:b].decode("utf-8", errors="replace") for a, b in zip(s, e)
            ]
        else:
            vals = [bytes(data[a:b]) for a, b in zip(s, e)]
    else:
        arr = np.asarray(v)
        if arr.ndim == 2:
            vals = [arr[j].tobytes() for j in np.asarray(dense_idx).tolist()]
        else:
            vals = arr[dense_idx].tolist()
    if not raw and logical_kind(node) is not None:
        conv = convert_logical
        vals = [conv(node, x) for x in vals]
    return vals


# -- per-leaf masks -------------------------------------------------------------


def _leaf_mask(chunks, entry, n_rows, null_mode, cache):
    path, leaf, op, value, vlo, vhi = entry
    cd = chunks.get(path)
    if cd is None:
        raise VecFilterError(f"filter_vec: column {'.'.join(path)} not decoded")
    if op == "contains":
        return _contains_mask(cd, leaf, vlo, vhi, n_rows, (path, cache))
    if leaf.max_rep != 0:
        raise VecFilterError(f"filter_vec: {'.'.join(path)} is repeated")
    if cd.num_values != n_rows:
        raise VecFilterError(
            f"filter_vec: {'.'.join(path)}: {cd.num_values} level entries "
            f"for {n_rows} rows"
        )
    valid = None
    if leaf.max_def > 0 and cd.def_levels is not None:
        v = np.asarray(cd.def_levels) == leaf.max_def
        if not v.all():
            valid = v
    if op == "is_null":
        if valid is None:
            return np.zeros(n_rows, dtype=bool)
        return ~valid
    if op == "not_null":
        if valid is None:
            return np.ones(n_rows, dtype=bool)
        return valid.copy()
    if op in ("in", "not_in") and null_mode == "arrow":
        # pyarrow's is_in CASTS the value set to the column type (unlike
        # its compare kernels, which promote the column): a float64 member
        # that is inexact in a float32 column matches under pc.is_in but
        # not under exact semantics — decline so the fallback decides and
        # to_arrow stays value-identical whichever engine runs
        from ..meta.parquet_types import Type

        if leaf.type == Type.FLOAT and isinstance(vlo, list) and any(
            lo is not None
            and isinstance(lo, float)
            and float(np.float32(lo)) != lo
            for lo, _ in vlo
        ):
            raise VecFilterError(
                f"filter_vec: {leaf.path_str}: in-list member inexact in "
                "float32 (pyarrow is_in casts the value set)"
            )
    cmp = _dense_compare(cd, leaf, op, vlo, vhi, (path, cache))
    if op == "not_in" and null_mode == "arrow":
        # pyarrow's pc.invert(pc.is_in(...)) maps null to True: nulls KEPT
        if valid is None:
            return cmp
        out = np.ones(n_rows, dtype=bool)
        out[valid] = cmp
        return out
    if valid is None:
        return cmp
    out = np.zeros(n_rows, dtype=bool)
    out[valid] = cmp
    return out


def _contains_mask(cd, leaf, vlo, vhi, n_rows, ckey):
    """List-slot membership: compare the dense element values once, then
    lift element hits to their rows through the record-start scan."""
    if cd.rep_levels is None:
        raise VecFilterError(
            f"filter_vec: {leaf.path_str}: contains without repetition levels"
        )
    rl = np.asarray(cd.rep_levels)
    if len(rl) and int(rl[0]) != 0:
        raise VecFilterError(f"filter_vec: {leaf.path_str}: stream opens mid-record")
    starts = rows_from_rep(rl)
    if len(starts) != n_rows:
        raise VecFilterError(
            f"filter_vec: {leaf.path_str}: {len(starts)} records for {n_rows} rows"
        )
    # which row each level entry belongs to (inclusive prefix count of starts)
    row_of = np.cumsum(rl == 0) - 1
    if cd.def_levels is not None:
        valid = np.asarray(cd.def_levels) == leaf.max_def
        row_of = row_of[valid]
    cmp = _dense_compare(cd, leaf, "==", vlo, vhi, ckey)
    if len(cmp) != len(row_of):
        raise VecFilterError(f"filter_vec: {leaf.path_str}: level/value mismatch")
    out = np.zeros(n_rows, dtype=bool)
    out[row_of[cmp]] = True
    return out


# -- dense value comparison -----------------------------------------------------


def _dense_compare(cd, leaf, op, vlo, vhi, ckey):
    """bool mask over the chunk's DENSE (non-null) values for one value op,
    in the physical domain. `vlo`/`vhi` bracket the filter value (for
    in/not_in, vlo is the list of member brackets)."""
    if vlo is None:
        raise VecFilterError(
            f"filter_vec: {leaf.path_str}: no orderable physical form"
        )
    if op in ("in", "not_in"):
        if any(lo is None for lo, _ in vlo):
            raise VecFilterError(
                f"filter_vec: {leaf.path_str}: unorderable in-list member"
            )
        exact = [lo for lo, hi in vlo if lo == hi]
        m = _member_mask(cd, leaf, exact, ckey)
        return ~m if op == "not_in" else m
    values = cd.values
    if values is None and cd.indices is not None and cd.dictionary is not None:
        # dictionary-preserved chunk: compare the (small) dictionary once,
        # then one gather through the indices
        dcmp = _raw_compare(cd.dictionary, leaf, op, vlo, vhi, ckey)
        return dcmp[np.asarray(cd.indices)]
    return _raw_compare(values, leaf, op, vlo, vhi, ckey)


def _member_mask(cd, leaf, members, ckey):
    """OR of equality masks for the exactly-representable in-list members
    (an inexact bracket can equal no stored value: contributes nothing)."""
    values = cd.values
    via_dict = (
        values is None and cd.indices is not None and cd.dictionary is not None
    )
    target = cd.dictionary if via_dict else values
    if target is None:
        raise VecFilterError(f"filter_vec: {leaf.path_str}: no value buffer")
    if not members:
        n = len(target) if via_dict else _dense_len(target)
        m = np.zeros(n, dtype=bool)
    elif isinstance(target, ByteArrayData):
        m = None
        for b in members:
            em = _bytes_compare(target, "==", b, ckey)
            m = em if m is None else (m | em)
    elif isinstance(target, np.ndarray) and target.ndim == 1:
        arr = _numeric_view(target, leaf)
        try:
            m = np.isin(arr, np.array(members))
        except (OverflowError, TypeError, ValueError) as e:
            raise VecFilterError(
                f"filter_vec: {leaf.path_str}: in-list not comparable: {e}"
            ) from None
    else:
        m = None
        for b in members:
            em = _raw_compare(target, leaf, "==", b, b, ckey)
            m = em if m is None else (m | em)
    return m[np.asarray(cd.indices)] if via_dict else m


def _dense_len(values) -> int:
    if isinstance(values, ByteArrayData):
        return len(values)
    return len(values)


def _numeric_view(arr, leaf):
    """The chunk's 1-D numeric array in its COMPARISON domain: unsigned
    logical types reinterpret the stored bit pattern (convert_logical's
    `v & (2**bits - 1)` as one vectorized view/mask)."""
    if column_is_unsigned(leaf):
        from .assembly import logical_kind

        kind = logical_kind(leaf)
        bits = kind[1] if isinstance(kind, tuple) and kind[0] == "uint" else None
        u = arr.view(arr.dtype.newbyteorder("="))
        if u.dtype == np.int32:
            u = u.view(np.uint32)
        elif u.dtype == np.int64:
            u = u.view(np.uint64)
        if bits is not None and bits < u.dtype.itemsize * 8:
            u = u & np.array((1 << bits) - 1, dtype=u.dtype)
        return u
    return arr


def _raw_compare(values, leaf, op, vlo, vhi, ckey):
    if isinstance(values, ByteArrayData):
        # bytes brackets are always exact (vlo is the value itself)
        return _bytes_compare(values, op, vlo, ckey)
    arr = np.asarray(values)
    if arr.ndim == 2:
        return _fixed_compare(arr, op, vlo)
    if arr.dtype == np.bool_:
        arr = arr.astype(np.int8)
        vlo, vhi = int(vlo), int(vhi)
    else:
        arr = _numeric_view(arr, leaf)
    try:
        return _bracket_compare(arr, op, vlo, vhi)
    except (OverflowError, TypeError) as e:
        # a filter value outside the dtype's range (or an exotic type numpy
        # refuses to coerce): the scalar walk compares exactly
        raise VecFilterError(
            f"filter_vec: {leaf.path_str}: not comparable vectorized: {e}"
        ) from None


def _bracket_compare(arr, op, lo, hi):
    """Ordered/equality comparison against the [lo, hi] physical bracket of
    the filter value x. lo == hi: x is exactly representable. lo != hi:
    lo < x < hi with no representable value between, so equality is
    impossible and each ordered op uses the end that stays exact. A NaN
    filter value brackets as (nan, nan) — `lo == hi` is then False, and the
    inexact branches below return all-False/all-True exactly like Python's
    NaN comparisons in the scalar walk."""
    exact = lo == hi
    if op == "==":
        return (arr == lo) if exact else np.zeros(len(arr), dtype=bool)
    if op == "!=":
        return (arr != lo) if exact else np.ones(len(arr), dtype=bool)
    if op == "<":
        return (arr < lo) if exact else (arr <= lo)
    if op == "<=":
        return arr <= lo
    if op == ">":
        return (arr > hi) if exact else (arr >= hi)
    if op == ">=":
        return arr >= hi
    raise VecFilterError(f"filter_vec: unsupported op {op!r}")


def _fixed_compare(arr, op, value):
    """FIXED_LEN_BYTE_ARRAY rows ((n, width) uint8): equality family only —
    the sign/byte-order conventions that would make ordered comparisons
    meaningful vary by logical type, and normalize_filters already maps the
    orderable ones (int-backed decimals) to integer brackets."""
    if op not in ("==", "!="):
        raise VecFilterError("filter_vec: ordered comparison on fixed-width bytes")
    b = bytes(value)
    if arr.shape[1] != len(b):
        eq = np.zeros(len(arr), dtype=bool)
    elif arr.shape[1] == 0:
        eq = np.ones(len(arr), dtype=bool)
    else:
        eq = (arr == np.frombuffer(b, dtype=np.uint8)).all(axis=1)
    return eq if op == "==" else ~eq


def _bytes_compare(ba: ByteArrayData, op, value, ckey):
    """Variable-length byte/string comparison, vectorized via one padded
    fixed-width view. numpy's S-dtype compares null-PADDED values — exactly
    the stored bytes except that trailing NULs tie — so every op breaks
    S-ties with the true lengths (a longer value whose prefix matches is
    the greater one) and the result is exact for arbitrary bytes.
    UTF-8 byte order equals code-point order, so str predicates coerced to
    bytes by normalize_filters compare identically to the scalar walk."""
    b = bytes(value)
    S, lens, width = _padded_bytes(ba, len(b), ckey)
    eq_s = S == b
    if op == "==":
        return eq_s & (lens == len(b))
    if op == "!=":
        return ~(eq_s & (lens == len(b)))
    if op == "<":
        return (S < b) | (eq_s & (lens < len(b)))
    if op == "<=":
        return (S < b) | (eq_s & (lens <= len(b)))
    if op == ">":
        return (S > b) | (eq_s & (lens > len(b)))
    if op == ">=":
        return (S > b) | (eq_s & (lens >= len(b)))
    raise VecFilterError(f"filter_vec: unsupported op {op!r}")


def _padded_bytes(ba: ByteArrayData, min_width: int, ckey):
    """(S-dtype array[n], int64 lengths[n], width) for one chunk's byte
    values, padded to max(longest value, the filter value) — cached per
    leaf path across the predicates of one DNF so a column referenced in N
    conjunctions pads once."""
    path, cache = ckey
    hit = cache.get(path)
    if hit is not None and hit[2] >= min_width:
        return hit
    offs = np.asarray(ba.offsets, dtype=np.int64)
    lens = np.diff(offs)
    n = len(lens)
    maxlen = int(lens.max()) if n else 0
    width = max(maxlen, min_width, 1)
    if width > _MAX_BYTES_WIDTH or n * width > _MAX_PAD_BYTES:
        raise VecFilterError(
            f"filter_vec: byte values too wide to pad ({width} B x {n})"
        )
    padded = np.zeros((n, width), dtype=np.uint8)
    if n and int(offs[-1] - offs[0]):
        src = np.frombuffer(ba.data, dtype=np.uint8)[offs[0] : offs[-1]]
        row_of = np.repeat(np.arange(n, dtype=np.int64), lens)
        within = np.arange(len(src), dtype=np.int64) - np.repeat(
            offs[:-1] - offs[0], lens
        )
        padded.reshape(-1)[row_of * width + within] = src
    S = padded.view(f"S{width}")[:, 0]
    out = (S, lens, width)
    cache[path] = out
    return out

"""Device-resident residual filtering: normalized DNF -> HBM boolean row mask.

The device twin of core/filter_vec.dnf_mask: the same (already normalized)
DNF evaluates over one row group's DEVICE-DELIVERED columns ({leaf path:
kernels.pipeline.DeviceColumn}) and yields a jax boolean row mask that never
leaves HBM — it feeds device partial aggregation directly, or
kernels/device_ops.mask_take_device for the compaction gather
(predicate -> mask -> gather, SURVEY §7.1). Value comparisons run as
device_ops.predicate_mask_device kernels over the chunk's dense values;
LIST `contains` lifts element hits to rows through
list_contains_mask_device; level-derived structure (validity, record
starts) is computed from the HOST-side level streams DeviceColumn carries
and uploaded once per referenced leaf.

Semantics are pinned to the host vec engine bracket-for-bracket:

  * comparisons happen in the PHYSICAL storage domain against the
    (stat_lo, stat_hi) bracket normalize_filters computed — lo == hi means
    exactly representable, lo != hi means the value falls BETWEEN stored
    values (equality impossible, ordered ops use the exact end);
  * unsigned logical types compare as bit-pattern views
    (lax.bitcast_convert_type + the sub-width mask — the device form of
    filter_vec._numeric_view);
  * dictionary-preserved chunks compare their (small, host-side)
    dictionary ONCE with the host engine's own comparators, then one
    device gather through the resident indices lifts the verdict to rows;
  * both null conventions ("row" and "arrow") are implemented, matching
    filter_vec._leaf_mask including pyarrow's null-keeping not_in and the
    float32 in-list cast decline.

Anything outside that envelope — non-dictionary byte arrays (no device
value ordering), out-of-range brackets, unorderable physical domains —
raises the typed DeviceFilterError and the CALLER falls back to the host
engine (counted, never silent): exactness always wins over residency.
"""

from __future__ import annotations

import numpy as np

try:
    import jax
    import jax.numpy as jnp
except ImportError:  # pragma: no cover - callers gate on jax availability
    jax = None
    jnp = None

from ..kernels.device_ops import (
    list_contains_mask_device,
    predicate_mask_device,
)
from .arrays import ByteArrayData
from .filter import FilterError
from .filter_vec import VecFilterError, _bytes_compare, _numeric_view, _raw_compare
from .stats import column_is_unsigned

__all__ = ["DeviceFilterError", "device_dnf_mask"]

# `in`-list sets compare as one equality kernel per member; a pathological
# member list would turn into a launch storm, so it takes the host engine
_MAX_MEMBERS = 64


class DeviceFilterError(FilterError):
    """The device mask pipeline cannot evaluate this predicate over these
    device-delivered columns (no device value form, uncovered shape,
    out-of-range bracket). Callers fall back to the host engine — vec mask
    or scalar walk — which is exact for everything; same contract as
    filter_vec.VecFilterError one rung down the ladder."""


def device_dnf_mask(group: dict, dnf, n_rows: int, *, null_mode: str = "row"):
    """bool[n_rows] DEVICE row mask of a normalized DNF over one row
    group's device-delivered columns ({leaf path: DeviceColumn}). Raises
    DeviceFilterError when any referenced predicate cannot run on device —
    all or nothing, so engines never mix within one group and outputs stay
    identical to the host walk whichever engine runs."""
    if jnp is None:
        raise DeviceFilterError("filter_device: jax is not importable")
    if null_mode not in ("row", "arrow"):
        raise ValueError('null_mode must be "row" or "arrow"')
    ctx: dict = {}
    out = None
    for conj in dnf:
        m = None
        for entry in conj:
            lm = _leaf_mask(group, entry, n_rows, null_mode, ctx)
            m = lm if m is None else (m & lm)
        if m is None:  # empty conjunction is vacuously true
            return jnp.ones(n_rows, dtype=bool)
        out = m if out is None else (out | m)
    if out is None:
        return jnp.ones(n_rows, dtype=bool)
    return out


# -- per-leaf masks -------------------------------------------------------------


def _leaf_mask(group, entry, n_rows, null_mode, ctx):
    path, leaf, op, value, vlo, vhi = entry
    dc = group.get(path)
    if dc is None:
        raise DeviceFilterError(
            f"filter_device: column {'.'.join(path)} not delivered on device"
        )
    if op == "contains":
        return _contains_mask(dc, leaf, vlo, vhi, n_rows, (path, ctx))
    if leaf.max_rep != 0:
        raise DeviceFilterError(f"filter_device: {'.'.join(path)} is repeated")
    if dc.num_values != n_rows:
        raise DeviceFilterError(
            f"filter_device: {'.'.join(path)}: {dc.num_values} level entries "
            f"for {n_rows} rows"
        )
    valid = None
    if leaf.max_def > 0 and dc.def_levels is not None:
        v = np.asarray(dc.def_levels) == leaf.max_def
        if not v.all():
            valid = v
    if op == "is_null":
        if valid is None:
            return jnp.zeros(n_rows, dtype=bool)
        return jnp.asarray(~valid)
    if op == "not_null":
        if valid is None:
            return jnp.ones(n_rows, dtype=bool)
        return jnp.asarray(valid)
    if op in ("in", "not_in") and null_mode == "arrow":
        # same decline as filter_vec._leaf_mask: pyarrow's is_in CASTS the
        # value set to float32, diverging from exact semantics — whichever
        # host engine takes the fallback decides, and results stay
        # value-identical to the to_arrow path
        from ..meta.parquet_types import Type

        if leaf.type == Type.FLOAT and isinstance(vlo, list) and any(
            lo is not None
            and isinstance(lo, float)
            and float(np.float32(lo)) != lo
            for lo, _ in vlo
        ):
            raise DeviceFilterError(
                f"filter_device: {leaf.path_str}: in-list member inexact in "
                "float32 (pyarrow is_in casts the value set)"
            )
    cmp = _dense_compare(dc, leaf, op, vlo, vhi, (path, ctx))
    nd = int(valid.sum()) if valid is not None else n_rows
    if cmp.shape[0] != nd:
        raise DeviceFilterError(
            f"filter_device: {'.'.join(path)}: {cmp.shape[0]} dense values "
            f"for {nd} defined cells"
        )
    if op == "not_in" and null_mode == "arrow":
        # pyarrow's pc.invert(pc.is_in(...)) maps null to True: nulls KEPT
        if valid is None:
            return cmp
        v, didx = _valid_expand(valid, nd, ctx, path)
        if nd == 0:
            return jnp.asarray(~valid)
        return (~v) | (v & cmp[didx])
    if valid is None:
        return cmp
    if nd == 0:
        return jnp.zeros(n_rows, dtype=bool)
    v, didx = _valid_expand(valid, nd, ctx, path)
    return v & cmp[didx]


def _valid_expand(valid_np, nd, ctx, path):
    """(device validity mask, dense-index gather map) for one leaf: entry i
    reads dense cell cumsum(valid)[i] - 1 — uploaded once per path, shared
    by every predicate of the DNF that references the column."""
    key = ("valid", path)
    hit = ctx.get(key)
    if hit is not None:
        return hit
    v = jnp.asarray(valid_np)
    didx = jnp.clip(
        jnp.cumsum(v.astype(jnp.int32)) - 1, 0, max(nd - 1, 0)
    )
    ctx[key] = (v, didx)
    return v, didx


def _contains_mask(dc, leaf, vlo, vhi, n_rows, ckey):
    """List-slot membership on device: the dense element equality mask
    scatters through the (host-carried, uploaded-once) level streams to row
    membership — list_contains_mask_device, the kernel twin of
    filter_vec._contains_mask."""
    if dc.rep_levels is None:
        raise DeviceFilterError(
            f"filter_device: {leaf.path_str}: contains without repetition levels"
        )
    rl = np.asarray(dc.rep_levels)
    if len(rl) == 0:
        return jnp.zeros(n_rows, dtype=bool)
    if int(rl[0]) != 0:
        raise DeviceFilterError(
            f"filter_device: {leaf.path_str}: stream opens mid-record"
        )
    if int((rl == 0).sum()) != n_rows:
        raise DeviceFilterError(
            f"filter_device: {leaf.path_str}: record count != row count"
        )
    if dc.def_levels is not None:
        dfl = np.asarray(dc.def_levels).astype(np.int32, copy=False)
    else:
        dfl = np.full(len(rl), leaf.max_def, dtype=np.int32)
    nd = int((dfl == leaf.max_def).sum())
    dm = _dense_compare(dc, leaf, "==", vlo, vhi, ckey)
    if dm.shape[0] != nd:
        raise DeviceFilterError(
            f"filter_device: {leaf.path_str}: level/value mismatch"
        )
    rows, _n = list_contains_mask_device(
        jnp.asarray(rl.astype(np.int32, copy=False)),
        jnp.asarray(dfl),
        dm,
        leaf.max_def,
    )
    return rows[:n_rows]


# -- dense value comparison -----------------------------------------------------


def _dense_compare(dc, leaf, op, vlo, vhi, ckey):
    """bool DEVICE mask over the chunk's dense (non-null) values for one
    value op, in the physical domain — predicate_mask_device for resident
    numerics, a host dictionary compare + device gather for
    dictionary-preserved chunks."""
    if vlo is None:
        raise DeviceFilterError(
            f"filter_device: {leaf.path_str}: no orderable physical form"
        )
    if op in ("in", "not_in"):
        if any(lo is None for lo, _ in vlo):
            raise DeviceFilterError(
                f"filter_device: {leaf.path_str}: unorderable in-list member"
            )
        if len(vlo) > _MAX_MEMBERS:
            raise DeviceFilterError(
                f"filter_device: {leaf.path_str}: in-list of {len(vlo)} "
                f"members (> {_MAX_MEMBERS}) takes the host engine"
            )
        m = _member_mask(dc, leaf, vlo, ckey)
        return ~m if op == "not_in" else m
    if dc.values is None and dc.indices is not None and dc.dictionary is not None:
        # dictionary-preserved chunk: the host engine compares the (small)
        # dictionary once, one device gather lifts it through the indices
        dcmp = _host_compare(dc.dictionary, leaf, op, vlo, vhi, ckey)
        return jnp.asarray(dcmp)[dc.indices]
    if dc.values is None:
        raise DeviceFilterError(
            f"filter_device: {leaf.path_str}: no device value form "
            "(raw byte arrays have no resident ordering)"
        )
    return _device_compare(dc.values, leaf, op, vlo, vhi)


def _member_mask(dc, leaf, brackets, ckey):
    """OR of equality masks for the in-list members (an inexact bracket can
    equal no stored value: exact=False contributes all-False, matching the
    host engine's exact-members-only isin)."""
    via_dict = (
        dc.values is None and dc.indices is not None and dc.dictionary is not None
    )
    if via_dict:
        exact = [lo for lo, hi in brackets if lo == hi]
        m = _host_dict_members(dc.dictionary, leaf, exact, ckey)
        return jnp.asarray(m)[dc.indices]
    if dc.values is None:
        raise DeviceFilterError(
            f"filter_device: {leaf.path_str}: no device value form "
            "(raw byte arrays have no resident ordering)"
        )
    m = None
    for lo, hi in brackets:
        em = _device_compare(dc.values, leaf, "==", lo, hi)
        m = em if m is None else (m | em)
    if m is None:
        return jnp.zeros(dc.values.shape[0], dtype=bool)
    return m


def _host_dict_members(dictionary, leaf, members, ckey):
    """np bool mask over a HOST dictionary for the exactly-representable
    in-list members — filter_vec._member_mask's target compare, reused so
    bytes/unsigned semantics stay single-sourced."""
    try:
        if not members:
            return np.zeros(len(dictionary), dtype=bool)
        if isinstance(dictionary, ByteArrayData):
            m = None
            for b in members:
                em = _bytes_compare(dictionary, "==", b, ckey)
                m = em if m is None else (m | em)
            return m
        arr = np.asarray(dictionary)
        if arr.ndim != 1:
            m = None
            for b in members:
                em = _raw_compare(dictionary, leaf, "==", b, b, ckey)
                m = em if m is None else (m | em)
            return m
        try:
            return np.isin(_numeric_view(arr, leaf), np.array(members))
        except (OverflowError, TypeError, ValueError) as e:
            raise VecFilterError(
                f"filter_device: {leaf.path_str}: in-list not comparable: {e}"
            ) from None
    except VecFilterError as e:
        raise DeviceFilterError(str(e)) from None


def _host_compare(dictionary, leaf, op, vlo, vhi, ckey):
    try:
        return _raw_compare(dictionary, leaf, op, vlo, vhi, ckey)
    except VecFilterError as e:
        raise DeviceFilterError(str(e)) from None


def _device_compare(values, leaf, op, vlo, vhi):
    """predicate_mask_device over resident values, with the bracket coerced
    to the array's dtype HOST-SIDE (a weak python scalar would re-promote
    on device; an out-of-range bracket declines instead of wrapping)."""
    if values.ndim == 2:
        return _fixed_compare(values, op, vlo)
    dt = np.dtype(values.dtype.name)
    if dt == np.bool_:
        # mirror filter_vec._raw_compare: booleans compare as int8
        if not isinstance(vlo, (bool, int, np.integer)) or not isinstance(
            vhi, (bool, int, np.integer)
        ):
            raise DeviceFilterError(
                f"filter_device: {leaf.path_str}: non-integer bracket on bool"
            )
        return predicate_mask_device(
            values.astype(jnp.int8),
            op,
            np.int8(int(vlo)),
            np.int8(int(vhi)),
            bool(int(vlo) == int(vhi)),
        )
    arr = _device_numeric_view(values, leaf)
    dt = np.dtype(arr.dtype.name)
    if dt.kind in "iu":
        if not isinstance(vlo, (int, np.integer)) or not isinstance(
            vhi, (int, np.integer)
        ):
            raise DeviceFilterError(
                f"filter_device: {leaf.path_str}: non-integer bracket on an "
                "integer column"
            )
        info = np.iinfo(dt)
        if int(vlo) < info.min or int(vhi) > info.max:
            raise DeviceFilterError(
                f"filter_device: {leaf.path_str}: bracket outside {dt} range"
            )
        lo, hi = dt.type(int(vlo)), dt.type(int(vhi))
    elif dt.kind == "f":
        try:
            lo, hi = dt.type(vlo), dt.type(vhi)
        except (OverflowError, TypeError, ValueError) as e:
            raise DeviceFilterError(
                f"filter_device: {leaf.path_str}: bracket not representable: {e}"
            ) from None
    else:
        raise DeviceFilterError(
            f"filter_device: {leaf.path_str}: uncovered device dtype {dt}"
        )
    try:
        return predicate_mask_device(arr, op, lo, hi, bool(vlo == vhi))
    except ValueError as e:
        raise DeviceFilterError(f"filter_device: {leaf.path_str}: {e}") from None


def _device_numeric_view(arr, leaf):
    """The resident array in its COMPARISON domain — the device form of
    filter_vec._numeric_view: unsigned logical types reinterpret the stored
    bit pattern (bitcast + sub-width mask)."""
    if not column_is_unsigned(leaf):
        return arr
    from .assembly import logical_kind

    kind = logical_kind(leaf)
    bits = kind[1] if isinstance(kind, tuple) and kind[0] == "uint" else None
    if arr.dtype == jnp.int32:
        arr = jax.lax.bitcast_convert_type(arr, jnp.uint32)
    elif arr.dtype == jnp.int64:
        arr = jax.lax.bitcast_convert_type(arr, jnp.uint64)
    if bits is not None and bits < np.dtype(arr.dtype.name).itemsize * 8:
        arr = arr & np.dtype(arr.dtype.name).type((1 << bits) - 1)
    return arr


def _fixed_compare(arr, op, value):
    """FIXED_LEN_BYTE_ARRAY rows ((n, width) uint8) on device: equality
    family only, exactly like filter_vec._fixed_compare."""
    if op not in ("==", "!="):
        raise DeviceFilterError(
            "filter_device: ordered comparison on fixed-width bytes"
        )
    b = bytes(value)
    if arr.shape[1] != len(b):
        eq = jnp.zeros(arr.shape[0], dtype=bool)
    elif arr.shape[1] == 0:
        eq = jnp.ones(arr.shape[0], dtype=bool)
    else:
        eq = jnp.all(arr == jnp.asarray(np.frombuffer(b, dtype=np.uint8)), axis=1)
    return eq if op == "==" else ~eq

"""Record shredding: nested rows -> per-leaf (value, def, rep) streams.

Write-side Dremel, the inverse of assembly.py — the semantics of the
reference's recursiveAddColumnData/nil-propagation (reference:
schema.go:837-891, :802-819) with one addition: ergonomic input. The reference
only accepts raw nested maps ({"list": [{"element": v}]}); here LIST-annotated
groups also accept plain Python lists and MAP-annotated groups plain dicts,
mirroring the reader's raw/ergonomic duality.
"""

from __future__ import annotations

from ..meta.parquet_types import ConvertedType, FieldRepetitionType
from .schema import Column, Schema

__all__ = ["Shredder", "ShredError"]


class ShredError(ValueError):
    pass


def _value_size(v) -> int:
    """Approximate encoded size of one leaf value (reference: the per-type
    sizeOf of the typed stores, interfaces.go:67-81). Strings/bytes charge
    their real length — a flat per-value constant made string-heavy row
    groups overshoot the target size badly."""
    if isinstance(v, (str, bytes)):
        return len(v) + 4
    return 8


class _LeafBuffer:
    __slots__ = ("values", "def_levels", "rep_levels", "data_size")

    def __init__(self):
        self.values: list = []
        self.def_levels: list[int] = []
        self.rep_levels: list[int] = []
        self.data_size = 0  # approximate bytes of buffered values


class Shredder:
    """Accumulates shredded rows for all leaves of a schema."""

    def __init__(self, schema: Schema):
        self.schema = schema
        self.buffers: dict[tuple, _LeafBuffer] = {
            leaf.path: _LeafBuffer() for leaf in schema.leaves
        }
        self.num_rows = 0

    def add_row(self, row: dict) -> None:
        if not isinstance(row, dict):
            raise ShredError(f"shred: row must be a dict, got {type(row).__name__}")
        for child in self.schema.root.children:
            self._shred(child, row.get(child.name), 0, 0)
        self.num_rows += 1

    # -- core recursion --------------------------------------------------------

    def _shred(self, node: Column, value, rep: int, parent_def: int) -> None:
        r = node.repetition
        if r == FieldRepetitionType.REPEATED:
            items = self._as_repeated(node, value)
            if not items:
                self._null_subtree(node, rep, parent_def)
                return
            for i, item in enumerate(items):
                self._present(node, item, rep if i == 0 else node.max_rep)
            return
        if value is None:
            if r == FieldRepetitionType.REQUIRED:
                raise ShredError(f"shred: required field {node.path_str} is None")
            self._null_subtree(node, rep, parent_def)
            return
        self._present(node, value, rep)

    def _present(self, node: Column, value, rep: int) -> None:
        if node.is_leaf:
            if value is None:
                # Only reachable for REPEATED leaves: a bare repeated field has
                # no definition level to express a null element.
                raise ShredError(
                    f"shred: null element in repeated field {node.path_str} "
                    "(wrap the element in an optional group to store nulls)"
                )
            buf = self.buffers[node.path]
            buf.values.append(value)
            buf.data_size += _value_size(value)
            buf.def_levels.append(node.max_def)
            buf.rep_levels.append(rep)
            return
        value = self._normalize_group(node, value)
        if not isinstance(value, dict):
            raise ShredError(
                f"shred: group {node.path_str} expects a dict, got {type(value).__name__}"
            )
        for child in node.children:
            self._shred(child, value.get(child.name), rep, node.max_def)

    def _null_subtree(self, node: Column, rep: int, def_level: int) -> None:
        """One absent entry for every leaf beneath `node`
        (reference: schema.go:802-819 nil-propagation)."""
        if node.is_leaf:
            buf = self.buffers[node.path]
            buf.values.append(None)
            buf.def_levels.append(def_level)
            buf.rep_levels.append(rep)
            return
        for child in node.children:
            self._null_subtree(child, rep, def_level)

    # -- ergonomic sugar -------------------------------------------------------

    def _as_repeated(self, node: Column, value) -> list:
        if value is None:
            return []
        if isinstance(value, (list, tuple)):
            return list(value)
        raise ShredError(
            f"shred: repeated field {node.path_str} expects a list, "
            f"got {type(value).__name__}"
        )

    def _normalize_group(self, node: Column, value):
        """Accept plain lists for LIST groups and dicts for MAP groups."""
        ct = node.converted_type
        lt = node.logical_type
        is_list = ct == ConvertedType.LIST or (lt is not None and lt.LIST is not None)
        is_map = ct in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE) or (
            lt is not None and lt.MAP is not None
        )
        if is_list and isinstance(value, (list, tuple)) and len(node.children) == 1:
            mid = node.children[0]
            if mid.repetition == FieldRepetitionType.REPEATED:
                if mid.is_leaf or len(mid.children) != 1:
                    return {mid.name: list(value)}
                elem = mid.children[0]
                return {mid.name: [{elem.name: v} for v in value]}
        if is_map and isinstance(value, dict) and len(node.children) == 1:
            kv = node.children[0]
            if (
                kv.repetition == FieldRepetitionType.REPEATED
                and not kv.is_leaf
                and len(kv.children) == 2
                # Raw nested form is {"key_value": [...]} — require the value
                # to be a list so a real map entry whose key happens to be
                # "key_value" still takes the ergonomic path.
                and not (
                    set(value.keys()) == {kv.name}
                    and isinstance(value.get(kv.name), (list, tuple, type(None)))
                )
            ):
                kname = kv.children[0].name
                vname = kv.children[1].name
                return {kv.name: [{kname: k, vname: v} for k, v in value.items()]}
        return value

    # -- draining --------------------------------------------------------------

    def drain(self):
        """Return and reset the accumulated per-leaf buffers."""
        out = {
            path: (b.values, b.def_levels, b.rep_levels)
            for path, b in self.buffers.items()
        }
        self.buffers = {leaf.path: _LeafBuffer() for leaf in self.schema.leaves}
        n = self.num_rows
        self.num_rows = 0
        return out, n

"""Record shredding: nested rows -> per-leaf (value, def, rep) streams.

Write-side Dremel, the inverse of assembly.py — the semantics of the
reference's recursiveAddColumnData/nil-propagation (reference:
schema.go:837-891, :802-819) with one addition: ergonomic input. The reference
only accepts raw nested maps ({"list": [{"element": v}]}); here LIST-annotated
groups also accept plain Python lists and MAP-annotated groups plain dicts,
mirroring the reader's raw/ergonomic duality.

The schema walk is COMPILED once per Shredder: each node becomes a closure
with its repetition kind, levels, annotation sugar, and leaf buffers bound as
locals, so the per-row hot path does no attribute lookups, enum compares, or
annotation checks (the interpreted walk measured 3x slower on nested rows).
"""

from __future__ import annotations

from ..meta.parquet_types import ConvertedType, FieldRepetitionType
from .schema import Column, Schema

__all__ = ["Shredder", "ShredError"]

_REPEATED = FieldRepetitionType.REPEATED
_REQUIRED = FieldRepetitionType.REQUIRED


class ShredError(ValueError):
    pass


def _value_size(v) -> int:
    """Approximate encoded size of one leaf value (reference: the per-type
    sizeOf of the typed stores, interfaces.go:67-81). Strings/bytes charge
    their real length — a flat per-value constant made string-heavy row
    groups overshoot the target size badly."""
    if isinstance(v, (str, bytes)):
        return len(v) + 4
    return 8


class _LeafBuffer:
    __slots__ = ("values", "def_levels", "rep_levels", "data_size")

    def __init__(self):
        self.values: list = []
        self.def_levels: list[int] = []
        self.rep_levels: list[int] = []
        self.data_size = 0  # approximate bytes of buffered values


class Shredder:
    """Accumulates shredded rows for all leaves of a schema."""

    def __init__(self, schema: Schema):
        self.schema = schema
        self.buffers: dict[tuple, _LeafBuffer] = {
            leaf.path: _LeafBuffer() for leaf in schema.leaves
        }
        self.num_rows = 0
        # buffer objects stay stable across drain() (lists rebind inside
        # them), so the compiled closures below never go stale
        self._fields = [
            (child.name, self._compile(child)) for child in schema.root.children
        ]

    def add_row(self, row: dict) -> None:
        if not isinstance(row, dict):
            raise ShredError(f"shred: row must be a dict, got {type(row).__name__}")
        get = row.get
        for name, fn in self._fields:
            fn(get(name), 0, 0)
        self.num_rows += 1

    # -- compilation (once per schema) -----------------------------------------

    def _compile(self, node: Column):
        """node -> fn(value, rep, parent_def) with everything prebound."""
        present = (
            self._compile_leaf(node) if node.is_leaf else self._compile_group(node)
        )
        nulls = self._compile_null(node)
        rep_kind = node.repetition
        path_str = node.path_str
        if rep_kind == _REPEATED:
            max_rep = node.max_rep

            def shred_repeated(
                value, rep, parent_def, present=present, nulls=nulls,
                max_rep=max_rep, path_str=path_str,
            ):
                if value is None:
                    nulls(rep, parent_def)
                    return
                if not isinstance(value, (list, tuple)):
                    raise ShredError(
                        f"shred: repeated field {path_str} expects a list, "
                        f"got {type(value).__name__}"
                    )
                if not value:
                    nulls(rep, parent_def)
                    return
                it = iter(value)
                present(next(it), rep)
                for item in it:  # no value[1:] copy on the hot path
                    present(item, max_rep)

            return shred_repeated
        if rep_kind == _REQUIRED:

            def shred_required(
                value, rep, parent_def, present=present, path_str=path_str
            ):
                if value is None:
                    raise ShredError(f"shred: required field {path_str} is None")
                present(value, rep)

            return shred_required

        def shred_optional(value, rep, parent_def, present=present, nulls=nulls):
            if value is None:
                nulls(rep, parent_def)
            else:
                present(value, rep)

        return shred_optional

    def _compile_leaf(self, node: Column):
        buf = self.buffers[node.path]
        max_def = node.max_def
        path_str = node.path_str

        def present_leaf(value, rep, buf=buf, max_def=max_def, path_str=path_str):
            if value is None:
                # Only reachable for REPEATED leaves: a bare repeated field
                # has no definition level to express a null element.
                raise ShredError(
                    f"shred: null element in repeated field {path_str} "
                    "(wrap the element in an optional group to store nulls)"
                )
            buf.values.append(value)
            # inlined _value_size (call elision on the hottest line); keep
            # the size model in sync with _value_size below
            buf.data_size += (
                len(value) + 4 if isinstance(value, (str, bytes)) else 8
            )
            buf.def_levels.append(max_def)
            buf.rep_levels.append(rep)

        return present_leaf

    def _compile_group(self, node: Column):
        children = [(c.name, self._compile(c)) for c in node.children]
        max_def = node.max_def
        path_str = node.path_str
        normalize = self._compile_normalize(node)

        def present_group(
            value, rep, children=children, max_def=max_def,
            normalize=normalize, path_str=path_str,
        ):
            if normalize is not None:
                value = normalize(value)
            if not isinstance(value, dict):
                raise ShredError(
                    f"shred: group {path_str} expects a dict, "
                    f"got {type(value).__name__}"
                )
            get = value.get
            for name, fn in children:
                fn(get(name), rep, max_def)

        return present_group

    def _compile_null(self, node: Column):
        """One absent entry for every leaf beneath `node`
        (reference: schema.go:802-819 nil-propagation)."""
        bufs: list[_LeafBuffer] = []
        self._collect_leaf_buffers(node, bufs)

        def nulls(rep, def_level, bufs=bufs):
            for buf in bufs:
                buf.values.append(None)
                buf.def_levels.append(def_level)
                buf.rep_levels.append(rep)

        return nulls

    def _collect_leaf_buffers(self, node: Column, out: list) -> None:
        if node.is_leaf:
            out.append(self.buffers[node.path])
            return
        for child in node.children:
            self._collect_leaf_buffers(child, out)

    def _compile_normalize(self, node: Column):
        """Ergonomic sugar, decided at compile time: LIST groups accept
        plain lists, MAP groups plain dicts; None for plain groups."""
        ct = node.converted_type
        lt = node.logical_type
        is_list = ct == ConvertedType.LIST or (lt is not None and lt.LIST is not None)
        is_map = ct in (ConvertedType.MAP, ConvertedType.MAP_KEY_VALUE) or (
            lt is not None and lt.MAP is not None
        )
        if is_list and len(node.children) == 1:
            mid = node.children[0]
            if mid.repetition == _REPEATED:
                mid_name = mid.name
                if mid.is_leaf or len(mid.children) != 1:

                    def norm_bare_list(value, mid_name=mid_name):
                        if isinstance(value, (list, tuple)):
                            return {mid_name: list(value)}
                        return value

                    return norm_bare_list
                elem_name = mid.children[0].name

                def norm_list(value, mid_name=mid_name, elem_name=elem_name):
                    if isinstance(value, (list, tuple)):
                        return {mid_name: [{elem_name: v} for v in value]}
                    return value

                return norm_list
        if is_map and len(node.children) == 1:
            kv = node.children[0]
            if (
                kv.repetition == _REPEATED
                and not kv.is_leaf
                and len(kv.children) == 2
            ):
                kv_name = kv.name
                kname = kv.children[0].name
                vname = kv.children[1].name

                def norm_map(value, kv_name=kv_name, kname=kname, vname=vname):
                    # Raw nested form is {"key_value": [...]} — require the
                    # value to be a list so a real map entry whose key
                    # happens to be "key_value" still takes this path.
                    if isinstance(value, dict) and not (
                        set(value.keys()) == {kv_name}
                        and isinstance(
                            value.get(kv_name), (list, tuple, type(None))
                        )
                    ):
                        return {
                            kv_name: [{kname: k, vname: v} for k, v in value.items()]
                        }
                    return value

                return norm_map
        return None

    # -- draining --------------------------------------------------------------

    def drain(self):
        """Return and reset the accumulated per-leaf buffers (the buffer
        OBJECTS persist — compiled closures hold them)."""
        out = {}
        for path, b in self.buffers.items():
            out[path] = (b.values, b.def_levels, b.rep_levels)
            b.values = []
            b.def_levels = []
            b.rep_levels = []
            b.data_size = 0
        n = self.num_rows
        self.num_rows = 0
        return out, n

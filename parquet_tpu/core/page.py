"""Data page (V1/V2) and dictionary page decode/encode.

Layout semantics follow the reference:
  V1 (reference: page_v1.go): [rep levels: 4-byte size + hybrid] [def levels:
     same] [values] — all inside one optionally-compressed block; optional CRC
     over the compressed block.
  V2 (reference: page_v2.go): rep + def level streams stored RAW (uncompressed,
     no size prefix — sizes live in the page header) ahead of the
     optionally-compressed values block; CRC over rep+def+compressed values.
  Dict page (reference: page_dict.go): PLAIN values of the column type.

Decode is page-at-a-time into typed arrays. The `values` of a dictionary-encoded
page stay as (indices, dictionary) until materialization so the TPU backend can
batch the gathers (kernels/pipeline.py).
"""

from __future__ import annotations

import struct as _struct
import zlib
from contextlib import contextmanager as _contextmanager
from dataclasses import dataclass

import numpy as np

from ..meta.file_meta import ParquetFileError
from ..meta.parquet_types import (
    DataPageHeader,
    DataPageHeaderV2,
    DictionaryPageHeader,
    Encoding,
    PageHeader,
    Type,
)
from ..ops import bytearray as ba_ops
from ..ops import delta as delta_ops
from ..ops import plain as plain_ops
from ..ops.dictionary import decode_dict_indices, encode_dict_indices
from ..ops.levels import (
    decode_levels_v1,
    decode_levels_v2,
    encode_levels_v1,
    encode_levels_v2,
)
from .arrays import ByteArrayData
from .compress import compress_block, decompress_block
from .schema import Column
from ..utils import metrics as _metrics
from ..utils.trace import stage

__all__ = ["DecodedPage", "PageError", "decode_data_page_v1", "decode_data_page_v2",
           "decode_dict_page", "encode_data_page_v1", "encode_data_page_v2",
           "encode_dict_page"]


class PageError(ValueError):
    pass


class MissingDictionaryError(PageError):
    """A data page references a chunk dictionary that is absent (or failed
    to decode). Distinct type so triage tooling (parquet-tool verify) can
    tell a DEPENDENT failure — data pages orphaned by one rotten dictionary
    page — from independent corruption, without matching message text."""


@_contextmanager
def typed_page_errors(what: str):
    """Context manager converting ANY stray exception from decoding
    untrusted page bytes into a typed PageError (already-typed Parquet
    errors pass through). Corrupt input must never surface as a raw
    struct.error / zlib.error / IndexError / OverflowError — the
    fault-injection harness (parquet_tpu.testing.faults) enforces this
    contract over every decode entry point."""
    try:
        yield
    except (PageError, ParquetFileError):
        raise
    except ValueError as e:
        # ChunkError is a ValueError defined downstream (core.chunk imports
        # this module); keep its exact message when it bubbles through a
        # page decode
        if type(e).__name__ == "ChunkError":
            raise
        raise PageError(f"page: corrupt {what}: {e}") from e
    except (
        KeyError,
        IndexError,
        OverflowError,
        ZeroDivisionError,
        TypeError,
        EOFError,
        _struct.error,
        zlib.error,
    ) as e:
        # MemoryError deliberately NOT converted: genuine memory pressure on
        # a valid page is not corruption, and under on_error='skip' a typed
        # rewrap would silently quarantine valid rows (header-driven bomb
        # allocations are already rejected by the preflight size guards
        # before any allocation happens)
        raise PageError(f"page: corrupt {what}: {e!r}") from e


@dataclass
class DecodedPage:
    """One decoded data page.

    num_values counts level entries (incl. nulls/empty lists); `values` holds
    only the non-null cells. For dictionary-encoded pages `indices` is set and
    `values` is None until materialized against the chunk dictionary.
    """

    num_values: int
    def_levels: np.ndarray | None
    rep_levels: np.ndarray | None
    values: object | None = None
    indices: np.ndarray | None = None

    def materialize(self, dictionary):
        if self.values is None and self.indices is not None:
            if dictionary is None:
                raise MissingDictionaryError(
                    "page: dictionary-encoded page but no dictionary page"
                )
            try:
                if isinstance(dictionary, ByteArrayData):
                    self.values = dictionary.take(self.indices)
                else:
                    self.values = np.asarray(dictionary)[self.indices]
            except (IndexError, ValueError) as e:
                # corrupt index stream, not a programming error: stay typed
                raise PageError(
                    f"page: dictionary index out of range: {e}"
                ) from e
        return self


_DICT_ENCODINGS = (int(Encoding.PLAIN_DICTIONARY), int(Encoding.RLE_DICTIONARY))


def _decode_values(
    data, n: int, encoding: int, column: Column, dict_size: int | None
):
    """Value-decoder selection matrix by (type, encoding)
    (reference: chunk_reader.go:41-159)."""
    ptype = column.type
    if encoding in _DICT_ENCODINGS:
        if dict_size is None:
            raise MissingDictionaryError(
                "page: dictionary encoding without dictionary"
            )
        return None, decode_dict_indices(data, n, dict_size)
    if encoding == int(Encoding.PLAIN):
        values, _ = plain_ops.decode_plain(data, n, ptype, column.type_length)
        return values, None
    if encoding == int(Encoding.DELTA_BINARY_PACKED):
        if ptype == Type.INT32:
            values, _ = delta_ops.decode_delta(data, 32, max_total=n)
        elif ptype == Type.INT64:
            values, _ = delta_ops.decode_delta(data, 64, max_total=n)
        else:
            raise PageError(f"page: DELTA_BINARY_PACKED unsupported for {ptype}")
        if len(values) < n:
            raise PageError(
                f"page: delta stream has {len(values)} values, page needs {n}"
            )
        return values[:n], None
    if encoding == int(Encoding.DELTA_LENGTH_BYTE_ARRAY):
        if ptype != Type.BYTE_ARRAY:
            raise PageError("page: DELTA_LENGTH_BYTE_ARRAY only for BYTE_ARRAY")
        values, _ = ba_ops.decode_delta_length_byte_array(data, n)
        return values, None
    if encoding == int(Encoding.DELTA_BYTE_ARRAY):
        if ptype != Type.BYTE_ARRAY:
            raise PageError("page: DELTA_BYTE_ARRAY only for BYTE_ARRAY")
        values, _ = ba_ops.decode_delta_byte_array(data, n)
        return values, None
    if encoding == int(Encoding.RLE):
        if ptype != Type.BOOLEAN:
            raise PageError("page: RLE value encoding only for BOOLEAN")
        # 4-byte length prefix + hybrid at width 1 (reference: type_boolean.go:100-146)
        levels, _ = decode_levels_v1(data, n, 1)
        return levels.astype(bool), None
    if encoding == int(Encoding.BYTE_STREAM_SPLIT):
        from ..ops.byte_stream_split import decode_byte_stream_split

        try:
            return decode_byte_stream_split(data, n, ptype, column.type_length), None
        except ValueError as e:
            raise PageError(f"page: {e}") from e
    try:
        name = Encoding(encoding).name
    except ValueError:
        name = str(encoding)
    raise PageError(f"page: unsupported value encoding {name} for {ptype}")


def decode_data_page_v1(
    header: PageHeader, block: bytes, column: Column, dict_size: int | None
) -> DecodedPage:
    h: DataPageHeader = header.data_page_header
    if h is None:
        raise PageError("page: DATA_PAGE without data_page_header")
    n = h.num_values or 0
    if n < 0:
        raise PageError(f"page: negative num_values {n}")
    buf = memoryview(block)
    pos = 0
    rep = None
    with typed_page_errors("v1 level stream"):
        if column.max_rep > 0:
            rep, used = decode_levels_v1(buf, n, column.max_rep)
            pos += used
        dfl = None
        non_null = n
        if column.max_def > 0:
            dfl, used = decode_levels_v1(buf[pos:], n, column.max_def)
            pos += used
            non_null = int((dfl == column.max_def).sum())
    with typed_page_errors("v1 value stream"):
        values, indices = _decode_values(
            buf[pos:], non_null, h.encoding, column, dict_size
        )
    _metrics.page_decoded(_metrics.encoding_name(h.encoding), nbytes=len(block))
    return DecodedPage(
        num_values=n, def_levels=dfl, rep_levels=rep, values=values, indices=indices
    )


def decode_data_page_v2(
    header: PageHeader,
    raw: bytes,
    column: Column,
    dict_size: int | None,
    codec: int,
) -> DecodedPage:
    """`raw` is the page exactly as stored: levels raw + values (maybe compressed)."""
    h: DataPageHeaderV2 = header.data_page_header_v2
    if h is None:
        raise PageError("page: DATA_PAGE_V2 without data_page_header_v2")
    n = h.num_values or 0
    rep_len = h.repetition_levels_byte_length or 0
    def_len = h.definition_levels_byte_length or 0
    if rep_len < 0 or def_len < 0 or rep_len + def_len > len(raw):
        raise PageError("page: v2 level sizes exceed page")
    buf = memoryview(raw)
    rep = None
    with typed_page_errors("v2 level stream"):
        if column.max_rep > 0:
            rep = decode_levels_v2(buf[:rep_len], n, column.max_rep)
        elif rep_len:
            raise PageError("page: v2 rep levels present for flat column")
        dfl = None
        non_null = n
        if column.max_def > 0:
            dfl = decode_levels_v2(buf[rep_len : rep_len + def_len], n, column.max_def)
            non_null = int((dfl == column.max_def).sum())
    if h.num_nulls is not None and dfl is not None and column.max_rep == 0:
        # FLAT columns only: for repeated columns parquet-cpp counts
        # num_nulls as null VALUES (def one below max at the element or a
        # struct member), excluding empty-list/ancestor placeholders — the
        # "non_null = num_values - num_nulls" invariant does not hold for
        # its nested pages (found by differential fuzz vs pyarrow), so the
        # levels are the only trustworthy source there
        if n - non_null != h.num_nulls:
            raise PageError(
                f"page: v2 header claims {h.num_nulls} nulls, levels say {n - non_null}"
            )
    values_block = bytes(buf[rep_len + def_len :])
    if h.is_compressed is None or h.is_compressed:
        uncompressed = (header.uncompressed_page_size or 0) - rep_len - def_len
        with stage("decompress", len(values_block)):
            values_block = decompress_block(values_block, codec, max(uncompressed, 0))
    with typed_page_errors("v2 value stream"):
        values, indices = _decode_values(
            values_block, non_null, h.encoding, column, dict_size
        )
    _metrics.page_decoded(
        _metrics.encoding_name(h.encoding),
        nbytes=header.uncompressed_page_size or 0,
    )
    return DecodedPage(
        num_values=n, def_levels=dfl, rep_levels=rep, values=values, indices=indices
    )


def decode_dict_page(
    header: PageHeader, block: bytes, column: Column, count_metrics: bool = True
):
    """count_metrics=False lets the fused native lane defer its page
    counters until the whole chunk plan commits (kernels/pipeline.py) —
    counting here would double the dict page if the plan later falls back
    to the staged walk."""
    h: DictionaryPageHeader = header.dictionary_page_header
    if h is None:
        raise PageError("page: DICTIONARY_PAGE without header")
    n = h.num_values or 0
    if n < 0:
        raise PageError("page: negative dictionary size")
    enc = h.encoding
    if enc not in (int(Encoding.PLAIN), int(Encoding.PLAIN_DICTIONARY)):
        raise PageError(f"page: dictionary page encoding {enc} unsupported")
    with typed_page_errors("dictionary page"):
        values, consumed = plain_ops.decode_plain(
            block, n, column.type, column.type_length
        )
    if consumed != len(block):
        # Strict full decode (reference: page_dict.go:35-72): trailing bytes
        # mean the header lied about num_values or the page is corrupt.
        raise PageError(
            f"page: dictionary page has {len(block) - consumed} trailing bytes"
        )
    if count_metrics:
        _metrics.page_decoded(_metrics.encoding_name(enc), nbytes=len(block))
    return values


# -- write side ----------------------------------------------------------------


def encode_data_page_v1(
    column: Column,
    values,
    def_levels,
    rep_levels,
    encoding: Encoding,
    codec: int,
    dict_size: int | None = None,
    with_crc: bool = False,
) -> tuple[PageHeader, bytes]:
    n = _count_level_entries(values, def_levels)
    vals = _encode_values(values, encoding, column, dict_size)
    if column.max_rep > 0 or column.max_def > 0:
        payload = bytearray()
        if column.max_rep > 0:
            payload += encode_levels_v1(rep_levels, column.max_rep)
        if column.max_def > 0:
            payload += encode_levels_v1(def_levels, column.max_def)
        payload += vals
        raw = payload
    else:
        raw = vals  # flat required column: the value stream IS the page
    block = compress_block(raw, codec)
    header = PageHeader(
        type=0,
        uncompressed_page_size=len(raw),
        compressed_page_size=len(block),
        data_page_header=DataPageHeader(
            num_values=n,
            encoding=int(encoding),
            definition_level_encoding=int(Encoding.RLE),
            repetition_level_encoding=int(Encoding.RLE),
        ),
    )
    if with_crc:
        header.crc = _crc32_signed(block)
    return header, block


def encode_data_page_v2(
    column: Column,
    values,
    def_levels,
    rep_levels,
    encoding: Encoding,
    codec: int,
    dict_size: int | None = None,
    with_crc: bool = False,
) -> tuple[PageHeader, bytes]:
    n = _count_level_entries(values, def_levels)
    rep_block = (
        encode_levels_v2(rep_levels, column.max_rep) if column.max_rep > 0 else b""
    )
    def_block = (
        encode_levels_v2(def_levels, column.max_def) if column.max_def > 0 else b""
    )
    values_raw = _encode_values(values, encoding, column, dict_size)
    values_block = compress_block(values_raw, codec)
    block = rep_block + def_block + values_block
    num_nulls = 0
    num_rows = n
    if def_levels is not None and column.max_def > 0:
        dl = np.asarray(def_levels)
        num_nulls = int((dl != column.max_def).sum())
    if rep_levels is not None and column.max_rep > 0:
        num_rows = int((np.asarray(rep_levels) == 0).sum())
    header = PageHeader(
        type=3,
        uncompressed_page_size=len(rep_block) + len(def_block) + len(values_raw),
        compressed_page_size=len(block),
        data_page_header_v2=DataPageHeaderV2(
            num_values=n,
            num_nulls=num_nulls,
            num_rows=num_rows,
            encoding=int(encoding),
            definition_levels_byte_length=len(def_block),
            repetition_levels_byte_length=len(rep_block),
            is_compressed=True,
        ),
    )
    if with_crc:
        header.crc = _crc32_signed(block)
    return header, block


def encode_dict_page(
    column: Column, dict_values, codec: int, with_crc: bool = False
) -> tuple[PageHeader, bytes]:
    raw = plain_ops.encode_plain(dict_values, column.type, column.type_length)
    block = compress_block(raw, codec)
    n = len(dict_values)
    header = PageHeader(
        type=2,
        uncompressed_page_size=len(raw),
        compressed_page_size=len(block),
        dictionary_page_header=DictionaryPageHeader(
            num_values=n, encoding=int(Encoding.PLAIN), is_sorted=False
        ),
    )
    if with_crc:
        header.crc = _crc32_signed(block)
    return header, block


def _count_level_entries(values, def_levels) -> int:
    if def_levels is not None:
        return len(def_levels)
    if isinstance(values, ByteArrayData):
        return len(values)
    return len(values)


def _encode_values(values, encoding: Encoding, column: Column, dict_size) -> bytes:
    ptype = column.type
    e = int(encoding)
    if e in _DICT_ENCODINGS:
        # `values` are indices here; dictionary page is written separately.
        return encode_dict_indices(values, dict_size)
    if e == int(Encoding.PLAIN):
        return plain_ops.encode_plain(values, ptype, column.type_length)
    if e == int(Encoding.DELTA_BINARY_PACKED):
        nbits = 32 if ptype == Type.INT32 else 64
        return delta_ops.encode_delta(np.asarray(values), nbits)
    if e == int(Encoding.DELTA_LENGTH_BYTE_ARRAY):
        return ba_ops.encode_delta_length_byte_array(values)
    if e == int(Encoding.DELTA_BYTE_ARRAY):
        return ba_ops.encode_delta_byte_array(values)
    if e == int(Encoding.RLE) and ptype == Type.BOOLEAN:
        return encode_levels_v1(np.asarray(values).astype(np.uint16), 1)
    if e == int(Encoding.BYTE_STREAM_SPLIT):
        from ..ops.byte_stream_split import encode_byte_stream_split

        try:
            return encode_byte_stream_split(values, ptype, column.type_length)
        except ValueError as err:
            raise PageError(f"page: {err}") from err
    raise PageError(f"page: unsupported write encoding {encoding} for {ptype}")


def _crc32_signed(block: bytes) -> int:
    """CRC-32 over the stored block, as a signed i32 for the Thrift field."""
    v = zlib.crc32(block) & 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v

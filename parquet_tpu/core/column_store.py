"""Write-side column stores: typed buffering, dictionary building, page split.

Equivalent of the reference's ColumnStore + dictStore + typed stores
(reference: data_store.go:15-53,96-136; type_dict.go:62-133; typed stores in
type_*.go) redesigned array-first: values accumulate as Python/NumPy values and
are converted to typed arrays once per chunk; the dictionary decision is made
vectorized over the whole chunk (np.unique on bit patterns) instead of
per-value hash updates.

Defaults carried from the reference: 1 MiB max page size (data_store.go:149-154),
dictionary cutoff 32767 uniques (chunk_writer.go:188-200, type_dict.go:101-103).
"""

from __future__ import annotations

import datetime as _dt

import numpy as np

from ..meta.parquet_types import Type
from .arrays import ByteArrayData, byte_array_from_items, _ext
from .schema import Column

__all__ = [
    "ColumnChunkBuilder",
    "StoreError",
    "MAX_PAGE_SIZE_DEFAULT",
    "DICT_MAX_UNIQUES",
    "PROBE_NA",
]

MAX_PAGE_SIZE_DEFAULT = 1 << 20  # 1 MiB, reference data_store.go:149-154
DICT_MAX_UNIQUES = (1 << 15) - 1  # 32767, reference chunk_writer.go:188-200

# fast_dictionary's "probe not applicable" sentinel (distinct from None,
# which is the definitive "dictionary encoding does not pay" verdict)
PROBE_NA = object()


class StoreError(ValueError):
    pass


_NUMERIC = {
    Type.INT32: np.int32,
    Type.INT64: np.int64,
    Type.FLOAT: np.float32,
    Type.DOUBLE: np.float64,
}

_TYPE_WIDTHS = {
    Type.INT32: 4, Type.INT64: 8, Type.FLOAT: 4, Type.DOUBLE: 8,
    Type.BOOLEAN: 1, Type.INT96: 12,
}


class ColumnChunkBuilder:
    """Buffers one column's values + levels for the current row group."""

    def __init__(self, column: Column, enable_dict: bool = True):
        self.column = column
        self.enable_dict = enable_dict
        self.values: list = []
        self.def_levels: list[int] = []
        self.rep_levels: list[int] = []
        self._columnar_values = None  # fast-path ndarray/ByteArrayData

    def __len__(self) -> int:
        return len(self.def_levels) if len(self.def_levels) else self._n_values()

    def _n_values(self) -> int:
        if self._columnar_values is not None:
            return len(self._columnar_values)
        return len(self.values)

    def data_size(self) -> int:
        """Rough UNCOMPRESSED byte size of the buffered values + levels
        (reference: data_store.go DataSize via file_writer.go:355
        CurrentRowGroupSize) — the signal callers use for size-based
        row-group flushing; encoding/compression usually shrink it."""
        n = len(self)
        size = n * 2 * (
            (self.column.max_def > 0) + (self.column.max_rep > 0)
        )
        v = self._columnar_values
        if v is not None:
            if isinstance(v, ByteArrayData):
                return size + len(v.data) + 4 * len(v)
            nb = getattr(v, "nbytes", None)
            if nb is not None:
                return size + int(nb)
            v = list(v)
        else:
            v = self.values
        if not v:
            return size
        first = v[0]
        if isinstance(first, (bytes, str)):
            return size + sum(len(x) for x in v) + 4 * len(v)
        width = self.column.type_length or _TYPE_WIDTHS.get(self.column.type, 8)
        return size + len(v) * width

    # -- ingestion -------------------------------------------------------------

    def extend_shredded(self, values: list, def_levels: list, rep_levels: list) -> None:
        """Row-path input from the Shredder (values include None placeholders)."""
        self.values.extend(v for v in values if v is not None)
        self.def_levels.extend(def_levels)
        self.rep_levels.extend(rep_levels)

    def set_columnar(self, values, def_levels=None, rep_levels=None) -> None:
        """Columnar fast path: typed array (+ optional levels) for the chunk."""
        if self.values or len(self.def_levels) or self._columnar_values is not None:
            raise StoreError(
                "store: column already holds data for this row group"
            )
        self._columnar_values = values
        # keep level arrays as ndarrays: a list() round-trip boxes 1 value
        # per cell and every consumer re-asarrays anyway
        self.def_levels = (
            np.asarray(def_levels, dtype=np.uint16) if def_levels is not None else []
        )
        self.rep_levels = (
            np.asarray(rep_levels, dtype=np.uint16) if rep_levels is not None else []
        )

    # -- typed conversion ------------------------------------------------------

    def typed_values(self):
        """Non-null cells as a typed array / ByteArrayData."""
        if self._columnar_values is not None:
            return self._coerce_array(self._columnar_values)
        ptype = self.column.type
        if ptype in _NUMERIC:
            # same exact-roundtrip validation as the columnar path: a float
            # 1.5 must not silently truncate into an int64 column
            return self._coerce_array(self.values)
        if ptype == Type.BOOLEAN:
            return np.asarray(self.values, dtype=bool)
        vals = self.values
        if vals and not isinstance(vals[0], (bytes, str)):
            # row-domain objects (e.g. Decimal into FLBA/BYTE_ARRAY
            # storage) convert by the leaf's logical annotation; datetimes
            # for INT96 pass through untouched (handled below)
            from .assembly import convert_to_storage, logical_kind

            k = logical_kind(self.column)
            if k is not None and k != "int96":
                try:
                    vals = [convert_to_storage(self.column, x, k) for x in vals]
                except ValueError as e:
                    raise StoreError(
                        f"store: {self.column.path_str}: {e}"
                    ) from e
        if ptype == Type.BYTE_ARRAY:
            return byte_array_from_items(vals, to_bytes=self._to_bytes)
        if ptype in (Type.INT96, Type.FIXED_LEN_BYTE_ARRAY):
            width = 12 if ptype == Type.INT96 else (self.column.type_length or 0)
            if width <= 0:
                raise StoreError(
                    f"store: fixed column {self.column.path_str} lacks type_length"
                )
            rows = []
            for v in vals:
                if ptype == Type.INT96 and isinstance(v, _dt.datetime):
                    # datetime into an INT96 column converts like the
                    # reference's floor writer (writer.go INT96 heuristics)
                    from ..utils.int96 import datetime_to_int96

                    v = datetime_to_int96(v)
                b = self._to_bytes(v)
                if len(b) != width:
                    raise StoreError(
                        f"store: fixed({width}) column {self.column.path_str} "
                        f"got {len(b)}-byte value"
                    )
                rows.append(np.frombuffer(b, dtype=np.uint8))
            if not rows:
                return np.empty((0, width), dtype=np.uint8)
            return np.stack(rows)
        raise StoreError(f"store: unsupported type {ptype}")

    def _from_arrow(self, v):
        """pyarrow Array/ChunkedArray -> our columnar containers, zero-copy
        where the layouts agree (numeric buffers, string offsets). The
        bench-visible case: handing write_column a pa.array skips the
        per-item Python string encode entirely."""
        import pyarrow as pa

        if isinstance(v, pa.ChunkedArray):
            v = v.combine_chunks()
        if not isinstance(v, pa.Array):
            raise StoreError(
                f"store: unsupported pyarrow input {type(v).__name__} for "
                f"{self.column.path_str}"
            )
        t = v.type
        if isinstance(t, pa.BaseExtensionType):  # arrow.uuid / arrow.json etc.
            v = v.storage
            t = v.type
        if pa.types.is_dictionary(t):
            v = v.dictionary_decode()
            t = v.type
        # null check AFTER dictionary decode: a dictionary array carrying
        # nulls in its VALUE buffer reports null_count 0 on the indices
        if v.null_count:
            raise StoreError(
                f"store: pyarrow array for {self.column.path_str} contains "
                "nulls; write_column takes non-null cells (pass def_levels "
                "explicitly, or drop/fill nulls upstream)"
            )
        if (
            pa.types.is_string(t)
            or pa.types.is_binary(t)
            or pa.types.is_large_string(t)
            or pa.types.is_large_binary(t)
        ):
            wide = pa.types.is_large_string(t) or pa.types.is_large_binary(t)
            dt = np.int64 if wide else np.int32
            off = np.frombuffer(
                v.buffers()[1],
                dtype=dt,
                count=len(v) + 1,
                offset=v.offset * np.dtype(dt).itemsize,
            )
            base = int(off[0]) if len(off) else 0
            end = int(off[-1]) if len(off) else 0
            # data stays `bytes` (ByteArrayData contract: slices hash)
            data = bytes(memoryview(v.buffers()[2] or b"")[base:end])
            offsets = off.astype(np.int64)
            if base:
                offsets = offsets - base
            return ByteArrayData(offsets=offsets, data=data)
        if pa.types.is_fixed_size_binary(t):
            width = t.byte_width
            flat = np.frombuffer(
                v.buffers()[1],
                dtype=np.uint8,
                count=len(v) * width,
                offset=v.offset * width,
            )
            return flat.reshape(len(v), width)
        if pa.types.is_boolean(t):
            return np.asarray(v)  # bit-packed in arrow: unpack copy
        if (
            pa.types.is_timestamp(t)
            or pa.types.is_time64(t)
            or pa.types.is_duration(t)
            or pa.types.is_time32(t)
            or pa.types.is_date32(t)
            or pa.types.is_date64(t)
        ):
            # temporal values pass through as their integer representation;
            # the schema annotation (TIMESTAMP(unit) etc.) defines meaning
            width = t.bit_width // 8
            dt = np.int64 if width == 8 else np.int32
            return np.frombuffer(
                v.buffers()[1], dtype=dt, count=len(v), offset=v.offset * width
            )
        if pa.types.is_decimal(t) and t.bit_width == 128:
            # unscaled 128-bit LE two's complement -> the column's physical
            # storage (the reverse of to_arrow's decimal128 widening). The
            # array's scale must MATCH the column's declared scale (raw
            # unscaled ints would silently rescale every value otherwise),
            # and every value must FIT the narrower storage — same exact
            # round-trip discipline as the numeric path below.
            decl_scale = self.column.element.scale
            lt = self.column.logical_type
            if lt is not None and lt.DECIMAL is not None:
                decl_scale = lt.DECIMAL.scale
            if decl_scale is not None and t.scale != decl_scale:
                raise StoreError(
                    f"store: decimal scale mismatch for "
                    f"{self.column.path_str}: array has scale {t.scale}, "
                    f"column declares {decl_scale}"
                )
            n = len(v)
            m = np.frombuffer(
                v.buffers()[1], dtype=np.uint8, count=n * 16, offset=v.offset * 16
            ).reshape(n, 16)
            ptype = self.column.type
            if ptype in (Type.INT32, Type.INT64):
                lohi = np.ascontiguousarray(m).view("<i8").reshape(n, 2)
                lo = lohi[:, 0]
                if not bool((lohi[:, 1] == (lo >> 63)).all()):
                    raise StoreError(
                        f"store: decimal value does not fit 64-bit storage "
                        f"of {self.column.path_str}"
                    )
                if ptype == Type.INT64:
                    return lo.copy()
                lo32 = lo.astype(np.int32)
                if not bool((lo32 == lo).all()):
                    raise StoreError(
                        f"store: decimal value does not fit INT32 storage "
                        f"of {self.column.path_str}"
                    )
                return lo32
            if ptype == Type.FIXED_LEN_BYTE_ARRAY:
                w = self.column.type_length or 0
                if 1 <= w <= 16:
                    if w < 16:
                        # dropped high bytes must be pure sign extension
                        sign = np.where(m[:, w - 1] >= 0x80, 0xFF, 0).astype(
                            np.uint8
                        )
                        if not bool(
                            (m[:, w:] == sign[:, None]).all()
                        ):
                            raise StoreError(
                                f"store: decimal value does not fit "
                                f"{w}-byte storage of {self.column.path_str}"
                            )
                    return np.ascontiguousarray(m[:, :w][:, ::-1])  # LE -> BE
                if w > 16:
                    out = np.zeros((n, w), dtype=np.uint8)
                    out[:, w - 16 :] = m[:, ::-1]
                    out[m[:, 15] >= 0x80, : w - 16] = 0xFF  # sign fill
                    return out
        if t == pa.float16():
            n = len(v)
            return np.frombuffer(
                v.buffers()[1], dtype=np.uint8, count=n * 2, offset=v.offset * 2
            ).reshape(n, 2)
        try:
            return v.to_numpy(zero_copy_only=True)
        except Exception as e:
            raise StoreError(
                f"store: cannot ingest pyarrow {t} array for "
                f"{self.column.path_str}: {e}"
            ) from e

    def _coerce_array(self, v):
        ptype = self.column.type
        if isinstance(v, list) and v:
            # row-domain objects (datetime/date/time/Decimal — what
            # iter_rows RETURNS) convert to storage by the leaf's logical
            # annotation; raw storage lists skip on the first-element
            # check. UINT columns also wrap plain ints >= 2^(bits-1) into
            # their signed storage bit pattern.
            first = v[0]
            needs = not isinstance(first, (int, float, str, bytes))
            if (
                not needs
                and isinstance(first, int)
                and ptype in (Type.INT32, Type.INT64)
            ):
                from .assembly import logical_kind

                k = logical_kind(self.column)
                needs = k is not None and k[0] == "uint"
            if needs:
                from .assembly import convert_to_storage, logical_kind

                k = logical_kind(self.column)
                if k is not None:
                    try:
                        v = [
                            convert_to_storage(self.column, x, k) for x in v
                        ]
                    except ValueError as e:
                        raise StoreError(
                            f"store: {self.column.path_str}: {e}"
                        ) from e
        if type(v).__module__.split(".", 1)[0] == "pyarrow":
            v = self._from_arrow(v)
            if isinstance(v, ByteArrayData):
                if ptype != Type.BYTE_ARRAY:
                    raise StoreError(
                        f"store: string/binary arrow array into non-BYTE_ARRAY "
                        f"column {self.column.path_str}"
                    )
                return v
        if ptype in _NUMERIC:
            try:
                arr = np.asarray(v)
            except (ValueError, OverflowError, TypeError) as e:
                raise StoreError(
                    f"store: bad value for {ptype.name} column "
                    f"{self.column.path_str}: {e}"
                ) from e
            if arr.ndim != 1 or arr.dtype.kind not in "iufb":
                raise StoreError(
                    f"store: {ptype.name} column {self.column.path_str} takes "
                    f"a flat numeric array, got ndim={arr.ndim} dtype={arr.dtype}"
                )
            want = _NUMERIC[ptype]
            if arr.dtype != want:
                with np.errstate(invalid="ignore"):
                    try:
                        cast = arr.astype(want)
                    except (ValueError, OverflowError, TypeError) as e:
                        raise StoreError(
                            f"store: bad value for {ptype.name} column "
                            f"{self.column.path_str}: {e}"
                        ) from e
                # Any implicit cast must round-trip exactly (catches integer
                # overflow, fractional floats into int columns, NaN into ints,
                # and lossy f64 -> f32).
                both_float = arr.dtype.kind == "f" and np.dtype(want).kind == "f"
                if not np.array_equal(cast.astype(arr.dtype), arr, equal_nan=both_float):
                    raise StoreError(
                        f"store: values do not fit {ptype.name} exactly in "
                        f"{self.column.path_str} (dtype {arr.dtype})"
                    )
                arr = cast
            return arr
        if ptype == Type.BOOLEAN:
            return np.asarray(v, dtype=bool)
        if ptype == Type.BYTE_ARRAY:
            if isinstance(v, ByteArrayData):
                # shallow wrapper sharing offsets/data: the write path's
                # to_list(cache=True) memo then lives on the writer's copy,
                # never pinning a caller-owned array
                return ByteArrayData(offsets=v.offsets, data=v.data)
            return byte_array_from_items(v, to_bytes=self._to_bytes)
        if isinstance(v, (list, tuple)) and (not v or isinstance(v[0], bytes)):
            width = 12 if ptype == Type.INT96 else (self.column.type_length or 0)
            if width <= 0 or any(
                not isinstance(x, bytes) or len(x) != width for x in v
            ):
                raise StoreError(
                    f"store: fixed({width}) column {self.column.path_str} "
                    f"takes {width}-byte values"
                )
            return np.frombuffer(b"".join(v), dtype=np.uint8).reshape(len(v), width)
        arr = np.asarray(v, dtype=np.uint8)
        if arr.ndim != 2:
            raise StoreError("store: fixed-width columnar input must be (n, width)")
        return arr

    @staticmethod
    def _to_bytes(v) -> bytes:
        if isinstance(v, bytes):
            return v
        if isinstance(v, str):
            return v.encode("utf-8")
        if isinstance(v, (bytearray, memoryview, np.ndarray)):
            return bytes(v)
        raise StoreError(f"store: cannot convert {type(v).__name__} to bytes")

    # -- dictionary decision (whole-chunk, reference: chunk_writer.go:174-209) --

    def fast_dictionary(self):
        """OBJECT-domain dictionary probe for string columns: dedup the
        Python str values BEFORE any UTF-8 materialization, so a
        dictionary-encoded chunk only ever byte-encodes its (few) uniques —
        the whole-column string conversion was the serial write path's
        single biggest cost. Byte-identical to probing the encoded bytes
        (str -> UTF-8 is injective, so uniques, first-occurrence order and
        the dict-vs-plain size cutoff all coincide); the probe refuses
        mixed-type input, where object equality and byte equality diverge.

        Returns (dict_values, indices) when dictionary encoding pays, None
        when it provably does not (the caller must NOT re-probe), or the
        PROBE_NA sentinel when the probe does not apply (non-list input,
        non-BYTE_ARRAY column, extension absent — take build_dictionary)."""
        if not self.enable_dict or self.column.type != Type.BYTE_ARRAY:
            return PROBE_NA
        raw = self._columnar_values if self._columnar_values is not None else self.values
        if not isinstance(raw, list) or not raw:
            return PROBE_NA
        if _ext is None or not hasattr(_ext, "dict_indices_str"):
            return PROBE_NA
        res = _ext.dict_indices_str(raw, DICT_MAX_UNIQUES)
        if res is False:
            return PROBE_NA  # non-str item seen: byte-domain path decides
        if res is None:
            return None  # uniques exceed the cutoff: dict never pays
        uniques, idx_b, total_utf8, uniq_utf8 = res
        n = len(raw)
        n_uniques = len(uniques)
        # the exact size cutoff of the ByteArrayData branch below, computed
        # from the probe's cached UTF-8 lengths
        plain_size = total_utf8 + 4 * n
        dict_size = uniq_utf8 + 4 * n_uniques + n * 4
        if dict_size >= plain_size:
            return None
        dict_values = ByteArrayData.from_list(
            [u.encode("utf-8") for u in uniques]
        )
        return dict_values, np.frombuffer(idx_b, dtype="<u4")

    def build_dictionary(self, typed):
        """Return (dict_values, indices) or None if dict encoding doesn't pay."""
        if not self.enable_dict:
            return None
        ptype = self.column.type
        n = len(typed)
        if n == 0:
            return None
        if isinstance(typed, ByteArrayData):
            from ..utils.native import get_native

            lib = get_native()
            if lib is not None and lib.has_bytes_dict:
                # C hash probe straight over (offsets, data) — no Python
                # object per value (to_list was the dictionary build's
                # dominant cost)
                res = lib.bytes_dict_indices(
                    typed.data, typed.offsets, DICT_MAX_UNIQUES
                )
                if res is None:
                    return None  # more uniques than the cutoff: dict never pays
                firsts, indices = res
                dict_values = typed.take(firsts.astype(np.int64))
                n_uniques = len(firsts)
            else:
                if _ext is not None:
                    res = _ext.dict_indices(typed.to_list(cache=True), DICT_MAX_UNIQUES)
                    if res is None:
                        return None  # more uniques than the cutoff
                    uniques, idx_b = res
                    indices = np.frombuffer(idx_b, dtype="<u4")
                else:
                    # one bulk slice pass (to_list) beats re-slicing per value,
                    # and the dict probe loop beats np.unique on object arrays
                    # (measured ~4x): hashing short bytes is cheaper than C
                    # comparisons in a mergesort
                    uniq: dict[bytes, int] = {}
                    indices = np.empty(n, dtype=np.uint32)
                    uniq_get = uniq.get
                    for i, key in enumerate(typed.to_list(cache=True)):
                        idx = uniq_get(key)
                        if idx is None:
                            idx = len(uniq)
                            if idx >= DICT_MAX_UNIQUES:
                                return None
                            uniq[key] = idx
                        indices[i] = idx
                    uniques = list(uniq.keys())
                dict_values = ByteArrayData.from_list(uniques)
                n_uniques = len(uniques)
            plain_size = len(typed.data) + 4 * n
            dict_size = len(dict_values.data) + 4 * n_uniques + n * 4
        elif isinstance(typed, np.ndarray) and typed.ndim == 1 and ptype != Type.BOOLEAN:
            # Bit-pattern uniqueness so NaN payloads dedup correctly
            # (reference CHANGELOG.md:31 NaN-in-dict fix).
            bits = typed.view(np.uint32 if typed.itemsize == 4 else np.uint64)
            from ..utils.native import get_native

            lib = get_native()
            if lib is not None and lib.has_u64_dict:
                # C hash probe with early exit past the cutoff — np.unique
                # sorts the whole column before the cutoff check can fire,
                # the worst cost exactly when dictionary encoding won't pay
                res = lib.u64_dict_indices(bits, DICT_MAX_UNIQUES)
                if res is None:
                    return None
                firsts, indices = res
                dict_values = typed[firsts.astype(np.int64)]
                uniq_count = len(firsts)
            else:
                uniq_bits, inverse = np.unique(bits, return_inverse=True)
                if len(uniq_bits) > DICT_MAX_UNIQUES:
                    return None
                dict_values = uniq_bits.view(typed.dtype)
                indices = inverse.astype(np.uint32)
                uniq_count = len(uniq_bits)
            width = max(int(uniq_count - 1).bit_length(), 1)
            plain_size = typed.nbytes
            dict_size = dict_values.nbytes + (n * width) // 8
        else:
            return None  # boolean / fixed-width: dict rarely pays
        if dict_size >= plain_size:
            return None
        return dict_values, indices

"""Aggregation push-down: per-unit partials, exact merge, canonical body.

The serve bench measured the daemon serialization-bound: a dashboard-style
"how many rows match, grouped by X" question paid for boxing and shipping
every matching row. This module answers it server-side: each unit (one row
group of one file) computes a PARTIAL aggregate over its filtered arrow
table on the pqt-serve pool, partials merge with exact semantics, and the
response is kilobytes regardless of how many rows matched.

Semantics are PINNED AGAINST PYARROW by construction, not by reimplementation:
unit partials are pyarrow.compute kernels (count/sum/min/max and
TableGroupBy for group-by), and merging two partial values runs the same
kernel over a two-element array OF THE PARTIAL'S ARROW TYPE — so null
skipping (sum/min/max ignore nulls, all-null yields null), NaN propagation
(sum) vs NaN skipping (min/max), decimal precision, and int64 wraparound
all come out identical to a single whole-corpus pyarrow aggregation
(differential tests assert exactly that).

Group-by cardinality is BOUNDED: the merged table growing past the
request's max_groups raises the typed overflow ServeError (413
group_overflow) instead of buffering an unbounded result — push-down must
not become a memory vector.

The canonical JSON rendering lives here too (render_query_body): the
daemon's POST /v1/query response and `parquet-tool scan --aggregate`
output are the SAME bytes for the same corpus and spec, like the
jsonl-scan contract protocol.py pins for rows.
"""

from __future__ import annotations

import json

from .protocol import QueryRequest, ServeError, agg_name, json_default

__all__ = [
    "QueryState",
    "query_columns",
    "unit_partial",
    "unit_count_partial",
    "result_dict",
    "render_query_body",
    "run_local_query",
]


def query_columns(query: QueryRequest) -> list:
    """The column projection a query's units must decode: group-by keys
    plus aggregate inputs, order-stable. Empty + no filters means NO decode
    at all (pure count(*) answers from footer-promised row counts); empty
    WITH filters borrows the first filter column so the filtered row count
    is still observable."""
    cols: list = []
    for c in query.group_by:
        if c not in cols:
            cols.append(c)
    for a in query.aggregates:
        if a.column is not None and a.column not in cols:
            cols.append(a.column)
    if not cols and query.filters is not None:
        first = query.filters[0]
        if isinstance(first, (list, tuple)) and first and isinstance(
            first[0], (list, tuple)
        ):
            first = first[0]  # DNF: first conjunction's first triple
        cols.append(first[0])
    return cols


def _agg_column(table, name: str):
    import pyarrow.compute as pc

    parts = name.split(".")
    try:
        col = table.column(parts[0])
    except KeyError:
        raise ServeError(
            400, "bad_aggregates", f"aggregate column {name!r} not in scan"
        ) from None
    for p in parts[1:]:
        col = pc.struct_field(col, p)
    return col


def unit_partial(table, query: QueryRequest):
    """(groups, types) partial of one unit's filtered arrow table:
    groups maps key tuple -> [one python value per aggregate] (the global
    form uses the () key); types carries each aggregate's arrow type so
    merges run in the exact same domain."""
    import pyarrow as pa
    import pyarrow.compute as pc

    aggs = query.aggregates
    if not query.group_by:
        vals: list = []
        types: list = [None] * len(aggs)
        for j, a in enumerate(aggs):
            if a.column is None:
                vals.append(table.num_rows)
                continue
            col = _agg_column(table, a.column)
            try:
                if a.op == "count":
                    vals.append(int(pc.count(col).as_py()))
                    continue
                s = {"sum": pc.sum, "min": pc.min, "max": pc.max}[a.op](col)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError) as e:
                raise ServeError(
                    400, "bad_aggregates",
                    f"cannot {a.op} column {a.column!r}: {e}",
                ) from None
            vals.append(s.as_py())
            types[j] = s.type
        return {(): vals}, types
    keys = list(query.group_by)
    spec = []
    for a in aggs:
        if a.column is None:
            spec.append(([], "count_all"))
        elif a.op == "count":
            spec.append((a.column, "count"))
        else:
            spec.append((a.column, a.op))
    try:
        res = table.group_by(keys).aggregate(spec)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, KeyError) as e:
        raise ServeError(
            400, "bad_aggregates", f"cannot group by {keys}: {e}"
        ) from None
    if res.num_columns != len(keys) + len(aggs):
        raise ServeError(
            500, "internal", "group-by result shape mismatch"
        )
    # pyarrow's aggregate table leads with the key columns, then the
    # aggregates in spec order — read positionally (names can collide)
    kl = [res.column(i).to_pylist() for i in range(len(keys))]
    al = [res.column(len(keys) + j).to_pylist() for j in range(len(aggs))]
    types = [
        None
        if a.op == "count"
        else res.column(len(keys) + j).type
        for j, a in enumerate(aggs)
    ]
    groups = {}
    for g in range(res.num_rows):
        key = tuple(k[g] for k in kl)
        groups[key] = [a[g] for a in al]
    return groups, types


def unit_count_partial(query: QueryRequest, num_rows: int):
    """The zero-decode partial: every aggregate is count(*) (query_columns
    returned empty with no filters), so the footer-promised row count IS
    the answer and the unit never opens its file."""
    return {(): [num_rows for _ in query.aggregates]}, [None] * len(
        query.aggregates
    )


def _merge_value(op: str, a, b, typ):
    if op == "count":
        return int(a) + int(b)
    if a is None:
        return b
    if b is None:
        return a
    import pyarrow as pa
    import pyarrow.compute as pc

    arr = pa.array([a, b], type=typ)
    if op == "sum":
        return pc.sum(arr).as_py()
    if op == "min":
        return pc.min(arr).as_py()
    return pc.max(arr).as_py()


class QueryState:
    """The merged aggregate state one request accumulates unit by unit."""

    __slots__ = ("query", "groups", "types", "rows_scanned", "rows_matched")

    def __init__(self, query: QueryRequest):
        self.query = query
        self.types: list = [None] * len(query.aggregates)
        self.rows_scanned = 0
        self.rows_matched = 0
        if query.group_by:
            self.groups: dict = {}
        else:
            # the global row exists even over zero units: count 0, sum/min/
            # max null — matching pyarrow kernels over an empty column
            self.groups = {
                (): [0 if a.column is None or a.op == "count" else None
                     for a in query.aggregates]
            }

    def absorb(self, part) -> None:
        """Merge one unit's ((groups, types), scanned, matched) partial."""
        (groups, types), scanned, matched = part
        self.rows_scanned += scanned
        self.rows_matched += matched
        for j, t in enumerate(types):
            if self.types[j] is None:
                self.types[j] = t
        q = self.query
        for key, vals in groups.items():
            cur = self.groups.get(key)
            if cur is None:
                if len(self.groups) >= q.max_groups:
                    raise ServeError(
                        413, "group_overflow",
                        f"group-by cardinality exceeded max_groups="
                        f"{q.max_groups}; narrow the filter or raise "
                        "max_groups",
                    )
                self.groups[key] = list(vals)
                continue
            for j, a in enumerate(q.aggregates):
                op = "count" if a.column is None else a.op
                cur[j] = _merge_value(op, cur[j], vals[j], self.types[j])


def _key_order(key: tuple) -> str:
    # deterministic total order over arbitrary (possibly None/mixed) keys:
    # their canonical JSON encoding — the same bytes the body renders
    return json.dumps(list(key), default=json_default)


def result_dict(query: QueryRequest, state: QueryState, *, units: int) -> dict:
    """The response body, deterministically ordered (groups sort by their
    canonical key encoding) so daemon bytes == CLI bytes."""
    names = [agg_name(a) for a in query.aggregates]
    body: dict = {
        "group_by": list(query.group_by),
        "aggregates": names,
        "units": units,
        "rows_scanned": state.rows_scanned,
        "rows_matched": state.rows_matched,
    }
    if query.group_by:
        body["group_count"] = len(state.groups)
        body["groups"] = [
            {
                "key": list(key),
                "aggregates": dict(zip(names, state.groups[key])),
            }
            for key in sorted(state.groups, key=_key_order)
        ]
    else:
        body["result"] = dict(zip(names, state.groups[()]))
    return body


def render_query_body(body: dict) -> bytes:
    """ONE canonical serialization (shared with `parquet-tool scan
    --aggregate`), so a daemon response is byte-identical to the CLI's."""
    return (json.dumps(body, default=json_default) + "\n").encode()


def run_local_query(paths, query: QueryRequest, *, footer_cache=None) -> dict:
    """The daemon-free twin of POST /v1/query: plan, execute every unit
    sequentially, merge — `parquet-tool scan --aggregate` and the parity
    tests run the daemon's exact semantics against local files."""
    from ..core.reader import FileReader
    from ..data.plan import build_plan, expand_paths

    files: list = []
    for p in paths:
        files.extend(expand_paths(p))
    files = sorted(set(files))
    plan = build_plan(files, filters=query.filters, footer_cache=footer_cache)
    if query.shard is not None:
        order = plan.epoch_order(
            0, shard_index=query.shard[0], shard_count=query.shard[1]
        )
        units = [plan.units[k] for k in order]
    else:
        units = list(plan.units)
    cols = query_columns(query)
    decode = bool(cols) or query.filters is not None
    state = QueryState(query)
    for u in units:
        if not decode:
            state.absorb(
                (unit_count_partial(query, u.num_rows), u.num_rows, u.num_rows)
            )
            continue
        meta = plan.metas[u.file_index]
        with FileReader(u.path, columns=cols or None, metadata=meta) as r:
            t = r.to_arrow(row_groups=[u.row_group], filters=query.filters)
        state.absorb((unit_partial(t, query), u.num_rows, t.num_rows))
    return result_dict(query, state, units=len(units))

"""Admission control: who gets to scan, for how long, at what volume.

A serving daemon dies three ways: unbounded queueing (every request
admitted, none finishing), one tenant starving the rest, and requests
that never end. This module bounds all three BEFORE the executor spends
a thread on them:

  * global in-flight cap (`max_inflight`) — request N+1 gets a typed 429
    `queue_full` body, never an unbounded queue;
  * per-tenant concurrency + a scanned-byte token bucket keyed on the
    `X-Tenant` header — budgets refill continuously at
    `tenant_budget_bytes / budget_window_s`, charged with the PLAN
    estimate so an over-budget scan is refused before reading data
    (429 `tenant_over_budget`, with a Retry-After hint);
  * per-request deadline (default/max configurable, `X-Timeout-Ms` or
    body `timeout_ms` override) — cooperative cancellation points in the
    executor check it between units and every few thousand rows, so an
    expired request frees its slot instead of scanning to the end;
  * brownout — when the pqt-serve pool's windowed mean queue wait crosses
    `brownout_wait_s` (or its queue depth crosses `brownout_depth`), NEW
    requests shed with a typed 503 `brownout` + Retry-After BEFORE they
    join the pile-up. Shedding early is the point: without it every
    admitted request queues until the deadline sweeps them all into 504s
    at once — clients get no signal to back off until the worst moment.
    Sheds count serve_shed_total{reason="queue_wait"};
  * graceful drain — `begin_drain()` (the SIGTERM path) rejects NEW
    requests with a typed 503 `draining` while in-flight ones run to
    completion; `wait_drained()` tells the server when the last one left.

Everything here is clock-injectable (tests pin time) and updates the
always-on registry: `serve_queue_depth` gauge tracks in-flight requests;
brownout reads the PR 9 pool_queue_wait_seconds{pool="pqt-serve"}
histogram back OUT of the registry (windowed deltas) as its pressure
signal.
"""

from __future__ import annotations

import threading
import time

from ..obs.log import log_event as _log_event
from ..utils import metrics as _metrics
from .protocol import ServeError


def _rejected(code: str, tenant: str) -> None:
    # the structured-log mirror of every typed admission rejection. The
    # code rides the EVENT KEY so the limiter buckets per code: a
    # queue_full flood can't absorb the one draining line at SIGTERM time
    # (codes are code-controlled, so the key set stays bounded)
    _log_event(
        f"admission_rejected:{code}", level="warning",
        code=code, tenant=tenant,
    )

__all__ = ["AdmissionController", "Deadline", "Ticket"]


class Deadline:
    """A cooperative per-request deadline. `check()` raises the typed 504
    at every cancellation point; `remaining()` bounds blocking waits so a
    stuck unit can never hold a request past its budget."""

    __slots__ = ("_expires", "_clock")

    def __init__(self, timeout_s: float | None, clock=time.monotonic):
        self._clock = clock
        self._expires = None if timeout_s is None else clock() + float(timeout_s)

    def remaining(self) -> float | None:
        if self._expires is None:
            return None
        return self._expires - self._clock()

    def expired(self) -> bool:
        return self._expires is not None and self._clock() >= self._expires

    def check(self) -> None:
        if self.expired():
            raise ServeError(
                504, "deadline_exceeded",
                "request deadline exceeded (raise timeout_ms / X-Timeout-Ms)",
            )


class _TenantState:
    __slots__ = ("concurrent", "tokens", "last_refill")

    def __init__(self, tokens: float, now: float):
        self.concurrent = 0
        self.tokens = tokens
        self.last_refill = now


class Ticket:
    """One admitted request's slot; a context manager so the slot releases
    on EVERY exit path (stream done, stream aborted, handler error)."""

    def __init__(self, controller: "AdmissionController", tenant: str):
        self._controller = controller
        self.tenant = tenant
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._controller._release(self.tenant)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class AdmissionController:
    # tenants past this many distinct X-Tenant values share one overflow
    # bucket: the header is CLIENT-controlled, so per-tenant state (and the
    # serve_requests_total{tenant=} label set) must stay bounded or random
    # header values become a remote memory-growth vector
    OVERFLOW_TENANT = "__overflow__"

    def __init__(
        self,
        *,
        max_inflight: int = 32,
        tenant_concurrent: int = 8,
        tenant_budget_bytes: int | None = None,
        budget_window_s: float = 60.0,
        default_timeout_s: float | None = 30.0,
        max_timeout_s: float = 300.0,
        max_tenants: int = 1024,
        brownout_wait_s: float | None = None,
        brownout_depth: int | None = None,
        brownout_window_s: float = 2.0,
        clock=time.monotonic,
        registry=None,
    ):
        if max_inflight <= 0:
            raise ValueError("admission: max_inflight must be positive")
        if tenant_concurrent <= 0:
            raise ValueError("admission: tenant_concurrent must be positive")
        if budget_window_s <= 0:
            raise ValueError("admission: budget_window_s must be positive")
        if max_tenants <= 0:
            raise ValueError("admission: max_tenants must be positive")
        if brownout_wait_s is not None and brownout_wait_s <= 0:
            raise ValueError("admission: brownout_wait_s must be positive")
        if brownout_depth is not None and brownout_depth <= 0:
            raise ValueError("admission: brownout_depth must be positive")
        if brownout_window_s <= 0:
            raise ValueError("admission: brownout_window_s must be positive")
        self.max_inflight = int(max_inflight)
        self.tenant_concurrent = int(tenant_concurrent)
        self.tenant_budget_bytes = tenant_budget_bytes
        self.budget_window_s = float(budget_window_s)
        self.default_timeout_s = default_timeout_s
        self.max_timeout_s = float(max_timeout_s)
        self.max_tenants = int(max_tenants)
        self.brownout_wait_s = brownout_wait_s
        self.brownout_depth = brownout_depth
        self.brownout_window_s = float(brownout_window_s)
        self._registry = registry if registry is not None else _metrics.REGISTRY
        # windowed brownout state: last pqt-serve queue-wait totals + when
        # they were read, and the verdict cached between windows
        self._bw_last: tuple[float, int, float] | None = None  # (t, count, sum)
        self._bw_hot = False
        self._clock = clock
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        self._inflight = 0
        self._draining = False
        self._tenants: dict[str, _TenantState] = {}
        # names ever handed out as accounting/label keys — NEVER shrinks
        # (metrics registry entries can't be evicted), so it must saturate:
        # past max_tenants distinct names, everything new is the overflow
        # bucket for the life of the process
        self._label_names: set[str] = set()

    def resolve_tenant(self, raw) -> str:
        """The bounded accounting key for a client-supplied X-Tenant value:
        sanitized/truncated, and collapsed to the shared overflow bucket
        once max_tenants distinct names have been seen — on ANY endpoint,
        admitted or not, so a /v1/plan or rejection flood cannot grow the
        serve_requests_total{tenant=} label set (or daemon memory) either."""
        tenant = (raw or "default").strip()[:64] or "default"
        with self._lock:
            if tenant in self._label_names:
                return tenant
            if len(self._label_names) < self.max_tenants:
                self._label_names.add(tenant)
                return tenant
            return self.OVERFLOW_TENANT

    # -- deadlines -------------------------------------------------------------

    def deadline_for(self, timeout_ms) -> Deadline:
        """The request's deadline: the caller's timeout_ms (header or body)
        clamped to max_timeout_s, else the configured default."""
        if timeout_ms is None:
            seconds = self.default_timeout_s
        else:
            try:
                seconds = int(timeout_ms) / 1e3
            except (TypeError, ValueError):
                raise ServeError(
                    400, "bad_request",
                    f"X-Timeout-Ms must be an integer, got {timeout_ms!r}",
                ) from None
            if seconds <= 0:
                raise ServeError(
                    400, "bad_request", "X-Timeout-Ms must be positive"
                )
        if seconds is not None:
            seconds = min(seconds, self.max_timeout_s)
        return Deadline(seconds, clock=self._clock)

    # -- admit / release -------------------------------------------------------

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    # -- brownout --------------------------------------------------------------

    def _brownout_hot(self) -> bool:
        """Is the pqt-serve pool under brownout pressure right now?

        Evaluated at most once per brownout_window_s (the verdict is cached
        between windows — admissions must not pay a histogram read each):
        hot when the windowed MEAN queue wait crosses brownout_wait_s, or
        the instantaneous queue depth crosses brownout_depth (the wedged-
        pool case, where no task starts so no new wait is ever observed).
        Called with self._lock held."""
        now = self._clock()
        if self._bw_last is None:
            h = self._registry.hist_stats(
                "pool_queue_wait_seconds", pool="pqt-serve"
            )
            self._bw_last = (now, h["count"], h["sum"])
            return False
        t0, c0, s0 = self._bw_last
        if now - t0 >= self.brownout_window_s:
            h = self._registry.hist_stats(
                "pool_queue_wait_seconds", pool="pqt-serve"
            )
            self._bw_last = (now, h["count"], h["sum"])
            d_count = h["count"] - c0
            d_sum = h["sum"] - s0
            mean = (d_sum / d_count) if d_count else 0.0
            self._bw_hot = (
                self.brownout_wait_s is not None
                and d_count > 0
                and mean > self.brownout_wait_s
            )
        if not self._bw_hot and self.brownout_depth is not None:
            depth = self._registry.get("pool_queue_depth", pool="pqt-serve")
            if depth > self.brownout_depth:
                return True
        return self._bw_hot

    def admit(self, tenant: str) -> Ticket:
        """Claim a slot for `tenant` or raise the typed rejection."""
        try:
            with self._lock:
                if self._draining:
                    raise ServeError(
                        503, "draining",
                        "daemon is draining; retry another replica",
                    )
                if (
                    self.brownout_wait_s is not None
                    or self.brownout_depth is not None
                ) and self._brownout_hot():
                    _metrics.inc("serve_shed_total", reason="queue_wait")
                    raise ServeError(
                        503, "brownout",
                        "daemon is shedding load (scan queue wait over the "
                        "brownout threshold); retry after backoff",
                        retry_after_s=max(1, int(self.brownout_window_s)),
                    )
                if self._inflight >= self.max_inflight:
                    raise ServeError(
                        429, "queue_full",
                        f"daemon at max in-flight requests ({self.max_inflight})",
                        retry_after_s=1,
                    )
                tenant, st = self._tenant_state(tenant)
                if st.concurrent >= self.tenant_concurrent:
                    raise ServeError(
                        429, "tenant_concurrency",
                        f"tenant {tenant!r} at max concurrent requests "
                        f"({self.tenant_concurrent})",
                        retry_after_s=1,
                    )
                st.concurrent += 1
                self._inflight += 1
                _metrics.set_gauge("serve_queue_depth", self._inflight)
        except ServeError as e:
            _rejected(e.code, tenant)  # outside the lock: logging IO must
            raise  # never serialize admissions
        return Ticket(self, tenant)

    def _release(self, tenant: str) -> None:
        with self._lock:
            self._inflight -= 1
            st = self._tenants.get(tenant)
            if st is not None:
                st.concurrent -= 1
            _metrics.set_gauge("serve_queue_depth", self._inflight)
            if self._inflight == 0:
                self._drained.notify_all()

    # -- tenant byte budgets ---------------------------------------------------

    def _tenant_state(self, tenant: str) -> tuple[str, _TenantState]:
        """(key, state) for `tenant`, creating the state under the bounded
        table discipline: evict an idle tenant to make room, else collapse
        to the overflow bucket. Callers must key all later accounting on
        the RETURNED name (Ticket.tenant carries it)."""
        st = self._tenants.get(tenant)
        if st is None:
            if len(self._tenants) >= self.max_tenants:
                victim = next(
                    (
                        k
                        for k, s in self._tenants.items()
                        if s.concurrent == 0 and k != self.OVERFLOW_TENANT
                    ),
                    None,
                )
                if victim is not None:
                    del self._tenants[victim]
                else:
                    tenant = self.OVERFLOW_TENANT
                    st = self._tenants.get(tenant)
                    if st is not None:
                        return tenant, st
            cap = float(self.tenant_budget_bytes or 0)
            st = self._tenants[tenant] = _TenantState(cap, self._clock())
        return tenant, st

    def charge(self, tenant: str, nbytes: int) -> None:
        """Debit `nbytes` (the plan's estimate) from the tenant's bucket.

        Token bucket: capacity tenant_budget_bytes, continuous refill over
        budget_window_s. A request larger than the whole capacity is still
        admitted when the bucket is FULL (one oversized scan per window
        beats never serving it), driving the bucket to empty."""
        if self.tenant_budget_bytes is None:
            return
        cap = float(self.tenant_budget_bytes)
        with self._lock:
            tenant, st = self._tenant_state(tenant)
            now = self._clock()
            st.tokens = min(
                cap,
                st.tokens + (now - st.last_refill) * cap / self.budget_window_s,
            )
            st.last_refill = now
            if nbytes <= st.tokens:
                st.tokens -= nbytes
                return
            if st.tokens >= cap:  # full bucket: let the oversized scan through
                st.tokens = 0.0
                return
            deficit = nbytes - st.tokens
            retry = min(
                self.budget_window_s, deficit * self.budget_window_s / cap
            )
            err = ServeError(
                429, "tenant_over_budget",
                f"tenant {tenant!r} scanned-byte budget exhausted "
                f"(needs {nbytes:,} B, {int(st.tokens):,} B available)",
                retry_after_s=max(1, int(retry)),
            )
        _rejected(err.code, tenant)  # outside the lock (see admit)
        raise err

    # -- drain -----------------------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admitting (the SIGTERM handler's first act). Idempotent;
        in-flight requests are unaffected."""
        with self._lock:
            self._draining = True
            if self._inflight == 0:
                self._drained.notify_all()

    def wait_drained(self, timeout: float | None = None) -> bool:
        """Block until every in-flight request released (True) or the
        timeout passed (False)."""
        with self._lock:
            if not self._draining:
                raise RuntimeError("admission: wait_drained before begin_drain")
            return self._drained.wait_for(
                lambda: self._inflight == 0, timeout=timeout
            )

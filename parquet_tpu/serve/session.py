"""Per-request planning against the daemon's process-shared caches.

A ScanSession is the state every request of one daemon plans against: the
shared FooterCache (a warm repeat plan parses zero footers and performs
ZERO source reads), the shared BlockCache (data/page-index/bloom ranges
survive across requests, so a warm repeat SCAN can serve entirely from
memory), an optional root directory every requested path is confined to,
and an optional daemon-level shard assignment so N daemons split one
logical corpus via the existing `shard=(i, n)` striping.

plan() is pure metadata work: expand paths, build the unit list through
data/plan.build_plan (projection/predicate push-down — statistics and
bloom pruning happen HERE, so excluded row groups never reach the
executor), stripe the units for the effective shard, and estimate the
byte volume the scan will touch (the admission layer charges tenant
budgets with this number before a single data byte is read)."""

from __future__ import annotations

import os
from typing import NamedTuple

from ..core.reader import PARQUET_ERRORS, resolve_column_prefixes
from ..core.schema import Schema
from ..data.plan import ScanPlan, build_plan, expand_paths
from ..io.cache import BlockCache, FooterCache
from ..io.source import SourceError
from ..utils import metrics as _metrics
from ..utils.trace import count as _trace_count
from ..utils.trace import span
from .protocol import ScanRequest, ServeError

__all__ = ["ScanSession", "PlannedScan"]


class PlannedScan(NamedTuple):
    """A request bound to its pruned, sharded unit list."""

    request: ScanRequest
    plan: ScanPlan  # the global (pre-shard) plan, pruning summary attached
    units: list  # this daemon's/request's units, plan order striped by shard
    shard: tuple | None  # the effective (index, count), None = whole corpus
    estimated_bytes: int  # compressed bytes of the selected columns, sharded
    rows_planned: int  # footer-promised rows across the sharded units

    def summary(self) -> dict:
        """The /v1/plan (and `scan --json`) pruning/dry-run report."""
        return {
            "files": len(self.plan.files),
            "units_total": self.plan.units_total,
            "units_pruned_stats": self.plan.units_pruned_stats,
            "units_pruned_bloom": self.plan.units_pruned_bloom,
            "units_admitted": self.plan.num_units,
            "units": len(self.units),
            "rows": self.rows_planned,
            "estimated_bytes": self.estimated_bytes,
            "shard": list(self.shard) if self.shard else None,
        }


def _selected_bytes(meta, group_index: int, columns) -> int:
    """Compressed bytes of the projected chunks of one row group. The
    projection matches the reader's prefix convention ('a' selects every
    leaf under 'a') without needing the parsed schema tree."""
    rg = (meta.row_groups or [])[group_index]
    prefixes = (
        None
        if columns is None
        else [tuple(c.split(".")) for c in columns]
    )
    total = 0
    for cc in rg.columns or []:
        md = cc.meta_data
        if md is None:
            continue
        path = tuple(md.path_in_schema or [])
        if prefixes is not None and not any(
            path[: len(p)] == p for p in prefixes
        ):
            continue
        total += md.total_compressed_size or 0
    return total


class ScanSession:
    """Process-shared planning state for one daemon (thread-safe: the
    caches lock internally, everything else is immutable after init)."""

    def __init__(
        self,
        *,
        root=None,
        footer_cache: FooterCache | None = None,
        block_cache: BlockCache | None = None,
        source_factory=None,
        shard: tuple | None = None,
        coalesce_gap=None,
        remote_map: dict | None = None,
    ):
        self.root = os.path.realpath(os.fspath(root)) if root is not None else None
        # {path prefix -> object-store base URL}: requested paths under a
        # mapped prefix resolve to URLs (longest prefix wins) and flow
        # through the ordinary URL read path — shared TieredCache, footer
        # cache, resilience policy — while everything else stays
        # root-confined exactly as before
        self.remote_map = {
            prefix.strip("/"): url.rstrip("/")
            for prefix, url in (remote_map or {}).items()
        }
        self.footer_cache = footer_cache if footer_cache is not None else FooterCache()
        self.block_cache = block_cache
        # source_factory(path) -> ByteSource: the chaos/remote seam — when
        # set, the EXECUTOR opens data reads through it (planning stays on
        # local footer reads, which the footer cache already absorbs)
        self.source_factory = source_factory
        self.shard = shard
        # what executor readers coalesce with: None (the 64 KiB default),
        # an explicit gap, or "auto" (per-transport profile — the
        # ServeConfig.io_autotune wire)
        self.coalesce_gap = coalesce_gap

    # -- path confinement ------------------------------------------------------

    def _map_remote(self, p: str):
        """Resolve `p` to an object-store URL when it sits under a mapped
        prefix (longest prefix wins), else None. The path is normpath-
        collapsed FIRST, so `remote/../../etc` cannot ride a mapping out
        of its prefix — a collapsed path that no longer starts with the
        prefix simply falls through to local handling (and its 403)."""
        if not self.remote_map or os.path.isabs(p):
            return None
        norm = os.path.normpath(p).replace(os.sep, "/")
        for prefix in sorted(self.remote_map, key=len, reverse=True):
            if norm == prefix or norm.startswith(prefix + "/"):
                rest = norm[len(prefix):].lstrip("/")
                base = self.remote_map[prefix]
                return f"{base}/{rest}" if rest else base
        return None

    def resolve_paths(self, paths: list) -> list:
        """Expand the request's paths/globs into a concrete file list,
        confined to the session root when one is set. Paths under a
        remote_map prefix resolve to object-store URLs instead; relative
        paths are rooted at the session root; anything resolving outside
        it (.. tricks, absolute paths, symlink escapes) is refused with a
        typed 403."""
        specs = []
        for p in paths:
            mapped = self._map_remote(p)
            if mapped is not None:
                # per-request attribution: the trace shows how many paths
                # went remote (their GETs then carry the request's
                # traceparent — the sources read under the request scope)
                _trace_count("remote.mapped")
                specs.append(mapped)
                continue
            if self.root is not None and not os.path.isabs(p):
                p = os.path.join(self.root, p)
            if self.root is not None:
                # refuse escapes BEFORE touching the filesystem: a 404 for
                # root/../../etc/… would leak what exists outside the root
                norm = os.path.normpath(p)
                if not (
                    norm == self.root or norm.startswith(self.root + os.sep)
                ):
                    raise ServeError(
                        403, "path_outside_root",
                        f"path {p!r} resolves outside the serving root",
                    )
            specs.append(p)
        try:
            files: list = []
            for spec in specs:
                files.extend(expand_paths(spec))
        except FileNotFoundError as e:
            raise ServeError(404, "not_found", str(e)) from None
        files = sorted(set(files))
        if self.root is not None:
            for f in files:
                if f.startswith(("http://", "https://")):
                    continue  # mapped object-store URLs are not root paths
                real = os.path.realpath(f)
                if not (real == self.root or real.startswith(self.root + os.sep)):
                    raise ServeError(
                        403, "path_outside_root",
                        f"path {f!r} resolves outside the serving root",
                    )
        return files

    # -- planning --------------------------------------------------------------

    def plan(self, request: ScanRequest) -> PlannedScan:
        """Plan one request: prune, stripe, estimate. Zero source reads
        when the footer cache is warm (and bloom/page-index consultation
        hits the block cache)."""
        with span("serve.plan", {"paths": ",".join(request.paths)}):
            files = self.resolve_paths(request.paths)
            try:
                plan = build_plan(
                    files,
                    filters=request.filters,
                    footer_cache=self.footer_cache,
                    block_cache=self.block_cache,
                )
            except ServeError:
                raise
            except PARQUET_ERRORS as e:
                raise ServeError(
                    422, "unreadable_file", f"{type(e).__name__}: {e}"
                ) from None
            except SourceError as e:
                # a breaker fast-fail during (cold) footer reads: the file
                # is not wrong, the transport is dark — 503 + Retry-After,
                # and the plan failed in microseconds instead of spinning
                # a retry ladder per footer
                code = getattr(e, "code", None)
                if code == "breaker_open":
                    _metrics.inc("serve_shed_total", reason="breaker_open")
                    raise ServeError(
                        503, "source_unavailable",
                        f"source circuit breaker open: {e}",
                        retry_after_s=1,
                    ) from None
                if code == "retry_exhausted":
                    raise ServeError(
                        503, "source_error", f"{type(e).__name__}: {e}",
                        retry_after_s=1,
                    ) from None
                raise ServeError(
                    422, "unreadable_file", f"{type(e).__name__}: {e}"
                ) from None
            except (ValueError, OSError) as e:
                # FilterError (unknown column, bad value coercion) and
                # vanished-file races land here: the request is wrong or
                # stale, the daemon is fine
                raise ServeError(400, "bad_request", str(e)) from None
            # Validate the projection ONCE against the first readable
            # schema (pure metadata — no file handle, so a file vanishing
            # after build_plan can't surface an untyped OSError here): a
            # misspelled column must fail the REQUEST with a 400, not each
            # unit task with a 422.
            if request.columns is not None:
                for meta in plan.metas:
                    if meta is None:
                        continue
                    try:
                        resolve_column_prefixes(
                            Schema.from_thrift(meta.schema), request.columns
                        )
                    except ValueError as e:
                        # ParquetFileError (unknown column) and SchemaError
                        # are both ValueErrors
                        raise ServeError(400, "bad_columns", str(e)) from None
                    break
            shard = request.shard or self.shard
            if shard is not None:
                order = plan.epoch_order(
                    0, shard_index=shard[0], shard_count=shard[1]
                )
                units = [plan.units[k] for k in order]
            else:
                units = list(plan.units)
            est = sum(
                _selected_bytes(
                    plan.metas[u.file_index], u.row_group, request.columns
                )
                for u in units
                if plan.metas[u.file_index] is not None
            )
            return PlannedScan(
                request=request,
                plan=plan,
                units=units,
                shard=shard,
                estimated_bytes=est,
                rows_planned=sum(u.num_rows for u in units),
            )

    # -- the executor's reader seam -------------------------------------------

    def open_source(self, path: str):
        """The byte source the executor reads `path` through: the chaos/
        remote factory when configured, else the path itself (FileReader
        opens a lock-free local pread source)."""
        if self.source_factory is not None:
            return self.source_factory(path)
        return path

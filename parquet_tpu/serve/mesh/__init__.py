"""parquet_tpu.serve.mesh — the multi-host sharded serve layer.

Three layers over the single-daemon stack:

- ring.py    consistent hashing over plan units / shard keys, so repeated
             requests keep landing the same unit on the same replica (its
             footer/block caches stay warm) and adding or removing one
             replica only moves that replica's share of the keyspace.
- table.py   the router's replica table: static `--replica URL` list, one
             circuit breaker + latency window per replica, passive state
             (up/degraded/draining/down/open-breaker) mirrored on the
             mesh_replica_state gauge family and GET /v1/debug/mesh.
- client.py  the resilient mesh client: breaker-gated attempts in ring
             preference order, Retry-After/brownout-aware retry, drain-
             aware failover, hedged duplicates past the observed p95, and
             a traceparent child span injected on EVERY router->replica
             hop (the join key `parquet-tool trace-merge` stitches on).
- router.py  the HTTP front door (`parquet-tool serve --mesh`): the same
             /v1/scan, /v1/query, /v1/plan, /metrics, /healthz surface as
             one daemon, scatter-gathering a request's plan units across
             the fleet and merging /v1/query partials with the exact
             pyarrow merge — responses are byte-identical to a single
             daemon serving the whole corpus (the acceptance oracle the
             differential tests pin).
"""

from .client import MeshClient, MeshError
from .ring import HashRing
from .router import MeshConfig, MeshRouter, MeshService
from .table import Replica, ReplicaTable

__all__ = [
    "HashRing",
    "MeshClient",
    "MeshConfig",
    "MeshError",
    "MeshRouter",
    "MeshService",
    "Replica",
    "ReplicaTable",
]

"""The mesh router: one daemon's HTTP surface, a fleet's execution.

`MeshRouter` binds the exact `/v1/scan`, `/v1/query`, `/v1/plan`,
`/metrics`, `/healthz`, `/v1/debug/*` surface as a single daemon —
existing clients and `parquet-tool` work unchanged — but executes against
a replica fleet:

SCATTER. The stride-slice shard contract is the enabler: a daemon's plan
orders units file-major, and `shard=[k, U]` selects exactly unit k of U.
The router probes ONE replica's /v1/plan for U, then issues one backend
request PER UNIT with `shard=[k, U]`, each routed to the unit's ring
owner, executed on the bounded pqt-mesh pool with an in-order lookahead
window (the executor's _pipelined discipline, one level up). Unit bodies
re-assemble in plan order:

- jsonl: a daemon's body IS the concatenation of per-unit payloads in
  plan order (empty units contribute nothing) — concatenating the
  per-unit responses reproduces it byte-for-byte.
- arrow-ipc: a daemon writes ONE IPC stream, `write_table` per unit; the
  router opens each unit response (itself a complete IPC stream of that
  unit's batches) and re-writes the batches through one writer — the
  same framing, the same bytes.
- /v1/query: each unit's response is the canonical body of a one-unit
  query; the router absorbs them IN UNIT ORDER into the same QueryState
  the daemon merges with — the identical pairwise pyarrow merge
  sequence, so sums of floats agree to the last bit. 413 group_overflow
  fires at the same unit it would on the daemon.

Requests that pin their own `shard` or `limit` (and 0/1-unit plans) pass
through whole to one replica — a limited scan's sequential row cap is
the daemon's own semantics, and re-deriving it would be a second
implementation to keep byte-identical. Either path, responses are
byte-identical to a single daemon serving the whole corpus; the
differential tests pin exactly that.

FAILURE. Backend faults inside a scatter are retried by the mesh client
across replicas (any replica can serve any unit — the corpus is shared);
only fleet exhaustion surfaces, as the typed `partial_failure` ServeError.
Before the stream starts that is a clean JSON error; mid-stream it is the
typed terminal jsonl record + chunked-encoding abort (no 0-chunk) every
client of the single daemon already detects. Never a silently torn or
spliced stream.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutTimeout
from dataclasses import dataclass
from urllib.parse import urlsplit

from ...obs import cost as _cost
from ...obs.pool import instrumented_submit
from ...obs.recorder import ObsConfig as _ObsConfig
from ...obs.recorder import configure as _obs_configure
from ...obs.slo import BurnRateEngine as _BurnRateEngine
from ...obs.slo import SLOObjective as _SLOObjective
from ...utils import metrics as _metrics
from ..admission import AdmissionController
from ..aggregate import QueryState, agg_name, result_dict
from ..protocol import QueryRequest, ScanRequest, ServeError
from ..server import (
    ScanServer,
    ScanService,
    ServeConfig,
    _count_request,
    _Handler,
)
from .client import MeshClient, MeshResponse
from .table import ReplicaTable

__all__ = ["MeshConfig", "MeshService", "MeshRouter"]

# -- the scatter pool ----------------------------------------------------------

_pool: ThreadPoolExecutor | None = None
_pool_lock = threading.Lock()


def mesh_pool() -> ThreadPoolExecutor:
    """The router's fan-out executor ("pqt-mesh", PQT_MESH_THREADS or 16).
    Its own pool: scatter tasks block on backend HTTP, and hedged
    duplicates those tasks launch run on pqt-hedge — two pools, so
    neither can deadlock waiting on work only itself could run."""
    global _pool
    with _pool_lock:
        if _pool is None:
            env = os.environ.get("PQT_MESH_THREADS")
            workers = int(env) if env else 16
            _pool = ThreadPoolExecutor(
                max_workers=max(1, workers), thread_name_prefix="pqt-mesh"
            )
        return _pool


@dataclass
class MeshConfig(ServeConfig):
    """ServeConfig plus the fleet: the router shares the daemon's HTTP/
    admission/obs knobs (host, port, max_inflight, timeouts, SLO...) and
    adds routing. Unused daemon knobs (root, caches, shard) are ignored."""

    replicas: tuple = ()  # backend daemon base URLs, the static fleet
    vnodes: int = 64  # ring points per replica
    scatter: bool = True  # False = pure passthrough routing
    scatter_window: int = 8  # in-flight backend unit requests per request
    backend_timeout_s: float = 30.0  # per-hop transport cap
    probe_timeout_s: float = 2.0  # /healthz probes (debug page only)
    hedge: bool = True  # duplicate a slow first attempt past p95
    hedge_min_s: float = 0.05
    hedge_max_s: float = 2.0
    breaker_failures: int = 3  # consecutive faults to open a replica
    breaker_open_s: float = 2.0

    def __post_init__(self):
        super().__post_init__()
        reps = tuple(dict.fromkeys(str(u).rstrip("/") for u in self.replicas))
        if not reps:
            raise ValueError("mesh: at least one replica URL required")
        self.replicas = reps
        if self.vnodes < 1:
            raise ValueError("mesh: vnodes must be >= 1")
        if self.scatter_window < 1:
            raise ValueError("mesh: scatter_window must be >= 1")
        if self.backend_timeout_s <= 0:
            raise ValueError("mesh: backend_timeout_s must be positive")
        if self.breaker_failures < 1:
            raise ValueError("mesh: breaker_failures must be >= 1")
        if self.breaker_open_s <= 0:
            raise ValueError("mesh: breaker_open_s must be positive")
        if not 0 < self.hedge_min_s <= self.hedge_max_s:
            raise ValueError("mesh: need 0 < hedge_min_s <= hedge_max_s")


# -- request (de)serialization -------------------------------------------------


def _jsonable(v):
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


def _scan_obj(req: ScanRequest) -> dict:
    obj: dict = {"paths": list(req.paths), "format": req.format}
    if req.columns is not None:
        obj["columns"] = list(req.columns)
    if req.filters is not None:
        obj["filters"] = _jsonable(req.filters)
    if req.limit is not None:
        obj["limit"] = req.limit
    if req.shard is not None:
        obj["shard"] = list(req.shard)
    if req.timeout_ms is not None:
        obj["timeout_ms"] = req.timeout_ms
    return obj


def _query_obj(req: QueryRequest) -> dict:
    obj: dict = {
        "paths": list(req.paths),
        "aggregates": [
            [a.op] if a.column is None else [a.op, a.column]
            for a in req.aggregates
        ],
        "max_groups": req.max_groups,
    }
    if req.filters is not None:
        obj["filters"] = _jsonable(req.filters)
    if req.group_by:
        obj["group_by"] = list(req.group_by)
    if req.shard is not None:
        obj["shard"] = list(req.shard)
    if req.timeout_ms is not None:
        obj["timeout_ms"] = req.timeout_ms
    return obj


def _doc_partial(doc: dict, query: QueryRequest):
    """A replica's /v1/query body as a QueryState partial. Types are
    inferred by the merge kernels from the JSON-round-tripped values —
    exact for the int64/float64/string domains JSON round-trips exactly."""
    names = [agg_name(a) for a in query.aggregates]
    if query.group_by:
        groups = {
            tuple(g["key"]): [g["aggregates"].get(n) for n in names]
            for g in doc.get("groups", [])
        }
    else:
        r = doc.get("result") or {}
        groups = {(): [r.get(n) for n in names]}
    types = [None] * len(names)
    return (
        (groups, types),
        int(doc.get("rows_scanned", 0)),
        int(doc.get("rows_matched", 0)),
    )


def _as_serve_error(resp: MeshResponse) -> ServeError:
    """A replica's typed error body, re-raised as this router's error —
    the client sees the replica's code/status, not a generic 502."""
    err = resp.error_body()
    if err and "code" in err:
        return ServeError(
            int(err.get("status") or resp.status),
            str(err["code"]),
            str(err.get("message", "")),
            retry_after_s=_hdr_retry_after(resp),
        )
    return ServeError(
        502, "bad_gateway",
        f"replica {resp.replica.label} answered http {resp.status} "
        "with no typed body",
    )


def _hdr_retry_after(resp: MeshResponse):
    raw = resp.headers.get("Retry-After") if resp.headers else None
    try:
        return float(raw) if raw is not None else None
    except (TypeError, ValueError):
        return None


# -- the service ---------------------------------------------------------------


class MeshService:
    """The router's request brain: same entry-point signatures as
    ScanService (scan/query/plan/healthz/debug_*), so the daemon's
    _Handler drives it unchanged — but execution is fleet-wide."""

    def __init__(self, config: MeshConfig):
        self.config = config
        self.table = ReplicaTable(
            config.replicas,
            failure_threshold=config.breaker_failures,
            open_s=config.breaker_open_s,
        )
        self.client = MeshClient(
            self.table,
            vnodes=config.vnodes,
            timeout_s=config.backend_timeout_s,
            hedge=config.hedge,
            hedge_min_s=config.hedge_min_s,
            hedge_max_s=config.hedge_max_s,
        )
        self.admission = AdmissionController(
            max_inflight=config.max_inflight,
            tenant_concurrent=config.tenant_concurrent,
            tenant_budget_bytes=(
                config.tenant_budget_mb << 20
                if config.tenant_budget_mb is not None
                else None
            ),
            budget_window_s=config.budget_window_s,
            default_timeout_s=config.default_timeout_s,
            max_timeout_s=config.max_timeout_s,
            brownout_wait_s=(
                config.brownout_wait_ms / 1e3
                if config.brownout_wait_ms is not None
                else None
            ),
            brownout_depth=config.brownout_depth,
            brownout_window_s=config.brownout_window_s,
        )
        self.recorder = _obs_configure(
            _ObsConfig(
                ring_size=config.debug_ring_size,
                trace_sample_rate=config.trace_sample_rate,
                slow_ms=config.slow_ms,
                max_traces=config.debug_max_traces,
            )
        )
        self.ledger = _cost.LEDGER
        self.started_at = time.time()
        if config.slo_engine is not None:
            self.slo = config.slo_engine
        else:
            self.slo = _BurnRateEngine(
                _SLOObjective(
                    availability=config.slo_availability,
                    p99_ms=config.slo_p99_ms,
                )
            )

    # the flight-recorder/SLO/profile/fleet debug views only touch
    # self.recorder/self.slo/self.ledger — the daemon's implementations
    # apply verbatim (one copy, no drift)
    debug_requests = ScanService.debug_requests
    debug_request = ScanService.debug_request
    debug_trace = ScanService.debug_trace
    debug_slo = ScanService.debug_slo
    debug_fleet = ScanService.debug_fleet
    debug_tenants = ScanService.debug_tenants
    debug_profile = ScanService.debug_profile

    # -- plumbing --------------------------------------------------------------

    def _headers(self, tenant: str) -> dict:
        return {
            "Content-Type": "application/json",
            "Accept": "*/*",
            "X-Tenant": tenant,
        }

    def _hop_timeout(self, deadline) -> float:
        rem = deadline.remaining()
        if rem is None:
            return self.config.backend_timeout_s
        return max(0.1, min(rem, self.config.backend_timeout_s))

    def _backend(
        self, method, target, *, key, headers, body, deadline
    ) -> MeshResponse:
        resp = self.client.request(
            method, target, key=key, headers=headers, body=body,
            timeout_s=self._hop_timeout(deadline),
        )
        if resp.status != 200:
            raise _as_serve_error(resp)
        return resp

    def _probe_plan(self, obj: dict, sig: str, hdrs, deadline) -> dict:
        body = json.dumps(obj).encode()
        resp = self._backend(
            "POST", "/v1/plan", key=sig, headers=hdrs, body=body,
            deadline=deadline,
        )
        try:
            return json.loads(resp.body)
        except (ValueError, UnicodeDecodeError):
            raise ServeError(
                502, "bad_gateway",
                f"replica {resp.replica.label} answered /v1/plan with "
                "an unparseable body",
            ) from None

    def _scatter_bodies(self, base_obj, target, sig, hdrs, units, deadline):
        """Per-unit backend bodies, IN PLAN ORDER, fetched with a bounded
        lookahead window on the pqt-mesh pool (the _pipelined discipline:
        submission is capped, consumption is strictly ordered, waits are
        deadline-sliced)."""
        _metrics.inc("mesh_scatter_units_total", units, endpoint=target)
        pool = mesh_pool()
        window = self.config.scatter_window

        def fetch(k: int) -> bytes:
            obj = dict(base_obj)
            obj["shard"] = [k, units]
            return self._backend(
                "POST", target, key=f"{sig}#{k}", headers=hdrs,
                body=json.dumps(obj).encode(), deadline=deadline,
            ).body

        def gen():
            pending: deque = deque()
            k = 0
            try:
                while k < units or pending:
                    while k < units and len(pending) < window:
                        pending.append(
                            instrumented_submit(
                                pool, fetch, k, pool="pqt-mesh"
                            )
                        )
                        k += 1
                    fut = pending[0]
                    while True:
                        try:
                            body = fut.result(timeout=0.2)
                            break
                        except _FutTimeout:
                            deadline.check()
                    pending.popleft()
                    yield body
            finally:
                for f in pending:
                    # queued tasks cancel; running stragglers finish on
                    # the pool and their results/errors are absorbed by
                    # the attempt's own breaker/latency bookkeeping
                    f.cancel()

        return gen()

    # -- /v1/scan --------------------------------------------------------------

    def scan(self, request: ScanRequest, tenant, timeout_ms=None, record=None):
        deadline = self.admission.deadline_for(
            timeout_ms if timeout_ms is not None else request.timeout_ms
        )
        ticket = self.admission.admit(tenant)
        try:
            content_type = (
                "application/vnd.apache.arrow.stream"
                if request.format == "arrow-ipc"
                else "application/x-ndjson"
            )
            sig = "|".join(request.paths)
            hdrs = self._headers(tenant)
            if (
                not self.config.scatter
                or request.shard is not None
                or request.limit is not None
            ):
                # the daemon's own sequential row-cap / explicit-stripe
                # semantics: forward whole, byte-identical by construction
                _metrics.inc(
                    "mesh_requests_total", endpoint="/v1/scan",
                    mode="passthrough",
                )
                return ticket, content_type, self._passthrough_scan(
                    request, sig, hdrs, deadline
                )
            summary = self._probe_plan(
                _scan_obj(request._replace(limit=None)), sig, hdrs, deadline
            )
            if record is not None:
                record.plan = summary
            self.admission.charge(
                ticket.tenant, int(summary.get("estimated_bytes") or 0)
            )
            deadline.check()
            units = int(summary.get("units") or 0)
            if units <= 1:
                _metrics.inc(
                    "mesh_requests_total", endpoint="/v1/scan",
                    mode="passthrough",
                )
                return ticket, content_type, self._passthrough_scan(
                    request, sig, hdrs, deadline
                )
            _metrics.inc(
                "mesh_requests_total", endpoint="/v1/scan", mode="scatter"
            )
            base = _scan_obj(request)
            inner = self._scatter_bodies(
                base, "/v1/scan", sig, hdrs, units, deadline
            )
            if request.format == "arrow-ipc":
                return ticket, content_type, _reframe_arrow(inner)
            return ticket, content_type, _concat_jsonl(inner)
        except BaseException:
            ticket.release()
            raise

    def _passthrough_scan(self, request, sig, hdrs, deadline):
        def gen():
            resp = self._backend(
                "POST", "/v1/scan", key=sig, headers=hdrs,
                body=json.dumps(_scan_obj(request)).encode(),
                deadline=deadline,
            )
            if resp.body:
                yield resp.body

        return gen()

    # -- /v1/query -------------------------------------------------------------

    def query(self, request: QueryRequest, tenant, timeout_ms=None, record=None):
        deadline = self.admission.deadline_for(
            timeout_ms if timeout_ms is not None else request.timeout_ms
        )
        ticket = self.admission.admit(tenant)
        try:
            sig = "|".join(request.paths)
            hdrs = self._headers(tenant)
            if not self.config.scatter or request.shard is not None:
                _metrics.inc(
                    "mesh_requests_total", endpoint="/v1/query",
                    mode="passthrough",
                )
                return ticket, self._passthrough_query(
                    request, sig, hdrs, deadline
                )
            probe: dict = {"paths": list(request.paths)}
            if request.filters is not None:
                probe["filters"] = _jsonable(request.filters)
            summary = self._probe_plan(probe, sig, hdrs, deadline)
            if record is not None:
                record.plan = summary
            self.admission.charge(
                ticket.tenant, int(summary.get("estimated_bytes") or 0)
            )
            deadline.check()
            units = int(summary.get("units") or 0)
            if units <= 1:
                _metrics.inc(
                    "mesh_requests_total", endpoint="/v1/query",
                    mode="passthrough",
                )
                return ticket, self._passthrough_query(
                    request, sig, hdrs, deadline
                )
            _metrics.inc(
                "mesh_requests_total", endpoint="/v1/query", mode="scatter"
            )
            base = _query_obj(request)
            state = QueryState(request)
            inner = self._scatter_bodies(
                base, "/v1/query", sig, hdrs, units, deadline
            )
            try:
                for raw in inner:
                    try:
                        doc = json.loads(raw)
                    except (ValueError, UnicodeDecodeError):
                        raise ServeError(
                            502, "bad_gateway",
                            "replica answered /v1/query with an "
                            "unparseable body",
                        ) from None
                    # absorbing per-unit docs IN UNIT ORDER replays the
                    # daemon's exact pairwise merge sequence
                    state.absorb(_doc_partial(doc, request))
            finally:
                inner.close()
            return ticket, result_dict(request, state, units=units)
        except BaseException:
            ticket.release()
            raise

    def _passthrough_query(self, request, sig, hdrs, deadline) -> dict:
        resp = self._backend(
            "POST", "/v1/query", key=sig, headers=hdrs,
            body=json.dumps(_query_obj(request)).encode(),
            deadline=deadline,
        )
        try:
            # the handler re-renders through render_query_body; a JSON
            # round trip is value- and order-preserving, so the bytes
            # out equal the replica's bytes
            return json.loads(resp.body)
        except (ValueError, UnicodeDecodeError):
            raise ServeError(
                502, "bad_gateway",
                f"replica {resp.replica.label} answered /v1/query with "
                "an unparseable body",
            ) from None

    # -- /v1/plan --------------------------------------------------------------

    def plan(self, request: ScanRequest) -> dict:
        _metrics.inc(
            "mesh_requests_total", endpoint="/v1/plan", mode="passthrough"
        )
        deadline = self.admission.deadline_for(request.timeout_ms)
        return self._probe_plan(
            _scan_obj(request), "|".join(request.paths),
            self._headers("router"), deadline,
        )

    # -- health + debug --------------------------------------------------------

    def healthz(self) -> tuple[int, dict]:
        draining = self.admission.draining
        verdict = self.slo.evaluate()["verdict"]
        counts = self.table.counts()
        routable = counts["up"] + counts["degraded"]
        if draining:
            status_str = "draining"
        elif verdict == "burning" or routable == 0:
            status_str = "degraded"
        else:
            status_str = "ok"
        in_flight = self.admission.in_flight
        body = {
            "status": status_str,
            "in_flight": in_flight,
            "slo": verdict,
            "replicas": counts,
        }
        if draining:
            body["retry_after_s"] = min(30, 1 + in_flight)
        return (503 if draining else 200), body

    def debug_mesh(self) -> dict:
        """GET /v1/debug/mesh: live-probed replica states + routing
        config — the operator's one-page answer to "where is my fleet"."""
        return {
            "replicas": self.client.probe(
                timeout_s=self.config.probe_timeout_s
            ),
            "counts": self.table.counts(),
            "ring": {
                "vnodes": self.config.vnodes,
                "nodes": self.table.urls(),
            },
            "scatter": {
                "enabled": self.config.scatter,
                "window": self.config.scatter_window,
            },
            "hedge": {
                "enabled": self.client.hedge,
                "min_s": self.config.hedge_min_s,
                "max_s": self.config.hedge_max_s,
            },
        }

    def debug_vars(self) -> dict:
        from ... import __version__ as _version
        from ...obs.pool import pool_depths

        cfg = self.config
        return {
            "pid": os.getpid(),
            "version": _version,
            "uptime_s": round(time.time() - self.started_at, 3),
            "started_at": self.started_at,
            "mode": "mesh",
            "replicas": self.table.urls(),
            "pools": {"depths": pool_depths()},
            "serve": {
                "max_inflight": cfg.max_inflight,
                "tenant_concurrent": cfg.tenant_concurrent,
                "tenant_budget_mb": cfg.tenant_budget_mb,
                "default_timeout_s": cfg.default_timeout_s,
                "max_timeout_s": cfg.max_timeout_s,
                "max_body_bytes": cfg.max_body_bytes,
                "socket_timeout_s": cfg.socket_timeout_s,
            },
            "mesh": {
                "vnodes": cfg.vnodes,
                "scatter": cfg.scatter,
                "scatter_window": cfg.scatter_window,
                "backend_timeout_s": cfg.backend_timeout_s,
                "hedge": cfg.hedge,
                "breaker_failures": cfg.breaker_failures,
                "breaker_open_s": cfg.breaker_open_s,
            },
            "obs": {
                "trace_sample_rate": cfg.trace_sample_rate,
                "slow_ms": cfg.slow_ms,
                "debug_ring_size": cfg.debug_ring_size,
                "debug_max_traces": cfg.debug_max_traces,
            },
            "slo": {
                "availability": self.slo.objective.availability,
                "p99_ms": self.slo.objective.p99_ms,
            },
            "process": _metrics.process_stats(),
        }


# -- stream re-assembly --------------------------------------------------------


def _concat_jsonl(inner):
    """jsonl re-assembly: unit payload concatenation in plan order (empty
    units are skipped, exactly as the daemon's executor skips them)."""
    try:
        for body in inner:
            if body:
                yield body
    finally:
        inner.close()


def _reframe_arrow(inner):
    """arrow-ipc re-assembly: each unit response is a complete IPC stream
    of that unit's batches; re-write them through ONE writer in unit
    order — the daemon's single-writer framing, byte-for-byte."""
    import pyarrow as pa

    from ..executor import _ChunkSink

    sink = _ChunkSink()
    writer = None
    try:
        for body in inner:
            reader = pa.ipc.open_stream(pa.py_buffer(body))
            if writer is None:
                writer = pa.ipc.new_stream(sink, reader.schema)
            for batch in reader:
                writer.write_batch(batch)
            payload = sink.take()
            if payload:
                yield payload
        if writer is not None:
            writer.close()
            tail = sink.take()
            if tail:
                yield tail
    finally:
        inner.close()


# -- the HTTP layer ------------------------------------------------------------


class _RouterHandler(_Handler):
    """The daemon's handler, plus the router-only debug route. Every
    inherited route (scan/query/plan/healthz/metrics/debug) drives
    MeshService through the ScanService signatures."""

    server_version = "parquet-tpu-mesh"

    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        route = urlsplit(self.path).path
        if route != "/v1/debug/mesh":
            super().do_GET()
            return
        self._body_read = False
        self._rid = self._request_id()
        self._tp = self._trace_context()
        try:
            self._send_json(200, self.service.debug_mesh())
        except ServeError as e:
            self._send_error_body(e)
        except (BrokenPipeError, ConnectionResetError, TimeoutError):
            self.close_connection = True
        except Exception as e:  # noqa: BLE001 - the no-traceback contract
            self._send_internal_error(e)

    def do_POST(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        route = urlsplit(self.path).path
        if route != "/v1/append":
            super().do_POST()
            return
        # Ingest is replica-pinned, not routable: an append must land on
        # exactly the replica that owns the lake table's manifest (one
        # writer per table), and the router has no write-routing table
        # yet. A typed 501 says "the route exists, target a replica"
        # instead of a bare 404's "no such thing".
        self._body_read = False
        self._rid = self._request_id()
        self._tp = self._trace_context()
        tenant = self._tenant()
        e = ServeError(
            501, "not_routable",
            "/v1/append is not routable: ingest targets one replica's "
            "lake table (POST to that replica directly; mesh "
            "write-routing is not implemented)",
        )
        self._send_error_body(e)
        _count_request(tenant, e.status)


class MeshRouter(ScanServer):
    """A ScanServer whose brain is a MeshService: same lifecycle (bind,
    background serve, drain, signal handlers), fleet execution."""

    service_cls = MeshService
    handler_cls = _RouterHandler
    thread_name = "pqt-mesh-http"

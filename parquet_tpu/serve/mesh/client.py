"""The resilient mesh client: one backend HTTP call, fleet semantics.

`MeshClient.request()` is the only way the router talks to a replica. It
composes the PR 10 resilience primitives PER REPLICA instead of per
source:

- attempts walk the ring's deterministic preference order for the
  request's key (owner first), each gated by that replica's circuit
  breaker — an open breaker costs a dict lookup, not a connect timeout;
- a 503 whose body says `draining` marks the replica draining for its
  Retry-After hint and fails over WITHOUT feeding the breaker (a clean
  drain is health, not failure); brownout/throttle 503/429s fail over the
  same way;
- transport faults (reset, truncated chunked stream, stale keep-alive)
  and residual 5xxs feed the breaker and fail over;
- 2xx/4xx are terminal: the replica answered, the router forwards it;
- when every candidate refused with a Retry-After hint and nothing is
  hard-down, ONE bounded sleep honors the smallest hint and the walk
  repeats — a whole-fleet brownout degrades to backoff, not to an error;
- a first attempt that outlives the replica's observed p95 (clamped to
  [hedge_min_s, hedge_max_s]) launches ONE duplicate on the next
  candidate; first answer wins. Hedge attempts run on the shared
  pqt-hedge pool — never the caller's pool, which may be the scatter
  pool, and a bounded pool submitting to itself deadlocks.

Every attempt injects a fresh traceparent CHILD span via
obs/propagate.outbound_traceparent, so each router->replica hop is a
distinct span under the request's trace and `parquet-tool trace-merge`
stitches the full multi-process timeline.

Exhaustion raises MeshError — a ServeError, so the HTTP layer renders the
same typed body discipline as every other failure: `partial_failure`
mid-scatter, `no_replicas` when the table has nothing routable.
"""

from __future__ import annotations

import json
import time
from concurrent.futures import FIRST_COMPLETED, wait

from ...io.hedge import hedge_pool
from ...io.remote import host_pool, pooled_roundtrip
from ...io.source import SourceError
from ...obs.pool import instrumented_submit
from ...obs.propagate import outbound_traceparent
from ...utils import metrics as _metrics
from ..protocol import ServeError
from .ring import HashRing
from .table import ReplicaTable

__all__ = ["MeshClient", "MeshError", "MeshResponse"]


class MeshError(ServeError):
    """A typed fleet-level failure (no replica could answer)."""


class MeshResponse:
    """One backend answer: status/headers/body plus the replica that won."""

    __slots__ = ("status", "headers", "body", "replica")

    def __init__(self, status, headers, body, replica):
        self.status = status
        self.headers = headers
        self.body = body
        self.replica = replica

    def error_body(self) -> dict | None:
        """The parsed typed error body of a non-2xx answer, if any."""
        try:
            obj = json.loads(self.body)
        except (ValueError, UnicodeDecodeError):
            return None
        return obj.get("error") if isinstance(obj, dict) else None


class _Failover(Exception):
    """One attempt failed in a way the next candidate may absorb."""

    def __init__(self, reason: str, detail: str, retry_after_s=None):
        super().__init__(detail)
        self.reason = reason
        self.retry_after_s = retry_after_s


class MeshClient:
    def __init__(
        self,
        table: ReplicaTable,
        *,
        vnodes: int = 64,
        timeout_s: float = 30.0,
        hedge: bool = True,
        hedge_quantile: float = 0.95,
        hedge_min_s: float = 0.05,
        hedge_max_s: float = 2.0,
        retry_backoff_cap_s: float = 0.5,
    ):
        self.table = table
        self.ring = HashRing(table.urls(), vnodes=vnodes)
        self.timeout_s = float(timeout_s)
        self.hedge = bool(hedge) and len(table) > 1
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_min_s = float(hedge_min_s)
        self.hedge_max_s = float(hedge_max_s)
        self.retry_backoff_cap_s = float(retry_backoff_cap_s)

    # -- single attempt --------------------------------------------------------

    def _attempt(
        self, replica, method, target, headers, body, timeout_s
    ) -> MeshResponse:
        """One HTTP round trip to one replica, classified. Returns a
        terminal MeshResponse or raises _Failover."""
        try:
            replica.breaker.before_read()
        except SourceError as e:
            _metrics.inc("mesh_retries_total", reason="breaker_open")
            raise _Failover(
                "breaker_open", f"{replica.label}: {e}", retry_after_s=None
            ) from None
        hdrs = dict(headers)
        tp = outbound_traceparent("mesh")
        if tp is not None:
            hdrs["traceparent"] = tp
        t0 = time.perf_counter()
        try:
            status, _reason, rhdrs, rbody = pooled_roundtrip(
                host_pool(replica.scheme, replica.host, replica.port),
                method,
                target,
                hdrs,
                body=body,
                timeout_s=timeout_s,
                counter="mesh_backend_requests_total",
            )
        except OSError as e:
            # connect refused, reset, truncated chunked body (a TORN
            # replica stream surfaces here as a transport fault — the
            # retry re-fetches the whole answer, never splices a prefix)
            replica.note_failure()
            _metrics.inc("mesh_retries_total", reason="transport")
            raise _Failover(
                "transport", f"{replica.label}: {e}"
            ) from None
        replica.latency.record(time.perf_counter() - t0)
        resp = MeshResponse(status, rhdrs, rbody, replica)
        if status < 500 and status != 429:
            # the replica ANSWERED: 2xx is the result, 4xx is the
            # request's own fault — both terminal, both health
            replica.note_ok()
            return resp
        retry_after = _retry_after_s(rhdrs)
        err = resp.error_body() or {}
        code = err.get("code", f"http_{status}")
        if code == "draining":
            replica.note_draining(retry_after)
            _metrics.inc("mesh_retries_total", reason="draining")
            raise _Failover("draining", f"{replica.label}: draining",
                            retry_after_s=retry_after)
        if status == 429 or code in ("brownout", "queue_full", "throttled"):
            # shedding, not sick: fail over without tripping the breaker
            _metrics.inc("mesh_retries_total", reason="shed")
            raise _Failover("shed", f"{replica.label}: {code}",
                            retry_after_s=retry_after or 1)
        replica.note_failure()
        _metrics.inc("mesh_retries_total", reason="5xx")
        raise _Failover(
            "5xx", f"{replica.label}: {code} (http {status})",
            retry_after_s=retry_after,
        )

    # -- the public call -------------------------------------------------------

    def request(
        self,
        method: str,
        target: str,
        *,
        key: str,
        headers: dict | None = None,
        body: bytes | None = None,
        timeout_s: float | None = None,
    ) -> MeshResponse:
        """One fleet call: preference-ordered failover + optional hedge.
        Returns the first terminal MeshResponse (any 2xx/4xx); raises
        MeshError when the fleet is exhausted."""
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        base = dict(headers or {})
        base.setdefault("Accept", "*/*")
        order = [self.table.by_url[u] for u in self.ring.preference(key)]
        failures: list = []
        for round_no in (0, 1):
            cands = [r for r in order if r.routable()]
            if not cands and round_no == 0 and not failures:
                # nothing routable before we even tried: last resort is
                # every replica whose breaker admits a probe — a stale
                # draining flag must not brick the router
                cands = [
                    r for r in order if r.breaker.state != "open"
                ]
            hedged = self.hedge and len(cands) > 1 and round_no == 0
            for i, rep in enumerate(cands):
                try:
                    if hedged and i == 0:
                        return self._hedged(
                            rep, cands[1], method, target, base, body,
                            timeout_s, failures,
                        )
                    return self._attempt(
                        rep, method, target, base, body, timeout_s
                    )
                except _Failover as f:
                    failures.append(f)
            # one bounded backoff pass: only when someone hinted a retry
            hints = [
                f.retry_after_s for f in failures
                if f.retry_after_s is not None
            ]
            if round_no == 0 and hints:
                time.sleep(min(min(hints), self.retry_backoff_cap_s))
                continue
            break
        if not failures:
            raise MeshError(
                503, "no_replicas",
                "mesh: no routable replica (all draining, down, or "
                "breaker-open)",
                retry_after_s=1,
            )
        _metrics.inc("mesh_partial_failures_total", target=_target_label(target))
        raise MeshError(
            503, "partial_failure",
            "mesh: every replica failed for this request: "
            + "; ".join(str(f) for f in failures[-4:]),
            retry_after_s=1,
        )

    def _hedged(
        self, primary, backup, method, target, headers, body, timeout_s,
        failures,
    ) -> MeshResponse:
        """First attempt with a p95-armed duplicate. The primary's window
        drives the delay; no window yet (cold client) means no hedge."""
        p95 = primary.p95_s()
        if p95 is None:
            return self._attempt(
                primary, method, target, headers, body, timeout_s
            )
        delay = min(max(p95, self.hedge_min_s), self.hedge_max_s)
        pool = hedge_pool()
        futs = {
            instrumented_submit(
                pool, self._attempt, primary, method, target, headers,
                body, timeout_s, pool="pqt-hedge",
            ): "primary"
        }
        done, not_done = wait(futs, timeout=delay, return_when=FIRST_COMPLETED)
        if not done:
            _metrics.inc("mesh_hedges_total", outcome="launched")
            futs[
                instrumented_submit(
                    pool, self._attempt, backup, method, target, headers,
                    body, timeout_s, pool="pqt-hedge",
                )
            ] = "hedge"
        pending = set(futs)
        first_error = None
        while pending:
            done, pending = wait(pending, return_when=FIRST_COMPLETED)
            for fut in done:
                try:
                    resp = fut.result()
                except _Failover as f:
                    if first_error is None:
                        first_error = f
                    else:
                        failures.append(f)
                    continue
                # a loser still running is absorbed by its own attempt
                # bookkeeping (latency + breaker); nothing to cancel
                if len(futs) > 1:
                    _metrics.inc(
                        "mesh_hedges_total",
                        outcome=(
                            "won_hedge"
                            if futs[fut] == "hedge"
                            else "won_primary"
                        ),
                    )
                if first_error is not None:
                    failures.append(first_error)
                return resp
        raise first_error

    # -- active probing (debug page / bench, never the request path) -----------

    def probe(self, timeout_s: float = 2.0) -> list:
        """GET every replica's /healthz and refresh its passive state.
        Returns the /v1/debug/mesh snapshot rows."""
        rows = []
        for rep in self.table.replicas:
            row = rep.snapshot()
            try:
                status, _r, hdrs, body = pooled_roundtrip(
                    host_pool(rep.scheme, rep.host, rep.port),
                    "GET", "/healthz", {"Accept": "application/json"},
                    timeout_s=timeout_s,
                    counter="mesh_backend_requests_total",
                )
            except OSError as e:
                rep.note_down()
                row.update(state="down", healthz=None, error=str(e))
                rows.append(row)
                continue
            try:
                doc = json.loads(body)
            except (ValueError, UnicodeDecodeError):
                doc = {}
            if status == 503 and doc.get("status") == "draining":
                rep.note_draining(
                    doc.get("retry_after_s") or _retry_after_s(hdrs)
                )
            elif status == 200:
                rep.note_ok(degraded=doc.get("status") == "degraded")
            else:
                rep.note_failure()
            row.update(state=rep.state(), healthz=doc or None)
            rows.append(row)
        return rows


def _retry_after_s(headers):
    raw = headers.get("Retry-After") if headers is not None else None
    if raw is None:
        return None
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        return None


def _target_label(target: str) -> str:
    """Bounded metric label: the route constant, never a raw path."""
    for route in ("/v1/scan", "/v1/query", "/v1/plan"):
        if target.startswith(route):
            return route
    return "other"

"""Consistent hashing for the mesh router.

Why a ring and not `hash(key) % n`: the router's whole value is cache
affinity — unit k of a hot corpus should land on the SAME replica request
after request, so that replica's footer/block caches absorb it. Modulo
hashing reshuffles nearly every key when n changes by one; a ring with
virtual nodes moves only the leaving/joining replica's share (~1/n of the
keyspace) and leaves everything else pinned.

Hashing is blake2b over the key bytes — deterministic across processes
and interpreter runs (python's builtin hash() is salted per process, and
a router restart must not cold every replica cache).

`preference(key)` returns ALL nodes in ring order starting at the key's
point: the mesh client's failover order. It is deterministic per key, so
a retry after a replica death lands on the same fallback every time —
which is what makes "byte-identical merged results on retry" testable.
"""

from __future__ import annotations

import bisect
import hashlib

__all__ = ["HashRing"]


def _point(data: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(data.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """An immutable consistent-hash ring over opaque node strings."""

    __slots__ = ("nodes", "_points", "_owners")

    def __init__(self, nodes, *, vnodes: int = 64):
        uniq = list(dict.fromkeys(nodes))
        if not uniq:
            raise ValueError("ring: at least one node required")
        if vnodes < 1:
            raise ValueError("ring: vnodes must be >= 1")
        self.nodes = tuple(uniq)
        marks = []
        for node in self.nodes:
            for v in range(vnodes):
                marks.append((_point(f"{node}#{v}"), node))
        # ties (astronomically unlikely at 64-bit points) break on the
        # node string so the ring is still a deterministic total order
        marks.sort()
        self._points = [m[0] for m in marks]
        self._owners = [m[1] for m in marks]

    def lookup(self, key: str) -> str:
        """The node owning `key`: the first vnode at or past its point,
        wrapping at the top of the ring."""
        i = bisect.bisect_left(self._points, _point(key))
        if i == len(self._points):
            i = 0
        return self._owners[i]

    def preference(self, key: str) -> list:
        """Every node exactly once, in ring order from `key`'s point —
        the deterministic failover sequence (owner first)."""
        start = bisect.bisect_left(self._points, _point(key))
        seen: dict = {}
        n = len(self._points)
        for off in range(n):
            node = self._owners[(start + off) % n]
            if node not in seen:
                seen[node] = None
                if len(seen) == len(self.nodes):
                    break
        return list(seen)

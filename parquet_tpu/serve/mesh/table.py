"""The router's replica table: static membership, passive health state.

One entry per `--replica URL`. Health is learned two ways and both are
cheap: PASSIVELY from every backend response the mesh client sees (a 503
`draining` body marks the replica draining for its Retry-After hint; a
transport fault feeds its circuit breaker), and ACTIVELY only on demand —
`probe()` GETs /healthz when a caller (GET /v1/debug/mesh, the bench)
wants fresh states, never on the request path.

The composite state each replica reports is a small closed vocabulary:

  up           serving, breaker closed
  degraded     serving but its own SLO verdict says "burning" — still
               routable, the client merely deprioritizes it
  draining     it answered 503 {"status": "draining"} — shed to peers
               until its Retry-After hint elapses
  open-breaker its circuit breaker is open (consecutive transport
               failures) — fast-fail window, probes resume via half-open
  down         an active probe could not reach it at all

States map to the mesh_replica_state gauge family (one series per
replica; the label set is bounded by the static --replica list)."""

from __future__ import annotations

import threading
import time
from urllib.parse import urlsplit

from ...io.hedge import CircuitBreaker, _LatencyWindow
from ...utils import metrics as _metrics

__all__ = ["Replica", "ReplicaTable", "STATE_VALUES"]

# gauge encoding: ascending severity so dashboards can max() a fleet
STATE_VALUES = {
    "up": 0,
    "degraded": 1,
    "draining": 2,
    "open-breaker": 3,
    "down": 4,
}


def _default_port(scheme: str) -> int:
    return 443 if scheme == "https" else 80


class Replica:
    """One backend daemon: parsed address + breaker + latency window +
    the passive state the client and /v1/debug/mesh read."""

    def __init__(
        self, url: str, *, failure_threshold: int = 3, open_s: float = 2.0
    ):
        url = url.rstrip("/")
        split = urlsplit(url)
        if split.scheme not in ("http", "https"):
            raise ValueError(
                f"mesh: replica URL must be http(s)://host:port, got {url!r}"
            )
        if not split.hostname:
            raise ValueError(f"mesh: no host in replica URL {url!r}")
        if split.path or split.query:
            raise ValueError(
                f"mesh: replica URL must not carry a path, got {url!r}"
            )
        self.url = url
        self.scheme = split.scheme
        self.host = split.hostname
        self.port = split.port or _default_port(split.scheme)
        self.label = f"{self.host}:{self.port}"
        self.breaker = CircuitBreaker(
            f"mesh:{self.label}",
            failure_threshold=failure_threshold,
            open_s=open_s,
            label=f"mesh:{self.label}",
        )
        self.latency = _LatencyWindow()
        self._lock = threading.Lock()
        self._flag = "up"  # up | degraded | draining | down
        self._flag_until = 0.0  # draining/down expire (the replica may heal)
        self._set_gauge()

    # -- passive state ---------------------------------------------------------

    def note_ok(self, degraded: bool = False) -> None:
        with self._lock:
            self._flag = "degraded" if degraded else "up"
            self._flag_until = 0.0
        self.breaker.record_success()
        self._set_gauge()

    def note_draining(self, retry_after_s=None) -> None:
        """Drain-aware failover: respect the replica's own hint for how
        long to shed (a missing hint backs off briefly and re-probes —
        "draining" usually means "gone in seconds")."""
        hold = float(retry_after_s) if retry_after_s else 1.0
        with self._lock:
            self._flag = "draining"
            self._flag_until = time.monotonic() + min(hold, 30.0)
        self._set_gauge()

    def note_down(self, hold_s: float = 1.0) -> None:
        with self._lock:
            self._flag = "down"
            self._flag_until = time.monotonic() + hold_s
        self._set_gauge()

    def note_failure(self) -> None:
        self.breaker.record_failure()
        self._set_gauge()

    # -- reads -----------------------------------------------------------------

    def state(self) -> str:
        """The composite routing state (breaker wins over stale flags)."""
        if self.breaker.state == "open":
            return "open-breaker"
        with self._lock:
            flag, until = self._flag, self._flag_until
        if flag in ("draining", "down") and time.monotonic() >= until:
            return "up"  # hint expired: eligible again, next attempt decides
        return flag

    def routable(self) -> bool:
        return self.state() in ("up", "degraded")

    def p95_s(self):
        return self.latency.quantile(0.95)

    def _set_gauge(self) -> None:
        _metrics.set_gauge(
            "mesh_replica_state",
            STATE_VALUES[self.state()],
            replica=self.label,
        )

    def snapshot(self) -> dict:
        p95 = self.p95_s()
        return {
            "url": self.url,
            "state": self.state(),
            "breaker": self.breaker.state,
            "p95_ms": round(p95 * 1e3, 3) if p95 is not None else None,
        }


class ReplicaTable:
    """The static fleet: replicas in --replica order, unique by URL."""

    def __init__(
        self, urls, *, failure_threshold: int = 3, open_s: float = 2.0
    ):
        urls = list(dict.fromkeys(u.rstrip("/") for u in urls))
        if not urls:
            raise ValueError("mesh: at least one --replica URL required")
        self.replicas = [
            Replica(u, failure_threshold=failure_threshold, open_s=open_s)
            for u in urls
        ]
        self.by_url = {r.url: r for r in self.replicas}

    def __len__(self) -> int:
        return len(self.replicas)

    def urls(self) -> list:
        return [r.url for r in self.replicas]

    def routable(self) -> list:
        return [r for r in self.replicas if r.routable()]

    def counts(self) -> dict:
        out = {s: 0 for s in STATE_VALUES}
        for r in self.replicas:
            out[r.state()] += 1
        return out

    def snapshot(self) -> list:
        return [r.snapshot() for r in self.replicas]

"""parquet_tpu.serve — the concurrent scan/query service.

The long-running request layer over everything the library already does:
warm-cache planning (io.cache), projection/predicate push-down
(data.plan + core.filter), bounded streaming execution (executor on the
pqt-serve pool), admission control (queue depth, per-tenant budgets,
deadlines, graceful drain), all behind a stdlib HTTP daemon
(`parquet-tool serve`). See each module's docstring.
"""

from .admission import AdmissionController, Deadline  # noqa: F401
from .executor import execute_stream, serve_pool  # noqa: F401
from .protocol import (  # noqa: F401
    ScanRequest,
    ServeError,
    filters_from_spec,
    json_default,
    parse_scan_request,
)
from .server import ScanServer, ScanService, ServeConfig  # noqa: F401
from .session import PlannedScan, ScanSession  # noqa: F401

__all__ = [
    "ServeError",
    "ScanRequest",
    "parse_scan_request",
    "filters_from_spec",
    "json_default",
    "ScanSession",
    "PlannedScan",
    "AdmissionController",
    "Deadline",
    "execute_stream",
    "serve_pool",
    "ServeConfig",
    "ScanService",
    "ScanServer",
]

"""parquet_tpu.serve — the concurrent scan/query service.

The long-running request layer over everything the library already does:
warm-cache planning (io.cache), projection/predicate push-down
(data.plan + core.filter), bounded streaming execution (executor on the
pqt-serve pool), admission control (queue depth, per-tenant budgets,
deadlines, graceful drain), all behind a stdlib HTTP daemon
(`parquet-tool serve`). See each module's docstring.
"""

from .admission import AdmissionController, Deadline  # noqa: F401
from .aggregate import render_query_body, run_local_query  # noqa: F401
from .executor import execute_query, execute_stream, serve_pool  # noqa: F401
from .protocol import (  # noqa: F401
    AggregateSpec,
    QueryRequest,
    ScanRequest,
    ServeError,
    aggregates_from_spec,
    filters_from_spec,
    json_default,
    parse_query_request,
    parse_scan_request,
)
from .server import ScanServer, ScanService, ServeConfig  # noqa: F401
from .session import PlannedScan, ScanSession  # noqa: F401

__all__ = [
    "ServeError",
    "ScanRequest",
    "QueryRequest",
    "AggregateSpec",
    "parse_scan_request",
    "parse_query_request",
    "filters_from_spec",
    "aggregates_from_spec",
    "json_default",
    "render_query_body",
    "run_local_query",
    "ScanSession",
    "PlannedScan",
    "AdmissionController",
    "Deadline",
    "execute_stream",
    "execute_query",
    "serve_pool",
    "ServeConfig",
    "ScanServer",
    "ScanService",
]

"""Typed request parsing/validation for the scan service.

Everything that crosses the HTTP boundary is validated HERE, into plain
typed objects, before any planning or IO happens — a malformed request
costs one JSON parse and produces a structured error body, never a
traceback and never a half-planned scan. The same module owns the
JSON filter-spec parser (`filters_from_spec`) so `parquet-tool scan
--filters` and `POST /v1/scan {"filters": ...}` accept the exact same
language, the aggregate-spec parser (`aggregates_from_spec` /
`parse_query_request`) shared the same way by `POST /v1/query` and
`parquet-tool scan --aggregate`, and the canonical JSON row serialization
(`json_default`) so a daemon response is byte-identical to `parquet-tool
cat` / a direct `FileReader.iter_rows()` dump of the same rows.

ServeError is the one error currency of the serving stack: every layer
(protocol, session, admission, executor) raises it with an HTTP status +
a stable machine-readable `code`, and the server renders `to_body()` —
`{"error": {"code", "message", "status"}}` — whatever stage failed.
"""

from __future__ import annotations

import json
from typing import NamedTuple

__all__ = [
    "ServeError",
    "ScanRequest",
    "QueryRequest",
    "AggregateSpec",
    "FORMATS",
    "AGG_OPS",
    "filters_from_spec",
    "aggregates_from_spec",
    "parse_scan_request",
    "parse_query_request",
    "scan_request_from_query",
    "json_default",
    "agg_name",
]

FORMATS = ("jsonl", "arrow-ipc")

# ops accepted by the filter spec; mirrors core/filter._OPS (validated
# again, against the actual schema, at normalize time — this early check
# exists so a typo'd op fails the REQUEST, not the first file's plan)
_OPS = (
    "==", "!=", "<", "<=", ">", ">=", "is_null", "not_null", "in", "not_in",
    "contains",
)

# aggregate ops accepted by /v1/query and `parquet-tool scan --aggregate`
AGG_OPS = ("count", "sum", "min", "max")

# group-by cardinality is BOUNDED: past max_groups the query fails with a
# typed overflow error instead of buffering an unbounded result (the whole
# point of push-down is that results are kilobytes)
DEFAULT_MAX_GROUPS = 10_000
MAX_MAX_GROUPS = 1_000_000

_SCAN_KEYS = {
    "paths", "columns", "filters", "limit", "format", "shard", "timeout_ms",
}

_QUERY_KEYS = {
    "paths", "filters", "aggregates", "group_by", "max_groups", "shard",
    "timeout_ms",
}


class ServeError(ValueError):
    """A typed, HTTP-renderable service error (subclass of ValueError so
    CLI callers sharing the parsers get ordinary `parquet-tool: <msg>`
    handling). `status` is the HTTP status to send, `code` a stable
    machine-readable discriminator clients can branch on."""

    def __init__(self, status: int, code: str, message: str, *, retry_after_s=None):
        super().__init__(message)
        self.status = int(status)
        self.code = str(code)
        self.message = str(message)
        self.retry_after_s = retry_after_s

    def to_body(self) -> dict:
        return {
            "error": {
                "code": self.code,
                "message": self.message,
                "status": self.status,
            }
        }


class AggregateSpec(NamedTuple):
    """One validated aggregate: op in AGG_OPS; column None only for the
    row-count form of count (count(*))."""

    op: str
    column: str | None


def agg_name(a: AggregateSpec) -> str:
    """The stable result key of one aggregate — shared by the daemon body
    and the CLI output so the rendered bytes match."""
    return a.op if a.column is None else f"{a.op}({a.column})"


class QueryRequest(NamedTuple):
    """One validated aggregation push-down request (POST /v1/query)."""

    paths: list  # file paths and/or glob patterns, server-root relative
    filters: list | None  # normalized triples/DNF, core/filter convention
    aggregates: tuple  # AggregateSpec, >= 1
    group_by: tuple  # column names (empty = one global row)
    max_groups: int  # typed overflow past this many distinct groups
    shard: tuple | None  # (index, count) unit striping for this request
    timeout_ms: int | None  # per-request deadline override


class ScanRequest(NamedTuple):
    """One validated scan (or plan dry-run) request."""

    paths: list  # file paths and/or glob patterns, server-root relative
    columns: list | None  # column projection (dotted paths)
    filters: list | None  # normalized triples/DNF, core/filter convention
    limit: int | None  # max rows streamed back
    format: str  # "jsonl" | "arrow-ipc"
    shard: tuple | None  # (index, count) unit striping for this request
    timeout_ms: int | None  # per-request deadline override


def json_default(v):
    """The canonical JSON fallback shared by parquet-tool cat/head and the
    scan service — one definition, so daemon bytes match CLI bytes."""
    if isinstance(v, bytes):
        return v.decode("utf-8", errors="replace")
    return str(v)


def _bad(message: str) -> ServeError:
    return ServeError(400, "bad_request", message)


def _check_triple(t):
    if not isinstance(t, (list, tuple)) or len(t) not in (2, 3):
        raise ServeError(
            400, "bad_filters",
            f"filter entry must be [column, op] or [column, op, value], got {t!r}",
        )
    if not isinstance(t[0], str) or not t[0]:
        raise ServeError(
            400, "bad_filters", f"filter column must be a string, got {t[0]!r}"
        )
    if t[1] not in _OPS:
        raise ServeError(
            400, "bad_filters",
            f"unknown filter op {t[1]!r} (use one of {', '.join(_OPS)})",
        )
    return tuple(t)


def filters_from_spec(spec):
    """Validate a JSON-decoded filter spec into the (column, op, value)
    triple convention of core/filter.normalize_dnf.

    Accepts the pyarrow shapes: a flat list of triples (one conjunction) or
    a list of LISTS of triples (an OR of conjunctions). The disambiguation
    matches normalize_dnf: an element whose head is a string is a triple.
    Column existence / value coercion is checked later against each file's
    schema; this parser only pins the SHAPE, so a bad spec fails the
    request with a typed 400 before any file is touched."""
    if spec is None:
        return None
    if not isinstance(spec, (list, tuple)):
        raise ServeError(
            400, "bad_filters",
            f"filters must be a list of [column, op, value] triples "
            f"(or a list of such lists), got {type(spec).__name__}",
        )
    if not spec:
        return None
    if all(
        isinstance(c, (list, tuple)) and c and not isinstance(c[0], str)
        for c in spec
    ):
        return [[_check_triple(t) for t in conj] for conj in spec]
    return [_check_triple(t) for t in spec]


def _parse_shard(v):
    if v is None:
        return None
    if isinstance(v, str):
        sep = "/" if "/" in v else ","
        parts = v.split(sep)
    else:
        parts = list(v) if isinstance(v, (list, tuple)) else None
    try:
        i, n = (int(x) for x in parts)
    except (TypeError, ValueError):
        raise ServeError(
            400, "bad_request",
            f"shard must be [index, count] (or 'i/n'), got {v!r}",
        ) from None
    if n <= 0 or not 0 <= i < n:
        raise ServeError(
            400, "bad_request", f"shard index {i} out of range for count {n}"
        )
    return (i, n)


def _build_request(obj: dict) -> ScanRequest:
    unknown = set(obj) - _SCAN_KEYS
    if unknown:
        raise _bad(
            f"unknown request field(s) {sorted(unknown)} "
            f"(accepted: {sorted(_SCAN_KEYS)})"
        )
    paths = obj.get("paths")
    if isinstance(paths, str):
        paths = [paths]
    if (
        not isinstance(paths, list)
        or not paths
        or not all(isinstance(p, str) and p for p in paths)
    ):
        raise _bad("'paths' must be a non-empty string or list of strings")
    columns = obj.get("columns")
    if columns is not None:
        if isinstance(columns, str):
            columns = [c for c in columns.split(",") if c]
        if not isinstance(columns, list) or not all(
            isinstance(c, str) and c for c in columns
        ):
            raise _bad("'columns' must be a list of column names")
        if not columns:
            columns = None
    limit = obj.get("limit")
    if limit is not None:
        if not isinstance(limit, int) or isinstance(limit, bool) or limit < 0:
            raise _bad(f"'limit' must be a non-negative integer, got {limit!r}")
    fmt = obj.get("format", "jsonl")
    if fmt not in FORMATS:
        raise _bad(f"unknown format {fmt!r} (use one of {', '.join(FORMATS)})")
    timeout_ms = obj.get("timeout_ms")
    if timeout_ms is not None:
        if not isinstance(timeout_ms, int) or isinstance(timeout_ms, bool) or timeout_ms <= 0:
            raise _bad(f"'timeout_ms' must be a positive integer, got {timeout_ms!r}")
    return ScanRequest(
        paths=paths,
        columns=columns,
        filters=filters_from_spec(obj.get("filters")),
        limit=limit,
        format=fmt,
        shard=_parse_shard(obj.get("shard")),
        timeout_ms=timeout_ms,
    )


def aggregates_from_spec(spec):
    """Validate a JSON-decoded aggregate spec into AggregateSpec tuples.

    Accepts a list whose entries are "count" (count(*)), [op] / [op,
    column] pairs, or {"op": ..., "column": ...} objects. Column existence
    is checked later against each file's schema — like filters_from_spec,
    this pins the SHAPE so a bad spec fails the request typed before any
    file is touched."""
    if not isinstance(spec, (list, tuple)) or not spec:
        raise ServeError(
            400, "bad_aggregates",
            "'aggregates' must be a non-empty list of [op, column] pairs "
            f"(ops: {', '.join(AGG_OPS)}; count may omit the column)",
        )
    out = []
    for a in spec:
        if isinstance(a, str):
            op, column = a, None
        elif isinstance(a, dict):
            unknown = set(a) - {"op", "column"}
            if unknown:
                raise ServeError(
                    400, "bad_aggregates",
                    f"unknown aggregate field(s) {sorted(unknown)}",
                )
            op, column = a.get("op"), a.get("column")
        elif isinstance(a, (list, tuple)) and 1 <= len(a) <= 2:
            op = a[0]
            column = a[1] if len(a) == 2 else None
        else:
            raise ServeError(
                400, "bad_aggregates", f"bad aggregate entry {a!r}"
            )
        if op not in AGG_OPS:
            raise ServeError(
                400, "bad_aggregates",
                f"unknown aggregate op {op!r} (use one of {', '.join(AGG_OPS)})",
            )
        if column is not None and (not isinstance(column, str) or not column):
            raise ServeError(
                400, "bad_aggregates",
                f"aggregate column must be a non-empty string, got {column!r}",
            )
        if column is None and op != "count":
            raise ServeError(
                400, "bad_aggregates", f"aggregate {op!r} needs a column"
            )
        out.append(AggregateSpec(op=op, column=column))
    return tuple(out)


def _build_query_request(obj: dict) -> QueryRequest:
    unknown = set(obj) - _QUERY_KEYS
    if unknown:
        raise _bad(
            f"unknown request field(s) {sorted(unknown)} "
            f"(accepted: {sorted(_QUERY_KEYS)})"
        )
    paths = obj.get("paths")
    if isinstance(paths, str):
        paths = [paths]
    if (
        not isinstance(paths, list)
        or not paths
        or not all(isinstance(p, str) and p for p in paths)
    ):
        raise _bad("'paths' must be a non-empty string or list of strings")
    group_by = obj.get("group_by") or []
    if isinstance(group_by, str):
        group_by = [c for c in group_by.split(",") if c]
    if not isinstance(group_by, list) or not all(
        isinstance(c, str) and c for c in group_by
    ):
        raise _bad("'group_by' must be a list of column names")
    max_groups = obj.get("max_groups", DEFAULT_MAX_GROUPS)
    if (
        not isinstance(max_groups, int)
        or isinstance(max_groups, bool)
        or not 1 <= max_groups <= MAX_MAX_GROUPS
    ):
        raise _bad(
            f"'max_groups' must be an integer in [1, {MAX_MAX_GROUPS}], "
            f"got {max_groups!r}"
        )
    timeout_ms = obj.get("timeout_ms")
    if timeout_ms is not None:
        if not isinstance(timeout_ms, int) or isinstance(timeout_ms, bool) or timeout_ms <= 0:
            raise _bad(f"'timeout_ms' must be a positive integer, got {timeout_ms!r}")
    return QueryRequest(
        paths=paths,
        filters=filters_from_spec(obj.get("filters")),
        aggregates=aggregates_from_spec(obj.get("aggregates")),
        group_by=tuple(group_by),
        max_groups=max_groups,
        shard=_parse_shard(obj.get("shard")),
        timeout_ms=timeout_ms,
    )


def _parse_body(raw: bytes) -> dict:
    if not raw:
        raise _bad("empty request body (expected a JSON object)")
    try:
        obj = json.loads(raw)
    except (ValueError, UnicodeDecodeError) as e:
        raise _bad(f"request body is not valid JSON: {e}") from None
    if not isinstance(obj, dict):
        raise _bad(f"request body must be a JSON object, got {type(obj).__name__}")
    return obj


def parse_scan_request(raw: bytes) -> ScanRequest:
    """Parse + validate a POST /v1/scan (or /v1/plan) JSON body."""
    return _build_request(_parse_body(raw))


def parse_query_request(raw: bytes) -> QueryRequest:
    """Parse + validate a POST /v1/query JSON body."""
    return _build_query_request(_parse_body(raw))


def scan_request_from_query(qs: dict) -> ScanRequest:
    """Build a request from GET /v1/plan query parameters (urllib parse_qs
    shape: {key: [values]}). `paths` repeats or comma-separates; `columns`
    comma-separates; `filters` is the same JSON spec as the POST body."""
    obj: dict = {}
    paths: list = []
    for v in qs.get("paths", []):
        paths.extend(p for p in v.split(",") if p)
    if paths:
        obj["paths"] = paths
    if "columns" in qs:
        obj["columns"] = ",".join(qs["columns"])
    if "filters" in qs:
        try:
            obj["filters"] = json.loads(qs["filters"][-1])
        except ValueError as e:
            raise ServeError(
                400, "bad_filters", f"'filters' is not valid JSON: {e}"
            ) from None
    for key in ("limit", "timeout_ms"):
        if key in qs:
            try:
                obj[key] = int(qs[key][-1])
            except ValueError:
                raise _bad(f"'{key}' must be an integer") from None
    if "shard" in qs:
        obj["shard"] = qs["shard"][-1]
    if "format" in qs:
        obj["format"] = qs["format"][-1]
    if "paths" not in obj:
        raise _bad("missing 'paths' query parameter")
    return _build_request(obj)
